"""Chaos suite for the fault-tolerance layer (core/faults.py).

Every scenario is deterministic: seeded FaultInjector plans, seeded
RetryPolicy jitter, injected sleeps <= 0.2s. Covers the resilience contract
end to end (docs/faults.md): retry policy + deadline propagation, chaos
injection points, atomic-file helpers, journal crash recovery, circuit-
breaker routing with health-probe re-admission, bounded admission + graceful
drain, GBDT mid-train resume, and the preemption-aware DNN train loop.
"""

import errno
import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from mmlspark_tpu.core import faults
from mmlspark_tpu.core.faults import (
    DEADLINE_HEADER,
    Deadline,
    FaultInjector,
    InjectedFault,
    RetryPolicy,
    atomic_write_text,
    deadline_from_headers,
    rename_with_exdev_fallback,
)

pytestmark = pytest.mark.faults

#: seed matrix knob for the CI chaos lane (tools/ci/run_ci.sh chaos stage):
#: scenarios that draw randomness seed their injectors/policies from this,
#: so `MMLSPARK_CHAOS_SEED=7 pytest -m faults` replays a DIFFERENT but
#: still fully deterministic fault schedule
CHAOS_SEED = int(os.environ.get("MMLSPARK_CHAOS_SEED", "0"))


def _post(url, obj, timeout=15, headers=None):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=json.dumps(obj).encode(),
                                 headers=hdrs, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def _post_status(url, obj, timeout=15, headers=None):
    """Status + parsed body + headers, HTTP errors included."""
    try:
        return _post(url, obj, timeout, headers)
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, (json.loads(body) if body else {}), dict(e.headers)


# ---------------------------------------------------------------------------
# RetryPolicy / Deadline
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def test_jitter_is_deterministic_under_seed(self):
        p = RetryPolicy(max_retries=5, base_s=0.1, jitter=0.3, seed=7)
        assert list(p.backoffs()) == list(p.backoffs())
        q = RetryPolicy(max_retries=5, base_s=0.1, jitter=0.3, seed=8)
        assert list(p.backoffs()) != list(q.backoffs())

    def test_backoff_grows_and_caps(self):
        p = RetryPolicy(max_retries=6, base_s=0.1, multiplier=2.0,
                        max_backoff_s=0.4, jitter=0.0)
        waits = list(p.backoffs())
        assert waits == [0.1, 0.2, 0.4, 0.4, 0.4, 0.4]

    def test_budget_bounds_total_sleep(self):
        p = RetryPolicy(max_retries=50, base_s=1.0, jitter=0.0, budget_s=2.5)
        waits = list(p.backoffs())
        assert sum(waits) <= 2.5 + 1e-9

    def test_deadline_stops_run(self):
        """Each wait is capped at the remaining deadline and the retry loop
        stops once it lapses: a 10s backoff against a 50ms deadline sleeps at
        most ~50ms total, then re-raises."""
        p = RetryPolicy(max_retries=50, base_s=10.0, jitter=0.0)
        dl = Deadline.from_timeout(0.05)
        calls, slept = [], []

        def boom():
            calls.append(1)
            raise ValueError("down")

        with pytest.raises(ValueError):
            p.run(boom, deadline=dl,
                  sleep_fn=lambda s: (slept.append(s), time.sleep(s)))
        assert len(calls) <= 3
        assert all(w <= 0.05 + 1e-6 for w in slept)

    def test_run_retries_then_raises(self):
        calls = []

        def boom():
            calls.append(1)
            raise ValueError("nope")

        p = RetryPolicy(max_retries=3, base_s=0.001, jitter=0.0)
        slept = []
        with pytest.raises(ValueError):
            p.run(boom, sleep_fn=slept.append)
        assert len(calls) == 4 and len(slept) == 3

    def test_run_respects_should_retry(self):
        calls = []

        def boom():
            calls.append(1)
            raise KeyError("fatal")

        p = RetryPolicy(max_retries=5, base_s=0.001)
        with pytest.raises(KeyError):
            p.run(boom, should_retry=lambda e: not isinstance(e, KeyError),
                  sleep_fn=lambda s: None)
        assert len(calls) == 1


class TestDeadline:
    def test_header_round_trip(self):
        dl = Deadline.from_timeout(30)
        back = Deadline.from_header(dl.to_header())
        assert back is not None and abs(back.at - dl.at) < 1e-9

    def test_case_insensitive_lookup(self):
        dl = Deadline.from_timeout(30)
        got = deadline_from_headers({DEADLINE_HEADER.lower(): dl.to_header()})
        assert got is not None and abs(got.at - dl.at) < 1e-9
        assert deadline_from_headers({}) is None
        assert deadline_from_headers(None) is None
        assert deadline_from_headers({DEADLINE_HEADER: "garbage"}) is None

    def test_cap_and_expiry(self):
        dl = Deadline(time.time() - 1)
        assert dl.expired() and dl.remaining() == 0.0 and dl.cap(5.0) == 0.0


# ---------------------------------------------------------------------------
# Retry-After parsing + send_with_retries hardening
# ---------------------------------------------------------------------------


class TestRetryAfter:
    def test_numeric_seconds(self):
        from mmlspark_tpu.io.http import parse_retry_after

        assert parse_retry_after("2.5") == 2.5
        assert parse_retry_after("-3") == 0.0

    def test_http_date(self):
        from email.utils import formatdate

        from mmlspark_tpu.io.http import parse_retry_after

        now = time.time()
        wait = parse_retry_after(formatdate(now + 60, usegmt=True), now=now)
        assert wait is not None and 58 <= wait <= 61
        # a date in the past means "retry now", not a negative sleep
        assert parse_retry_after(formatdate(now - 60, usegmt=True),
                                 now=now) == 0.0

    def test_garbage_is_none(self):
        from mmlspark_tpu.io.http import parse_retry_after

        assert parse_retry_after("soon") is None
        assert parse_retry_after("") is None
        assert parse_retry_after(None) is None


class TestSendWithRetries:
    def _flaky(self, replies):
        """send_request stub yielding canned responses."""
        from mmlspark_tpu.io.http import HTTPResponseData

        it = iter(replies)

        def fake(req, timeout=60.0, deadline=None):
            code, headers = next(it)
            return HTTPResponseData(code, str(code), headers=headers)

        return fake

    def test_retry_after_http_date_honored(self, monkeypatch):
        from email.utils import formatdate

        import mmlspark_tpu.io.http as H

        ra = formatdate(time.time() + 40, usegmt=True)
        monkeypatch.setattr(H, "send_request", self._flaky(
            [(429, {"Retry-After": ra}), (200, None)]))
        slept = []
        resp = H.send_with_retries(H.HTTPRequestData("http://x"),
                                   sleep_fn=slept.append)
        assert resp.statusCode == 200
        assert len(slept) == 1 and 35 <= slept[0] <= 41

    def test_retry_after_capped_at_deadline(self, monkeypatch):
        import mmlspark_tpu.io.http as H

        monkeypatch.setattr(H, "send_request", self._flaky(
            [(429, {"Retry-After": "300"}), (200, None)]))
        slept = []
        resp = H.send_with_retries(
            H.HTTPRequestData("http://x"), sleep_fn=slept.append,
            deadline=Deadline.from_timeout(2.0))
        assert resp.statusCode == 200
        assert slept and slept[0] <= 2.0  # not the server's 300s

    def test_expired_deadline_returns_without_retry(self, monkeypatch):
        import mmlspark_tpu.io.http as H

        monkeypatch.setattr(H, "send_request", self._flaky(
            [(503, None)] * 5))
        slept = []
        resp = H.send_with_retries(
            H.HTTPRequestData("http://x"), sleep_fn=slept.append,
            deadline=Deadline(time.time() - 1))
        assert resp.statusCode == 503 and slept == []

    def test_policy_jitter_deterministic(self, monkeypatch):
        import mmlspark_tpu.io.http as H

        pol = RetryPolicy(max_retries=3, base_s=0.1, jitter=0.5, seed=3)
        runs = []
        for _ in range(2):
            monkeypatch.setattr(H, "send_request", self._flaky(
                [(503, None)] * 3 + [(200, None)]))
            slept = []
            H.send_with_retries(H.HTTPRequestData("http://x"),
                                sleep_fn=slept.append, policy=pol)
            runs.append(slept)
        assert runs[0] == runs[1] and len(runs[0]) == 3

    def test_legacy_backoffs_are_jittered(self, monkeypatch):
        import mmlspark_tpu.io.http as H

        monkeypatch.setattr(H, "send_request", self._flaky(
            [(500, None), (500, None), (500, None), (200, None)]))
        slept = []
        H.send_with_retries(H.HTTPRequestData("http://x"),
                            sleep_fn=slept.append)
        for base, got in zip((0.1, 0.5, 1.0), slept):
            assert abs(got - base) <= base * 0.2 + 1e-9


# ---------------------------------------------------------------------------
# FaultInjector
# ---------------------------------------------------------------------------


class TestFaultInjector:
    def test_fires_on_exact_call_indices(self):
        with FaultInjector(seed=1).plan(faults.HTTP_SEND, at=(2, 4)) as inj:
            fired = []
            for i in range(5):
                try:
                    faults.fire(faults.HTTP_SEND)
                except InjectedFault:
                    fired.append(i + 1)
            assert fired == [2, 4]
        assert faults.active() is None

    def test_probability_stream_replays_under_seed(self):
        def run():
            with FaultInjector(seed=42).plan(faults.TRAIN_STEP, p=0.3,
                                             times=-1) as inj:
                hits = []
                for i in range(50):
                    try:
                        faults.fire(faults.TRAIN_STEP, iteration=i)
                    except InjectedFault:
                        hits.append(i)
                return hits

        a, b = run(), run()
        assert a == b and 5 <= len(a) <= 25

    def test_times_caps_fires_and_log_records(self):
        with FaultInjector().plan(faults.JOURNAL_WRITE, every=1,
                                  times=2) as inj:
            n_raised = 0
            for _ in range(5):
                try:
                    faults.fire(faults.JOURNAL_WRITE, epoch=9)
                except InjectedFault:
                    n_raised += 1
            assert n_raised == 2
            assert [c["epoch"] for _, _, c in inj.fired()] == [9, 9]
            assert inj.calls(faults.JOURNAL_WRITE) == 5

    def test_noop_when_not_installed(self):
        faults.fire(faults.HTTP_SEND)  # must not raise

    def test_delay_without_exception(self):
        with FaultInjector().plan(faults.INGEST_H2D, at=(1,), delay_s=0.05,
                                  exc=None):
            t0 = time.perf_counter()
            faults.fire(faults.INGEST_H2D)
            assert time.perf_counter() - t0 >= 0.045


# ---------------------------------------------------------------------------
# Atomic file helpers
# ---------------------------------------------------------------------------


class TestAtomicFiles:
    def test_atomic_write_replaces_whole_file(self, tmp_path):
        p = str(tmp_path / "f.txt")
        atomic_write_text(p, "one")
        atomic_write_text(p, "two")
        assert open(p).read() == "two"
        assert [f for f in os.listdir(tmp_path) if ".tmp" in f] == []

    def test_exdev_fallback_file(self, tmp_path, monkeypatch):
        src, dst = str(tmp_path / "src.bin"), str(tmp_path / "dst.bin")
        with open(src, "wb") as fh:
            fh.write(b"payload")
        real_rename = os.rename

        def exdev_once(a, b):
            if a == src:
                raise OSError(errno.EXDEV, "cross-device link")
            real_rename(a, b)

        rename_with_exdev_fallback(src, dst, _rename=exdev_once)
        assert open(dst, "rb").read() == b"payload"
        assert not os.path.exists(src)

    def test_exdev_fallback_directory(self, tmp_path):
        src = tmp_path / "srcdir"
        src.mkdir()
        (src / "a.txt").write_text("A")
        dst = str(tmp_path / "dstdir")

        def always_exdev(a, b):
            raise OSError(errno.EXDEV, "cross-device link")

        rename_with_exdev_fallback(str(src), dst, _rename=always_exdev)
        assert open(os.path.join(dst, "a.txt")).read() == "A"
        assert not os.path.exists(src)

    def test_non_exdev_errors_propagate(self, tmp_path):
        def eperm(a, b):
            raise OSError(errno.EPERM, "no")

        with pytest.raises(OSError) as ei:
            rename_with_exdev_fallback(str(tmp_path / "x"),
                                       str(tmp_path / "y"), _rename=eperm)
        assert ei.value.errno == errno.EPERM


# ---------------------------------------------------------------------------
# Journal chaos: crash windows around append/commit/compact
# ---------------------------------------------------------------------------


def _echo_transform(df):
    from mmlspark_tpu.serving.stages import parse_request

    parsed = parse_request(df, "data", parse="json")
    return parsed.with_column(
        "reply", lambda p: [{"sum": float(np.sum(v))} for v in p["data"]])


class TestJournalChaos:
    def test_crash_between_append_and_commit_replays(self, tmp_path):
        """The at-least-once window: entries journaled, commit never lands.
        Recovery must return exactly those requests."""
        from mmlspark_tpu.serving import RequestJournal, ServingServer

        jpath = str(tmp_path / "wal.jsonl")
        with FaultInjector(seed=0).plan(faults.JOURNAL_COMMIT, every=1):
            srv = ServingServer(_echo_transform, port=0, max_wait_ms=2.0,
                                journal_path=jpath)
            srv.start()
            try:
                status, body, _ = _post(srv.address, {"data": [1, 2]})
                assert status == 200 and body["sum"] == 3.0
            finally:
                srv.stop(drain=False)  # hard stop: the crash
        replay = RequestJournal.recover(jpath)
        assert [json.loads(b)["data"] for _, b, _ in replay] == [[1, 2]]

    def test_journal_write_failure_degrades_not_dies(self, tmp_path):
        """An injected append failure must not take serving down."""
        from mmlspark_tpu.serving import ServingServer

        jpath = str(tmp_path / "wal.jsonl")
        with FaultInjector(seed=0).plan(faults.JOURNAL_WRITE, at=(1,)):
            with ServingServer(_echo_transform, port=0, max_wait_ms=2.0,
                               journal_path=jpath) as srv:
                status, body, _ = _post(srv.address, {"data": [4]})
                assert status == 200 and body["sum"] == 4.0
                status, body, _ = _post(srv.address, {"data": [5]})
                assert status == 200 and body["sum"] == 5.0

    def test_commit_retries_after_transient_failure(self, tmp_path):
        """A commit that fails once lands on a later sweep — the epoch must
        not replay after a clean shutdown."""
        from mmlspark_tpu.serving import RequestJournal, ServingServer

        jpath = str(tmp_path / "wal.jsonl")
        with FaultInjector(seed=0).plan(faults.JOURNAL_COMMIT, at=(1,)):
            with ServingServer(_echo_transform, port=0, max_wait_ms=2.0,
                               journal_path=jpath) as srv:
                status, body, _ = _post(srv.address, {"data": [7]})
                assert status == 200
        assert RequestJournal.recover(jpath) == []

    def test_compact_crash_preserves_old_journal(self, tmp_path,
                                                 monkeypatch):
        """Crash mid-compact (fsync of the replacement raises) must leave the
        complete OLD journal, keep uncommitted epochs recoverable, and keep
        the journal writable."""
        from mmlspark_tpu.serving import RequestJournal

        jpath = str(tmp_path / "wal.jsonl")
        j = RequestJournal(jpath)
        j.append(1, 10, b"keep-me", {})
        j.commit(1)
        j.append(2, 11, b"uncommitted", {})
        before = open(jpath).read()

        real_fsync = os.fsync

        def fsync_boom(fd):
            raise OSError(errno.EIO, "injected fsync failure")

        monkeypatch.setattr(os, "fsync", fsync_boom)
        with pytest.raises(OSError):
            j.compact()
        monkeypatch.setattr(os, "fsync", real_fsync)

        assert open(jpath).read() == before  # old file intact, not torn
        assert [r for r, _, _ in RequestJournal.recover(jpath)] == [11]
        j.append(3, 12, b"still-writable", {})  # handle reopened
        j.close()
        assert [r for r, _, _ in RequestJournal.recover(jpath)] == [11, 12]

    def test_compact_keeps_uncommitted_and_drops_committed(self, tmp_path):
        from mmlspark_tpu.serving import RequestJournal

        jpath = str(tmp_path / "wal.jsonl")
        j = RequestJournal(jpath)
        j.append(1, 1, b"done", {})
        j.commit(1)
        j.append(2, 2, b"live", {})
        j.compact()
        j.close()
        assert [r for r, _, _ in RequestJournal.recover(jpath)] == [2]
        assert not os.path.exists(jpath + ".tmp")


# ---------------------------------------------------------------------------
# Routing chaos: circuit breaker, probes, worker kill mid-request
# ---------------------------------------------------------------------------


class _ToggleWorker:
    """Raw HTTP worker whose liveness flips under test control. When dead it
    resets connections (a killed process), when alive it answers JSON."""

    def __init__(self):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _serve(self):
                if not outer.alive:
                    # simulate a killed worker: RST the connection (a dead
                    # process resets; a bare close() leaves keep-alive
                    # clients hanging on a half-open socket, which is a
                    # DIFFERENT failure — the watchdog/hedge tests cover it)
                    import socket as socket_mod
                    import struct

                    try:
                        self.connection.setsockopt(
                            socket_mod.SOL_SOCKET, socket_mod.SO_LINGER,
                            struct.pack("ii", 1, 0))
                    except OSError:
                        pass
                    self.close_connection = True
                    self.connection.close()
                    return
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                body = json.dumps({"worker": "toggle"}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            do_GET = _serve
            do_POST = _serve

        self.alive = True
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.address = f"http://127.0.0.1:{self._httpd.server_address[1]}/"
        self._t = threading.Thread(target=self._httpd.serve_forever,
                                   daemon=True)
        self._t.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


class TestRoutingChaos:
    def _front(self, **kw):
        from mmlspark_tpu.serving import RoutingFront

        kw.setdefault("probe_interval_s", 0.05)
        kw.setdefault("probe_timeout_s", 1.0)
        kw.setdefault("probe_policy", RetryPolicy(
            max_retries=1 << 30, base_s=0.05, multiplier=1.0,
            max_backoff_s=0.05, jitter=0.0, seed=0))
        return RoutingFront(port=0, max_failures=2, **kw)

    def test_no_workers_503_with_retry_after(self):
        with self._front() as front:
            status, body, headers = _post_status(front.address, {"x": 1})
            assert status == 503 and "Retry-After" in headers

    def test_breaker_opens_worker_stays_registered(self):
        dead = "http://127.0.0.1:9/"
        live = _ToggleWorker()
        try:
            with self._front() as front:
                front.register(live.address)
                front.register(dead)
                for _ in range(4):
                    status, body, _ = _post_status(front.address, {"x": 1})
                    assert status == 200 and body["worker"] == "toggle"
                assert front.workers == [live.address]  # dead one excluded
                assert front.worker_states[dead] == "open"  # NOT forgotten
        finally:
            live.stop()

    def test_worker_kill_mid_stream_recovers_via_reroute(self):
        """One worker dies (connection reset); the front re-routes to the
        survivor and every request still answers 200."""
        w1, w2 = _ToggleWorker(), _ToggleWorker()
        try:
            with self._front() as front:
                front.register(w1.address)
                front.register(w2.address)
                w1.alive = False  # kill one mid-traffic
                for i in range(6):
                    status, body, _ = _post_status(front.address, {"i": i})
                    assert status == 200 and body["worker"] == "toggle"
                assert front.worker_states[w1.address] == "open"
        finally:
            w1.stop()
            w2.stop()

    def test_health_probe_readmits_recovered_worker(self):
        w = _ToggleWorker()
        try:
            with self._front() as front:
                front.register(w.address)
                w.alive = False
                for _ in range(3):
                    _post_status(front.address, {"x": 1}, timeout=5)
                assert front.worker_states[w.address] == "open"
                w.alive = True  # worker comes back
                deadline = time.time() + 5
                while (front.worker_states[w.address] == "open"
                       and time.time() < deadline):
                    time.sleep(0.02)
                assert front.worker_states[w.address] in ("half_open",
                                                          "closed")
                status, body, _ = _post_status(front.address, {"x": 2})
                assert status == 200  # traffic flows again
                assert front.worker_states[w.address] == "closed"
        finally:
            w.stop()

    def test_expired_deadline_rejected_pre_forward(self):
        w = _ToggleWorker()
        try:
            with self._front() as front:
                front.register(w.address)
                expired = Deadline(time.time() - 5).to_header()
                status, body, _ = _post_status(
                    front.address, {"x": 1},
                    headers={DEADLINE_HEADER: expired})
                assert status == 504
                live = Deadline.from_timeout(30).to_header()
                status, body, _ = _post_status(
                    front.address, {"x": 1},
                    headers={DEADLINE_HEADER: live})
                assert status == 200
        finally:
            w.stop()

    def test_injected_forward_fault_exercises_retry(self):
        """A planned WORKER_FORWARD fault behaves like a transport failure:
        the front retries the other worker, the request still answers."""
        w1, w2 = _ToggleWorker(), _ToggleWorker()
        try:
            with self._front() as front:
                front.register(w1.address)
                front.register(w2.address)
                with FaultInjector(seed=0).plan(faults.WORKER_FORWARD,
                                                at=(1,)) as inj:
                    status, body, _ = _post_status(front.address, {"x": 1})
                    assert status == 200
                    assert len(inj.fired(faults.WORKER_FORWARD)) == 1
        finally:
            w1.stop()
            w2.stop()


# ---------------------------------------------------------------------------
# Serving hardening: deadline in queue, admission bound, graceful drain
# ---------------------------------------------------------------------------


class TestServingHardening:
    def test_expired_deadline_rejected_at_ingress(self):
        from mmlspark_tpu.serving import ServingServer

        with ServingServer(_echo_transform, port=0, max_wait_ms=2.0) as srv:
            expired = Deadline(time.time() - 5).to_header()
            status, body, _ = _post_status(
                srv.address, {"data": [1]},
                headers={DEADLINE_HEADER: expired})
            assert status == 504

    def test_deadline_expiring_in_queue_gets_504_not_compute(self):
        """A request whose deadline lapses while queued is answered 504 by
        the batcher without reaching the transform."""
        from mmlspark_tpu.serving import ServingServer

        seen = []

        def transform(df):
            seen.extend(int(r) for r in df.collect()["id"])
            return _echo_transform(df)

        gate = threading.Event()

        def gated(df):
            gate.wait(5)
            return transform(df)

        with ServingServer(gated, port=0, max_wait_ms=1.0,
                           max_batch_size=1) as srv:
            # first request occupies the loop inside the gated transform
            t1 = threading.Thread(target=_post_status, args=(
                srv.address, {"data": [1]}))
            t1.start()
            time.sleep(0.1)
            # second request: deadline lapses while it waits in the queue
            res = {}

            def second():
                hdr = {DEADLINE_HEADER: Deadline.from_timeout(0.2).to_header()}
                res["status"], _, _ = _post_status(
                    srv.address, {"data": [2]}, headers=hdr)

            t2 = threading.Thread(target=second)
            t2.start()
            time.sleep(0.4)  # let the deadline lapse before opening the gate
            gate.set()
            t1.join(10)
            t2.join(10)
            assert res["status"] == 504
            assert len(seen) == 1  # the expired request never hit compute

    def test_admission_queue_load_sheds_503(self):
        from mmlspark_tpu.serving import ServingServer

        gate = threading.Event()

        def slow(df):
            gate.wait(5)
            return _echo_transform(df)

        with ServingServer(slow, port=0, max_wait_ms=1.0, max_batch_size=1,
                           max_queue=1) as srv:
            threads = []
            codes = []
            lock = threading.Lock()

            def client(i):
                status, _, headers = _post_status(srv.address, {"data": [i]},
                                                  timeout=10)
                with lock:
                    codes.append((status, headers.get("Retry-After")))

            for i in range(6):
                threads.append(threading.Thread(target=client, args=(i,)))
                threads[-1].start()
                time.sleep(0.05)
            gate.set()
            for t in threads:
                t.join(10)
            shed = [c for c in codes if c[0] == 503]
            assert shed, f"expected load shedding, got {codes}"
            assert all(ra is not None for _, ra in shed)
            assert any(s == 200 for s, _ in codes)

    def test_graceful_drain_answers_inflight_then_rejects(self, tmp_path):
        from mmlspark_tpu.serving import RequestJournal, ServingServer

        jpath = str(tmp_path / "wal.jsonl")
        gate = threading.Event()

        def slow(df):
            gate.wait(5)
            return _echo_transform(df)

        srv = ServingServer(slow, port=0, max_wait_ms=1.0,
                            journal_path=jpath, drain_timeout_s=5.0)
        srv.start()
        res = {}

        def client():
            res["status"], res["body"], _ = _post_status(
                srv.address, {"data": [1, 2, 3]}, timeout=15)

        t = threading.Thread(target=client)
        t.start()
        time.sleep(0.2)  # request is in flight behind the gate

        stopper = threading.Thread(target=srv.stop)  # drain=True default
        stopper.start()
        time.sleep(0.2)
        gate.set()  # in-flight transform completes during the drain
        stopper.join(10)
        t.join(10)
        assert res["status"] == 200 and res["body"]["sum"] == 6.0
        # a clean drain leaves nothing to replay
        assert RequestJournal.recover(jpath) == []


# ---------------------------------------------------------------------------
# Async-front chaos: the PR-2 scenarios rerun under http_mode="async"
# ---------------------------------------------------------------------------


class TestAsyncFrontChaos:
    """Worker-kill / journal-crash / deadline cases over the event-loop
    transports (serving/aio.py) — the threaded-path chaos suite above only
    exercised ThreadingHTTPServer."""

    def _front(self, **kw):
        from mmlspark_tpu.serving import RoutingFront

        kw.setdefault("probe_interval_s", 0.05)
        kw.setdefault("probe_timeout_s", 1.0)
        kw.setdefault("probe_policy", RetryPolicy(
            max_retries=1 << 30, base_s=0.05, multiplier=1.0,
            max_backoff_s=0.05, jitter=0.0, seed=CHAOS_SEED))
        return RoutingFront(port=0, max_failures=2, http_mode="async", **kw)

    def test_worker_kill_mid_stream_reroutes_async(self):
        w1, w2 = _ToggleWorker(), _ToggleWorker()
        try:
            with self._front() as front:
                front.register(w1.address)
                front.register(w2.address)
                w1.alive = False  # kill one mid-traffic
                for i in range(6):
                    status, body, _ = _post_status(front.address, {"i": i})
                    assert status == 200 and body["worker"] == "toggle"
                assert front.worker_states[w1.address] == "open"
        finally:
            w1.stop()
            w2.stop()

    def test_health_probe_readmits_async(self):
        w = _ToggleWorker()
        try:
            with self._front() as front:
                front.register(w.address)
                w.alive = False
                for _ in range(3):
                    _post_status(front.address, {"x": 1}, timeout=5)
                assert front.worker_states[w.address] == "open"
                w.alive = True
                deadline = time.time() + 5
                while (front.worker_states[w.address] == "open"
                       and time.time() < deadline):
                    time.sleep(0.02)
                status, _, _ = _post_status(front.address, {"x": 2})
                assert status == 200
                assert front.worker_states[w.address] == "closed"
        finally:
            w.stop()

    def test_expired_deadline_rejected_async_front_and_worker(self):
        from mmlspark_tpu.serving import ServingServer

        with ServingServer(_echo_transform, port=0, max_wait_ms=2.0,
                           http_mode="async") as srv:
            # dead-on-arrival at the async worker ingress
            expired = Deadline(time.time() - 5).to_header()
            status, _, _ = _post_status(
                srv.address, {"data": [1]},
                headers={DEADLINE_HEADER: expired})
            assert status == 504
            with self._front() as front:
                front.register(srv.address)
                status, _, _ = _post_status(
                    front.address, {"data": [1]},
                    headers={DEADLINE_HEADER: expired})
                assert status == 504  # gated at the async front, pre-forward
                live = Deadline.from_timeout(30).to_header()
                status, body, _ = _post_status(
                    front.address, {"data": [2, 3]},
                    headers={DEADLINE_HEADER: live})
                assert status == 200 and body["sum"] == 5.0

    def test_journal_crash_replays_async_http(self, tmp_path):
        """The PR-2 at-least-once window under the async transport: commit
        never lands, hard stop, recovery returns the uncommitted batch."""
        from mmlspark_tpu.serving import RequestJournal, ServingServer

        jpath = str(tmp_path / "wal.jsonl")
        with FaultInjector(seed=CHAOS_SEED).plan(faults.JOURNAL_COMMIT,
                                                 every=1):
            srv = ServingServer(_echo_transform, port=0, max_wait_ms=2.0,
                                journal_path=jpath, http_mode="async")
            srv.start()
            try:
                status, body, _ = _post(srv.address, {"data": [1, 2]})
                assert status == 200 and body["sum"] == 3.0
            finally:
                srv.stop(drain=False)  # hard stop: the crash
        replay = RequestJournal.recover(jpath)
        assert [json.loads(b)["data"] for _, b, _ in replay] == [[1, 2]]

    def test_journal_write_failure_degrades_async_http(self, tmp_path):
        from mmlspark_tpu.serving import ServingServer

        jpath = str(tmp_path / "wal.jsonl")
        with FaultInjector(seed=CHAOS_SEED).plan(faults.JOURNAL_WRITE,
                                                 at=(1,)):
            with ServingServer(_echo_transform, port=0, max_wait_ms=2.0,
                               journal_path=jpath,
                               http_mode="async") as srv:
                status, body, _ = _post(srv.address, {"data": [4]})
                assert status == 200 and body["sum"] == 4.0


# ---------------------------------------------------------------------------
# Hung-dispatch watchdog + replica supervision (serving/supervisor.py)
# ---------------------------------------------------------------------------


class TestDispatchWatchdog:
    def _server(self, **kw):
        from mmlspark_tpu.serving import ServingServer

        kw.setdefault("max_wait_ms", 1.0)
        kw.setdefault("async_exec", True)
        kw.setdefault("adaptive_batching", False)
        return ServingServer(_echo_transform, port=0, **kw)

    @staticmethod
    def _supervisor(srv):
        return srv._executor.supervisor

    def _wait_for(self, pred, timeout=6.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if pred():
                return True
            time.sleep(0.02)
        return pred()

    def test_wedged_dispatch_requeues_then_quarantine_and_readmit(self):
        """The headline chaos proof: a dispatch wedged by an injected hang
        is re-dispatched on a healthy replica (the request completes), the
        wedged replica is quarantined, and — once its stuck thread returns
        and the probe cooldown passes — re-admitted."""
        with FaultInjector(seed=CHAOS_SEED).plan(
                faults.WORKER_DISPATCH_HANG, at=(1,), delay_s=0.5,
                exc=None) as inj:
            with self._server(replicas=2, inflight=2,
                              watchdog_budget_s=0.05) as srv:
                # tight probe schedule so re-admission is fast in the test
                self._supervisor(srv).quarantine_s = 0.05
                t0 = time.perf_counter()
                status, body, _ = _post(srv.address, {"data": [1, 2]})
                took = time.perf_counter() - t0
                assert status == 200 and body["sum"] == 3.0
                # answered by the re-dispatch, not the 0.5s hang clearing
                assert took < 0.45, f"no re-dispatch: took {took:.3f}s"
                assert len(inj.fired(faults.WORKER_DISPATCH_HANG)) == 1
                ex = srv._executor
                assert ex.watchdog.requeues == 1
                sup = self._supervisor(srv)
                assert any(r["state"] != "healthy" or r["ejections"]
                           for r in sup.describe())
                # the stuck thread returns at ~0.5s; after the cooldown the
                # replica is probed and re-admitted
                assert self._wait_for(
                    lambda: sup.summary()["readmissions"] >= 1)
                assert self._wait_for(
                    lambda: sup.summary()["healthy"] == 2)
                # the recovered fleet still serves
                status, body, _ = _post(srv.address, {"data": [5]})
                assert status == 200 and body["sum"] == 5.0

    def test_hang_under_load_no_request_lost(self):
        """With a mid-load wedge on one replica, every request either
        completes on a healthy replica or sheds with an accounted reason —
        none hang to the slot timeout, none vanish."""
        with FaultInjector(seed=CHAOS_SEED).plan(
                faults.WORKER_DISPATCH_HANG, at=(3,), delay_s=0.5,
                exc=None):
            with self._server(replicas=2, inflight=2, max_batch_size=1,
                              watchdog_budget_s=0.05,
                              slot_timeout_s=15.0) as srv:
                self._supervisor(srv).quarantine_s = 0.05
                results = {}
                lock = threading.Lock()

                def client(i):
                    status, body, _ = _post_status(
                        srv.address, {"data": [i]}, timeout=20)
                    with lock:
                        results[i] = (status, body)

                threads = [threading.Thread(target=client, args=(i,))
                           for i in range(10)]
                for t in threads:
                    t.start()
                    time.sleep(0.02)
                for t in threads:
                    t.join(timeout=30)
                shed = srv.stats.shed_summary()
                assert sorted(results) == list(range(10))  # none lost
                answered = sum(1 for s, _ in results.values() if s == 200)
                accounted = shed["total"]
                assert answered + accounted >= 10
                # correct replies for everything answered 200
                for i, (s, body) in results.items():
                    if s == 200:
                        assert body["sum"] == float(i)
                sup = self._supervisor(srv)
                assert sup.summary()["ejections"] >= 1
                assert self._wait_for(
                    lambda: sup.summary()["healthy"] == 2)

    def test_single_replica_wedge_abandons_with_accounted_504(self):
        """No healthy peer: the watchdog extends the budget a bounded
        number of times, then abandons the batch with an accounted 504 —
        faster than the wedge itself, and attributed in the shed stats."""
        with FaultInjector(seed=CHAOS_SEED).plan(
                faults.WORKER_DISPATCH_HANG, at=(1,), delay_s=1.2,
                exc=None):
            with self._server(replicas=1, inflight=1,
                              watchdog_budget_s=0.05) as srv:
                self._supervisor(srv).quarantine_s = 0.05
                t0 = time.perf_counter()
                status, body, _ = _post_status(srv.address, {"data": [1]},
                                               timeout=20)
                took = time.perf_counter() - t0
                assert status == 504
                assert took < 1.1, f"abandon beat the wedge: {took:.3f}s"
                shed = srv.stats.shed_summary()
                assert shed["by_reason"].get("watchdog_abandoned", 0) >= 1
                assert srv._executor.watchdog.abandons == 1
                # once the hang clears, probe + readmit restore service
                sup = self._supervisor(srv)
                assert self._wait_for(
                    lambda: sup.summary()["healthy"] == 1, timeout=8.0)
                status, body, _ = _post(srv.address, {"data": [7]})
                assert status == 200 and body["sum"] == 7.0

    def test_replica_crash_scores_out_and_batch_gets_500(self):
        """worker.crash: the dispatch raises like a dying replica process —
        the batch fails 500 (current contract) and repeated crashes eject
        the replica via the consecutive-failure score."""
        with FaultInjector(seed=CHAOS_SEED).plan(
                faults.WORKER_CRASH, every=1, times=3) as inj:
            with self._server(replicas=2, inflight=1,
                              max_batch_size=1) as srv:
                codes = []
                for i in range(5):
                    status, _, _ = _post_status(srv.address, {"data": [i]},
                                                timeout=15)
                    codes.append(status)
                assert codes[:3] == [500, 500, 500]
                assert codes[3:] == [200, 200]  # fleet keeps serving
                assert len(inj.fired(faults.WORKER_CRASH)) == 3
                sup = self._supervisor(srv)
                rows = {r["replica"]: r for r in sup.describe()}
                assert sum(r["errors"] for r in rows.values()) == 3

    def test_watchdog_unarmed_until_calibrated(self):
        from mmlspark_tpu.serving.supervisor import DispatchWatchdog

        wd = DispatchWatchdog(k=4.0, min_budget_s=0.5)
        assert wd.budget_s(8) is None  # no estimate yet: never trips
        wd.observe(0.01)
        assert wd.budget_s(8) == 0.5  # floored
        wd.observe(1.0)
        assert wd.budget_s(8) > 0.5
        fixed = DispatchWatchdog(fixed_s=0.25)
        assert fixed.budget_s(1) == 0.25

    def test_watchdog_budget_prefers_cost_model(self):
        from mmlspark_tpu.serving.supervisor import DispatchWatchdog

        wd = DispatchWatchdog(k=2.0, min_budget_s=0.01,
                              predict_ms_fn=lambda rows: 100.0)
        wd.observe(5.0)  # EWMA would give 10s; the model predicts 100ms
        assert wd.budget_s(4) == pytest.approx(0.2)

    def test_supervisor_outlier_and_score_decay(self):
        from mmlspark_tpu.serving.supervisor import ReplicaSupervisor

        sup = ReplicaSupervisor(2, outlier_k=4.0)
        for _ in range(10):
            sup.note_success(0, 0.01)
        sup.note_success(0, 1.0)  # 100x the EWMA: an outlier
        row = sup.describe()[0]
        assert row["outliers"] == 1 and row["state"] == "healthy"
        assert row["score"] < 1.0

    def test_supervisor_consecutive_failures_eject_and_probe_backoff(self):
        from mmlspark_tpu.serving.supervisor import ReplicaSupervisor

        clock = [0.0]
        sup = ReplicaSupervisor(2, max_failures=2, quarantine_s=1.0,
                                clock=lambda: clock[0])
        sup.note_failure(0)
        assert sup.admitted(0)
        sup.note_failure(0)
        assert not sup.admitted(0)
        assert sup.healthy_peers(0) == 1
        assert not sup.probe_due(0)
        clock[0] = 1.5
        assert sup.probe_due(0)
        sup.begin_probe(0)
        sup.note_probe(0, False)  # failed probe: backoff doubles
        clock[0] = 2.5
        assert not sup.probe_due(0)  # needs 2s now
        clock[0] = 3.6
        assert sup.probe_due(0)
        sup.begin_probe(0)
        sup.note_probe(0, True)
        assert sup.admitted(0)
        assert sup.describe()[0]["readmissions"] == 1


# ---------------------------------------------------------------------------
# Hedged requests (RoutingFront + serving/supervisor.py HedgeTracker)
# ---------------------------------------------------------------------------


class _StallWorker:
    """ServingServer wrapper whose transform stalls ``stall_s`` while
    ``stalled`` is set — the deterministic slow replica."""

    def __init__(self, stall_s=0.0):
        from mmlspark_tpu.serving import ServingServer

        self.stalled = stall_s > 0
        self.stall_s = stall_s

        def transform(df):
            if self.stalled:
                time.sleep(self.stall_s)
            return _echo_transform(df)

        self.server = ServingServer(transform, port=0, max_wait_ms=1.0)
        self.server.start()
        self.address = self.server.address

    def stop(self):
        self.server.stop(drain=False)


class TestHedging:
    def _front(self, http_mode="thread", **hedge_kw):
        from mmlspark_tpu.serving import RoutingFront

        hedge_kw.setdefault("init_delay_ms", 40.0)
        hedge_kw.setdefault("min_samples", 1 << 30)  # pin the init delay
        return RoutingFront(port=0, http_mode=http_mode, hedge=hedge_kw)

    def test_hedge_under_stall_first_response_wins(self):
        """A 300ms stall on the primary worker: the hedge fires at ~40ms
        on the healthy peer and the client sees its reply — p99 under the
        injected stall, duplicate work bounded to the stalled requests."""
        fast, slow = _StallWorker(), _StallWorker(stall_s=0.3)
        try:
            with self._front() as front:
                # round-robin alternates; half the primaries stall
                front.register(slow.address)
                front.register(fast.address)
                lat = []
                for i in range(8):
                    t0 = time.perf_counter()
                    status, body, _ = _post_status(front.address,
                                                   {"data": [i]}, timeout=15)
                    lat.append(time.perf_counter() - t0)
                    assert status == 200 and body["sum"] == float(i)
                # every request beat the stall (hedge or fast primary)
                assert max(lat) < 0.28, [round(x, 3) for x in lat]
                s = front._hedge.summary()
                assert s["wins_hedge"] >= 1       # stalled primaries lost
                assert s["wins_primary"] >= 1     # fast primaries won
                assert s["hedged"] <= 5           # only the slow half hedged
        finally:
            fast.stop()
            slow.stop()

    def test_hedge_under_stall_async_front(self):
        fast, slow = _StallWorker(), _StallWorker(stall_s=0.3)
        try:
            with self._front(http_mode="async") as front:
                front.register(slow.address)
                front.register(fast.address)
                lat = []
                for i in range(8):
                    t0 = time.perf_counter()
                    status, body, _ = _post_status(front.address,
                                                   {"data": [i]}, timeout=15)
                    lat.append(time.perf_counter() - t0)
                    assert status == 200 and body["sum"] == float(i)
                assert max(lat) < 0.28, [round(x, 3) for x in lat]
                assert front._hedge.summary()["wins_hedge"] >= 1
        finally:
            fast.stop()
            slow.stop()

    def test_fast_fleet_never_hedges(self):
        """Duplicate-work bound: against healthy sub-delay workers, zero
        hedges launch."""
        a, b = _StallWorker(), _StallWorker()
        try:
            with self._front(init_delay_ms=250.0) as front:
                front.register(a.address)
                front.register(b.address)
                for i in range(10):
                    status, _, _ = _post_status(front.address, {"data": [i]})
                    assert status == 200
                s = front._hedge.summary()
                assert s["hedged"] == 0 and s["requests"] == 10
        finally:
            a.stop()
            b.stop()

    def test_front_hedge_injection_suppresses_deterministically(self):
        """A raising FRONT_HEDGE plan blocks the hedge launch: the stalled
        primary answers after its full stall, and the suppression is
        visible in both the injector log and the tracker."""
        fast, slow = _StallWorker(), _StallWorker(stall_s=0.25)
        try:
            with self._front() as front:
                front.register(slow.address)   # rotation starts here
                front.register(fast.address)
                with FaultInjector(seed=CHAOS_SEED).plan(
                        faults.FRONT_HEDGE, every=1) as inj:
                    t0 = time.perf_counter()
                    status, body, _ = _post_status(front.address,
                                                   {"data": [1]}, timeout=15)
                    took = time.perf_counter() - t0
                    assert status == 200 and body["sum"] == 1.0
                    assert took >= 0.22  # paid the stall: hedge suppressed
                    assert len(inj.fired(faults.FRONT_HEDGE)) == 1
                assert front._hedge.summary()["suppressed"] == 1
        finally:
            fast.stop()
            slow.stop()

    def test_hedge_failed_primary_recovers_via_hedge(self):
        """Primary connection-refused + hedge response: the hedge answer
        wins even when the primary fails outright (not just slowly)."""
        fast = _StallWorker()
        try:
            with self._front(init_delay_ms=20.0) as front:
                front.register("http://127.0.0.1:9/")  # dead primary
                front.register(fast.address)
                status, body, _ = _post_status(front.address, {"data": [2]},
                                               timeout=15)
                assert status == 200 and body["sum"] == 2.0
        finally:
            fast.stop()

    def test_quantile_delay_tracks_observed_latency(self):
        from mmlspark_tpu.serving.supervisor import HedgeConfig, HedgeTracker

        t = HedgeTracker(HedgeConfig(quantile=0.9, min_samples=10,
                                     init_delay_ms=77.0, min_delay_ms=1.0))
        assert t.delay_s() == pytest.approx(0.077)  # under min_samples
        for ms in range(1, 101):  # 1..100ms uniform
            t.observe(ms / 1e3)
        assert t.delay_s() == pytest.approx(0.091, rel=0.02)  # ~p90

    def test_hedge_config_validation(self):
        from mmlspark_tpu.serving.supervisor import HedgeConfig, make_hedge

        with pytest.raises(ValueError):
            HedgeConfig(quantile=1.5)
        with pytest.raises(ValueError):
            HedgeConfig(min_delay_ms=10.0, max_delay_ms=1.0)
        assert make_hedge(None) is None
        assert make_hedge(False) is None
        assert make_hedge(True) is not None
        with pytest.raises(ValueError):
            make_hedge(42)


# ---------------------------------------------------------------------------
# AsyncConnectionPool: stale-socket retry honors the request deadline
# ---------------------------------------------------------------------------


class TestPoolDeadlineGate:
    class _DeadWriter:
        def write(self, b):
            pass

        async def drain(self):
            pass

        def close(self):
            pass

        def is_closing(self):
            return False

    class _ClosedReader:
        async def readline(self):
            return b""  # peer closed before the status line

    def _pool_with_stale_checkout(self):
        import asyncio  # noqa: F401 — exercised via asyncio.run below

        from mmlspark_tpu.serving.aio import AsyncConnectionPool

        pool = AsyncConnectionPool()
        calls = []

        async def checkout(key, force_fresh):
            calls.append(force_fresh)
            return (False, (self._ClosedReader(), self._DeadWriter()))

        pool._checkout = checkout
        return pool, calls

    def test_expired_deadline_blocks_stale_retry(self):
        import asyncio

        pool, calls = self._pool_with_stale_checkout()
        dl = Deadline(time.time() - 1)
        with pytest.raises(OSError, match="deadline expired"):
            asyncio.run(pool._request(("h", 80), "POST", "/", b"", None,
                                      deadline=dl))
        # the single retry NEVER fired: one checkout, no fresh connection
        assert calls == [False]

    def test_live_deadline_allows_stale_retry(self):
        import asyncio

        pool, calls = self._pool_with_stale_checkout()
        dl = Deadline.from_timeout(30)
        with pytest.raises(OSError):
            asyncio.run(pool._request(("h", 80), "POST", "/", b"", None,
                                      deadline=dl))
        assert calls == [False, True]  # retried once on a fresh connection

    def test_no_deadline_keeps_legacy_single_retry(self):
        import asyncio

        pool, calls = self._pool_with_stale_checkout()
        with pytest.raises(OSError):
            asyncio.run(pool._request(("h", 80), "POST", "/", b"", None))
        assert calls == [False, True]


# ---------------------------------------------------------------------------
# ReplicaSet placement: a raising device skips, not fails
# ---------------------------------------------------------------------------


class TestReplicaPlacementSkip:
    def test_failing_device_is_skipped_with_survivors(self):
        from mmlspark_tpu.serving import ReplicaSet

        def factory(i, dev):
            if dev == "bad-dev":
                raise RuntimeError(f"device {dev} driver init failed")
            return lambda df: df

        rs = ReplicaSet(transform_factory=factory, n=3,
                        devices=["dev0", "bad-dev", "dev2"])
        assert [r.index for r in rs.replicas] == [0, 2]
        assert [r.device for r in rs.replicas] == ["dev0", "dev2"]
        assert len(rs.placement_failures) == 1
        f = rs.placement_failures[0]
        assert f["replica"] == 1 and f["device"] == "bad-dev"
        assert "driver init failed" in f["error"]

    def test_zero_survivors_raises(self):
        from mmlspark_tpu.serving import ReplicaSet

        def factory(i, dev):
            raise RuntimeError("no devices at all")

        with pytest.raises(RuntimeError, match="every replica placement"):
            ReplicaSet(transform_factory=factory, n=2,
                       devices=["d0", "d1"])

    def test_degraded_placement_surfaces_in_executor_stats(self):
        """A degraded ReplicaSet rides into the executor's stats payload
        (placement_failures) and the survivors still dispatch."""
        from mmlspark_tpu.core.dataframe import DataFrame
        from mmlspark_tpu.serving import ServingServer
        from mmlspark_tpu.serving.executor import PipelinedExecutor, ReplicaSet

        def factory(i, dev):
            if i == 0:
                raise RuntimeError("chip 0 wedged at init")
            return _echo_transform

        rs = ReplicaSet(transform_factory=factory, n=2, devices=[None, None])
        assert rs.placement_failures and len(rs.replicas) == 1
        srv = ServingServer(_echo_transform, port=0)  # not started: scaffold
        ex = PipelinedExecutor(srv, rs)
        stats = ex.stats()
        assert stats["placement_failures"][0]["replica"] == 0
        # the surviving replica still runs transforms
        out = rs.run(rs.replicas[0], DataFrame.from_dict(
            {"id": np.array([1], dtype=np.int64),
             "value": np.array([b'{"data": [1, 2]}'], dtype=object),
             "headers": np.array([{}], dtype=object),
             "origin": np.array([""], dtype=object)}))
        assert out.collect()["reply"][0]["sum"] == 3.0


# ---------------------------------------------------------------------------
# Brownout controller (serving/supervisor.py)
# ---------------------------------------------------------------------------


class _FakeSLO:
    def __init__(self):
        self.burn = 0.0

    def burn_rates(self):
        return {60: self.burn}


class TestBrownout:
    def _controller(self, slo, log, clock, **kw):
        from mmlspark_tpu.serving.supervisor import (BrownoutController,
                                                     BrownoutStep)

        steps = [BrownoutStep(f"s{i}",
                              lambda i=i: log.append(("apply", i)),
                              lambda i=i: log.append(("revert", i)))
                 for i in range(2)]
        kw.setdefault("enter_burn", 2.0)
        kw.setdefault("exit_burn", 0.5)
        kw.setdefault("hold_s", 1.0)
        kw.setdefault("check_interval_s", 0.0)
        return BrownoutController(slo, steps, clock=lambda: clock[0], **kw)

    def test_degrades_stepwise_and_restores_with_hysteresis(self):
        slo, log, clock = _FakeSLO(), [], [10.0]
        c = self._controller(slo, log, clock)
        slo.burn = 5.0
        assert c.check() == "degrade" and c.step == 1
        clock[0] += 0.5
        assert c.check() is None  # hold_s not elapsed: one step at a time
        clock[0] += 0.6
        assert c.check() == "degrade" and c.step == 2
        clock[0] += 2.0
        assert c.check() is None  # ladder exhausted, burn still high
        # burn drops: restore needs 2*hold_s BELOW exit continuously
        slo.burn = 0.1
        assert c.check() is None          # starts the below-window
        clock[0] += 1.0
        assert c.check() is None          # 1.0 < 2*hold_s
        clock[0] += 1.1
        assert c.check() == "restore" and c.step == 1
        # mid-band burn (between exit and enter): hold steady
        slo.burn = 1.0
        clock[0] += 5.0
        assert c.check() is None and c.step == 1
        assert log == [("apply", 0), ("apply", 1), ("revert", 1)]
        tr = c.summary()["transitions"]
        assert tr == {"degrade": 2, "restore": 1, "rollback": 0}

    def test_journal_and_one_step_rollback(self):
        slo, log, clock = _FakeSLO(), [], [10.0]
        c = self._controller(slo, log, clock)
        slo.burn = 9.0
        c.check()
        assert [e["action"] for e in c.summary()["journal"]] == ["degrade"]
        assert c.rollback() is True and c.step == 0
        assert log == [("apply", 0), ("revert", 0)]
        assert c.rollback() is False  # nothing left to roll back
        actions = [e["action"] for e in c.summary()["journal"]]
        assert actions == ["degrade", "rollback"]

    def test_a_failing_step_never_kills_the_tick(self):
        from mmlspark_tpu.serving.supervisor import (BrownoutController,
                                                     BrownoutStep)

        slo, clock = _FakeSLO(), [10.0]

        def boom():
            raise RuntimeError("knob exploded")

        c = BrownoutController(slo, [BrownoutStep("bad", boom, boom)],
                               enter_burn=2.0, exit_burn=0.5, hold_s=0.0,
                               check_interval_s=0.0,
                               clock=lambda: clock[0])
        slo.burn = 9.0
        assert c.check() == "degrade"  # transition recorded, error eaten
        assert c.step == 1

    def test_requires_slo_and_hysteresis_band(self):
        from mmlspark_tpu.serving.supervisor import BrownoutController

        with pytest.raises(ValueError, match="requires an SLO"):
            BrownoutController(None, [])
        with pytest.raises(ValueError, match="hysteresis"):
            BrownoutController(_FakeSLO(), [], enter_burn=1.0,
                               exit_burn=1.0)

    def test_server_brownout_engages_under_breach_and_surfaces(self):
        """Integration: a server whose every request breaches a 1ms
        objective degrades within a few batches — the batch window
        collapses and /_mmlspark/stats + metrics expose the step."""
        import urllib.request

        from mmlspark_tpu.serving import ServingServer

        def slowish(df):
            time.sleep(0.02)
            return _echo_transform(df)

        with ServingServer(slowish, port=0, max_wait_ms=5.0,
                           slo={"objective_ms": 1.0, "target": 0.99},
                           brownout={"enter_burn": 1.5, "exit_burn": 0.2,
                                     "hold_s": 0.0,
                                     "check_interval_s": 0.0}) as srv:
            for i in range(6):
                status, _, _ = _post(srv.address, {"data": [i]})
                assert status == 200
            with urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}/_mmlspark/stats",
                    timeout=10) as resp:
                stats = json.loads(resp.read())
            bo = stats["brownout"]
            assert bo["active"] and bo["step"] >= 1
            assert srv.max_wait_ms == 0.0  # step 1: window collapsed
            assert bo["journal"][0]["action"] == "degrade"
            with urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}/_mmlspark/metrics",
                    timeout=10) as resp:
                text = resp.read().decode()
            assert "mmlspark_brownout_step" in text
            assert 'mmlspark_brownout_transitions_total{direction="degrade"}' \
                in text

    def test_brownout_off_by_default_and_tenant_pressure(self):
        from mmlspark_tpu.serving import ServingServer, TenantAdmission

        with ServingServer(_echo_transform, port=0) as srv:
            assert srv._brownout is None
        t = TenantAdmission({"a": 1.0, "b": 1.0})
        base = t.quota("a", 100)
        prev = t.set_pressure(0.5)
        assert prev == 1.0
        assert t.quota("a", 100) == base // 2
        t.set_pressure(prev)
        assert t.quota("a", 100) == base


# ---------------------------------------------------------------------------
# Ingest H2D chaos
# ---------------------------------------------------------------------------


class TestIngestChaos:
    def test_injected_h2d_delay_shows_in_timings(self):
        from mmlspark_tpu.parallel.ingest import TransferRing

        batches = [np.ones((4, 4), dtype=np.float32)] * 3
        with FaultInjector().plan(faults.INGEST_H2D, at=(2,), delay_s=0.1,
                                  exc=None):
            ring = TransferRing(iter(batches), depth=1)
            out = list(ring)
        assert len(out) == 3
        h2d = [t.h2d_s for t in ring.stats.records]
        assert h2d[1] >= 0.09  # the injected slow link is visible
        assert h2d[0] < 0.09

    def test_injected_h2d_failure_surfaces_to_consumer(self):
        from mmlspark_tpu.parallel.ingest import TransferRing

        batches = [np.ones((2, 2), dtype=np.float32)] * 4
        with FaultInjector().plan(faults.INGEST_H2D, at=(2,)):
            ring = TransferRing(iter(batches), depth=1)
            with pytest.raises(InjectedFault):
                list(ring)

    def test_h2d_fault_on_deposit_path_never_corrupts_a_slot(self):
        """INGEST_H2D hitting a slot-staged (deposit) batch: the transform
        fails fast, the lease returns to the pool (no leak, no deadlock),
        and a retry produces bitwise-correct output — the slot content was
        never read half-transferred."""
        import jax

        from mmlspark_tpu.core.dataframe import DataFrame
        from mmlspark_tpu.core.fusion import CompileCache, FusedPipelineModel
        from mmlspark_tpu.core.pipeline import PipelineModel
        from mmlspark_tpu.core.schema import ImageSchema
        from mmlspark_tpu.image.featurizer import ImageFeaturizer
        from mmlspark_tpu.image.stages import ImageTransformer
        from mmlspark_tpu.models.module import (Dense, FunctionModel,
                                                GlobalAvgPool, Sequential)

        size = 12
        mod = Sequential([("pool", GlobalAvgPool()), ("head", Dense(3))],
                         name="tinycnn")
        params, _ = mod.init(jax.random.PRNGKey(0), (size, size, 3))
        backbone = FunctionModel(mod, params, (size, size, 3),
                                 layer_names=["head", "pool"],
                                 name="tinycnn")
        pm = PipelineModel([
            ImageTransformer().resize(size, size).flip(1),
            ImageFeaturizer(scaleFactor=1 / 255., batchSize=8)
            .set_model(backbone)])

        rng = np.random.default_rng(int(CHAOS_SEED))
        obj = np.empty(20, dtype=object)
        for i in range(20):
            obj[i] = ImageSchema.make(
                rng.integers(0, 256, (16, 16, 3), dtype=np.uint8),
                f"img{i}")
        df = DataFrame.from_dict({"image": obj}, num_partitions=1)

        def feats(model, frame):
            pdf = model.transform(frame).to_pandas()
            col = next(c for c in pdf.columns if c != "image")
            return np.stack([np.asarray(v) for v in pdf[col].to_list()])

        ref = feats(FusedPipelineModel(pm.stages, cache=CompileCache(),
                                       slot_staging=False), df)
        dep = FusedPipelineModel(pm.stages, cache=CompileCache())
        with FaultInjector().plan(faults.INGEST_H2D, at=(2,)):
            with pytest.raises(InjectedFault):
                dep.transform(df)
        # lease released on the failure path: the pool still hands out
        # every buffer (a leak would starve or deadlock this retry)
        got = feats(dep, df)
        np.testing.assert_array_equal(got, ref)
        s = dep.last_ingest_stats.summary()
        assert s.get("slot_deposits", 0) > 0
        # slow-link variant: an injected DELAY on the deposit path keeps
        # output correctness (the slot is not recycled mid-transfer)
        with FaultInjector().plan(faults.INGEST_H2D, at=(1,),
                                  delay_s=0.05, exc=None):
            np.testing.assert_array_equal(feats(dep, df), ref)


# ---------------------------------------------------------------------------
# GBDT checkpoint/resume
# ---------------------------------------------------------------------------


def _synth_binary(n=300, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    logit = X[:, 0] * 2 - X[:, 1] + 0.5 * X[:, 2] * X[:, 0]
    y = (logit + rng.normal(scale=0.3, size=n) > 0).astype(np.float64)
    return X, y


class TestGBDTCheckpointResume:
    def _params(self, **kw):
        from mmlspark_tpu.gbdt import TrainParams

        base = dict(objective="binary", num_iterations=8, num_leaves=7,
                    min_data_in_leaf=5, bagging_fraction=0.8,
                    bagging_freq=1, seed=3)
        base.update(kw)
        return TrainParams(**base)

    def test_interrupted_resume_is_identical(self, tmp_path):
        """Train interrupted at iteration k (injected preemption) then
        resumed must produce the SAME model as an uninterrupted run."""
        from mmlspark_tpu.gbdt import booster as B
        from mmlspark_tpu.gbdt.checkpoint import CheckpointConfig

        X, y = _synth_binary()
        p = self._params()
        full = B.train(p, X, y, checkpoint=CheckpointConfig(
            str(tmp_path / "full.ckpt"), every_k=3))

        ckpt = str(tmp_path / "interrupted.ckpt")
        with FaultInjector(seed=0).plan(faults.TRAIN_STEP, at=(6,)):
            with pytest.raises(InjectedFault):
                B.train(p, X, y,
                        checkpoint=CheckpointConfig(ckpt, every_k=3))
        # the pre-preemption checkpoint is on disk at iteration 3
        from mmlspark_tpu.gbdt.checkpoint import load_checkpoint

        assert load_checkpoint(ckpt)["iteration"] == 3
        resumed = B.train(p, X, y,
                          checkpoint=CheckpointConfig(ckpt, every_k=3))
        assert resumed.to_string() == full.to_string()
        np.testing.assert_array_equal(resumed.raw_predict(X),
                                      full.raw_predict(X))

    def test_checkpoint_cadence_and_final(self, tmp_path):
        from mmlspark_tpu.gbdt import booster as B
        from mmlspark_tpu.gbdt.checkpoint import (CheckpointConfig,
                                                  load_checkpoint)

        X, y = _synth_binary()
        ckpt = str(tmp_path / "m.ckpt")
        B.train(self._params(), X, y,
                checkpoint=CheckpointConfig(ckpt, every_k=3))
        ck = load_checkpoint(ckpt)
        assert ck["iteration"] == 8  # final checkpoint written at the end

    def test_param_mismatch_refuses_resume(self, tmp_path):
        from mmlspark_tpu.gbdt import booster as B
        from mmlspark_tpu.gbdt.checkpoint import CheckpointConfig

        X, y = _synth_binary()
        ckpt = str(tmp_path / "m.ckpt")
        B.train(self._params(), X, y,
                checkpoint=CheckpointConfig(ckpt, every_k=3))
        with pytest.raises(ValueError, match="different train params"):
            B.train(self._params(learning_rate=0.27), X, y,
                    checkpoint=CheckpointConfig(ckpt, every_k=3))

    def test_atomicity_survives_crash_mid_save(self, tmp_path, monkeypatch):
        """A crash inside the checkpoint write leaves the previous complete
        checkpoint (tmp + rename: never a torn file)."""
        from mmlspark_tpu.gbdt.checkpoint import (load_checkpoint,
                                                  save_checkpoint)

        path = str(tmp_path / "c.ckpt")
        args = dict(params_dict={"a": 1}, model_string="tree v1",
                    scores=np.zeros((4, 1)), rng_state={"s": 1},
                    bag_mask=np.ones(4, dtype=bool), best_val=0.5,
                    best_iter=2, rounds_no_improve=0)
        save_checkpoint(path, iteration=3, **args)

        def replace_boom(a, b):
            raise OSError(errno.EIO, "injected crash mid-rename")

        monkeypatch.setattr(os, "replace", replace_boom)
        with pytest.raises(OSError):
            save_checkpoint(path, iteration=4, **args)
        monkeypatch.undo()
        ck = load_checkpoint(path)
        assert ck["iteration"] == 3  # previous complete checkpoint intact


# ---------------------------------------------------------------------------
# DNN train loop: preemption hook + checkpoint/resume
# ---------------------------------------------------------------------------


class TestDNNTrainLoop:
    def _setup(self):
        from mmlspark_tpu.models import training as T
        from mmlspark_tpu.models.module import Dense, Sequential

        module = Sequential([("fc", Dense(2))], name="tiny")
        opt = T.make_optimizer(learning_rate=0.1)
        state = T.init_train_state(module, (4,), opt, seed=0)
        step = T.compile_train_step(module, opt)
        return T, state, step

    @staticmethod
    def _batches(n, seed=0):
        rng = np.random.default_rng(seed)
        out = []
        for _ in range(n):
            x = rng.normal(size=(8, 4)).astype(np.float32)
            y = (x[:, 0] > 0).astype(np.int32)
            out.append({"x": x, "y": y})
        return out

    def test_preemption_signal_checkpoints_and_stops(self, tmp_path):
        T, state, step = self._setup()
        ckpt = str(tmp_path / "dnn_ckpt")
        guard = T.PreemptionGuard()
        batches = self._batches(10)

        def preempting(batches):
            for i, b in enumerate(batches):
                if i == 4:
                    guard.request()  # SIGTERM equivalent, delivered manually
                yield b

        res = T.run_train_loop(state, step, preempting(batches),
                               checkpoint_path=ckpt, every_k=100,
                               guard=guard)
        assert res.preempted and res.steps_run == 4
        assert os.path.isdir(ckpt) or os.path.exists(ckpt)

        # resume finishes the remaining steps
        T2, state2, step2 = self._setup()
        res2 = T.run_train_loop(state2, step2, self._batches(10),
                                checkpoint_path=ckpt, guard=None)
        assert not res2.preempted and res2.steps_run == 6
        assert int(np.asarray(res2.state.step)) == 10

    def test_resume_matches_uninterrupted(self, tmp_path):
        T, state, step = self._setup()
        batches = self._batches(8)
        full = T.run_train_loop(state, step, batches)
        assert full.steps_run == 8

        T2, stateA, stepA = self._setup()
        ckpt = str(tmp_path / "halfway")
        half = T.run_train_loop(stateA, stepA, batches[:4],
                                checkpoint_path=ckpt, every_k=4)
        assert half.steps_run == 4
        T3, stateB, stepB = self._setup()
        res = T.run_train_loop(stateB, stepB, batches,
                               checkpoint_path=ckpt, every_k=100)
        assert res.steps_run == 4  # only the un-trained suffix ran
        import jax

        for a, b in zip(jax.tree.leaves(res.state.params),
                        jax.tree.leaves(full.state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_train_step_injection_point_fires(self):
        T, state, step = self._setup()
        with FaultInjector(seed=0).plan(faults.TRAIN_STEP, at=(3,)) as inj:
            with pytest.raises(InjectedFault):
                T.run_train_loop(state, step, self._batches(5))
            assert len(inj.fired(faults.TRAIN_STEP)) == 1

    def test_preemption_guard_signal_handler_roundtrip(self):
        import signal as S

        T, _, _ = self._setup()
        prev = S.getsignal(S.SIGUSR1)
        guard = T.PreemptionGuard(signals=(S.SIGUSR1,))
        with guard:
            os.kill(os.getpid(), S.SIGUSR1)
            deadline = time.time() + 2
            while not guard.requested() and time.time() < deadline:
                time.sleep(0.01)
            assert guard.requested()
        # handler restored after exit
        assert S.getsignal(S.SIGUSR1) == prev


class TestCompileCacheChaos:
    """Persistent compile-cache degradation contract (serving/fleet/cache):
    every load/store failure — injected or on-disk — is an accounted
    counter and a recompile, never a crash or a blocked serving path."""

    KEY = ("segF", (("col", (4,), "float32"),))

    def _compiled(self):
        import jax
        import jax.numpy as jnp

        x = jnp.ones((4,), jnp.float32)
        return jax.jit(lambda v: v * 3.0).lower(x).compile()

    def _populated(self, tmp_path):
        from mmlspark_tpu.core.device_stage import CompileCache
        from mmlspark_tpu.serving.fleet import PersistentCompileCache

        tier = PersistentCompileCache(str(tmp_path))
        cache = CompileCache()
        cache.attach_persistent(tier)
        cache.get(self.KEY, self._compiled, label="segF", shape="b4")
        assert tier.stats()["stores"] == 1
        return tier

    def test_load_fault_degrades_to_accounted_recompile(self, tmp_path):
        pytest.importorskip("jax")
        import jax.numpy as jnp

        from mmlspark_tpu.core.device_stage import CompileCache
        from mmlspark_tpu.serving.fleet import PersistentCompileCache

        self._populated(tmp_path)
        cache = CompileCache()
        tier = PersistentCompileCache(str(tmp_path))
        cache.attach_persistent(tier)
        built = []

        def builder():
            built.append(1)
            return self._compiled()

        with FaultInjector(seed=CHAOS_SEED).plan(
                faults.COMPILECACHE_LOAD, every=1) as inj:
            fn = cache.get(self.KEY, builder, label="segF", shape="b4")
            assert len(inj.fired(faults.COMPILECACHE_LOAD)) == 1
        # the populated entry was unreachable: serving recompiled and the
        # failure is a counter, not an exception
        assert built == [1]
        assert tier.stats()["load_errors"] == 1
        x = jnp.arange(4, dtype=jnp.float32)
        assert np.allclose(np.asarray(fn(x)), np.asarray(x) * 3.0)
        # honest memory-tier accounting: this WAS a compile
        assert cache.stats()["misses"] == 1

    def test_store_fault_never_blocks_serving(self, tmp_path):
        pytest.importorskip("jax")
        import jax.numpy as jnp

        from mmlspark_tpu.core.device_stage import CompileCache
        from mmlspark_tpu.serving.fleet import PersistentCompileCache

        tier = PersistentCompileCache(str(tmp_path))
        cache = CompileCache()
        cache.attach_persistent(tier)
        with FaultInjector(seed=CHAOS_SEED).plan(
                faults.COMPILECACHE_STORE, at=(1,)) as inj:
            fn = cache.get(self.KEY, self._compiled,
                           label="segF", shape="b4")
            assert len(inj.fired(faults.COMPILECACHE_STORE)) == 1
        x = jnp.arange(4, dtype=jnp.float32)
        assert np.allclose(np.asarray(fn(x)), np.asarray(x) * 3.0)
        s = tier.stats()
        assert s["store_errors"] == 1 and s["stores"] == 0
        assert tier.entry_count() == 0  # nothing half-written
        # the in-process cache is intact: the next request is a memory hit
        fn2 = cache.get(self.KEY, lambda: pytest.fail("must be resident"),
                        label="segF", shape="b4")
        assert fn2 is fn

    def test_warm_fault_shrinks_but_never_fails_pod_start(self, tmp_path):
        pytest.importorskip("jax")
        from mmlspark_tpu.core.device_stage import CompileCache
        from mmlspark_tpu.serving.fleet import PersistentCompileCache

        self._populated(tmp_path)
        tier = PersistentCompileCache(str(tmp_path))
        cache = CompileCache()
        with FaultInjector(seed=CHAOS_SEED).plan(
                faults.COMPILECACHE_LOAD, every=1):
            out = tier.warm(cache)
        assert out["warmed"] == 0 and out["errors"] == 1
        assert cache.stats()["entries"] == 0
        # without injection the same directory warms fine
        out2 = PersistentCompileCache(str(tmp_path)).warm(cache)
        assert out2["warmed"] == 1

    def test_on_disk_corruption_matrix(self, tmp_path):
        """Truncated tail, foreign magic, garbage payload: each load
        degrades to an accounted miss; the chaos seed picks the byte
        ranges so the matrix varies across CI lanes."""
        pytest.importorskip("jax")
        from mmlspark_tpu.serving.fleet import PersistentCompileCache
        from mmlspark_tpu.serving.fleet.cache import SUFFIX

        rng = np.random.default_rng(CHAOS_SEED)
        for mode in ("truncate", "magic", "garbage"):
            sub = tmp_path / mode
            sub.mkdir()
            self._populated(sub)
            (name,) = [n for n in os.listdir(sub) if n.endswith(SUFFIX)]
            path = os.path.join(str(sub), name)
            blob = open(path, "rb").read()
            if mode == "truncate":
                cut = int(rng.integers(1, len(blob)))
                blob = blob[:cut]
            elif mode == "magic":
                blob = b"XXXXXX" + blob[6:]
            else:
                lo = int(rng.integers(0, max(1, len(blob) - 64)))
                blob = blob[:lo] + bytes(rng.integers(
                    0, 256, 64, dtype=np.uint8)) + blob[lo + 64:]
            with open(path, "wb") as fh:
                fh.write(blob)
            tier = PersistentCompileCache(str(sub))
            assert tier.load(self.KEY, label="segF", shape="b4") is None, \
                mode
            st = tier.stats()
            # every outcome is accounted: either a parse failure or (for
            # a garbage run that shredded the header length) a miss
            assert st["load_errors"] + st["misses"] >= 1, mode


# ---------------------------------------------------------------------------
# Model lifecycle: crash mid-swap / mid-checkpoint (serving/lifecycle)
# ---------------------------------------------------------------------------


def _lc_sparse_rows(n, seed=0, nnz=3):
    rng = np.random.default_rng(seed)
    rows, labels = [], []
    for _ in range(n):
        idx = rng.choice(64, size=nnz, replace=False)
        rows.append({"indices": [int(i) for i in idx],
                     "values": [float(v) for v in
                                rng.normal(size=nnz).round(3)]})
        labels.append(float(rng.integers(0, 2)))
    return rows, labels


class TestLifecycleChaos:
    """The two lifecycle chaos seams: ``lifecycle.swap`` fires BEFORE any
    registry/executor state mutates (a crash mid-swap must leave the
    incumbent serving), ``lifecycle.checkpoint`` fires before the atomic
    checkpoint write (resume + journal replay must be bitwise)."""

    def _plane(self, candidate, steps=(0.0,)):
        pytest.importorskip("jax")
        from mmlspark_tpu.serving.lifecycle import (CanaryConfig,
                                                    LifecyclePlane)

        clock = [1_000.0]
        plane = LifecyclePlane(
            CanaryConfig(shadow_fraction=0.0, steps=steps, hold_s=0.0,
                         min_step_requests=0, check_interval_s=0.0,
                         objective_ms=60_000.0),
            clock=lambda: clock[0])
        plane.registry.adopt_live(
            lambda df: df.with_column("reply", lambda p: p["value"]),
            version="base")
        plane.deploy(candidate, version="cand")
        return plane, clock

    def test_crash_mid_swap_keeps_registry_intact(self):
        """An injected crash inside swap_live (fired before any mutation)
        leaves the incumbent live and the candidate retriable; the next
        tick completes the promotion."""
        from mmlspark_tpu.serving.lifecycle import CANARY

        plane, clock = self._plane(
            lambda df: df.with_column("reply", lambda p: p["value"]))
        with FaultInjector(seed=CHAOS_SEED).plan(
                faults.LIFECYCLE_SWAP, at=(1,)):
            clock[0] += 1.0
            plane.tick(0.01)  # promotion attempt 1: seam raises mid-swap
            assert any(e["action"] == "swap_failed"
                       for e in plane.controller.journal)
            assert plane.registry.live.version == "base"
            assert plane.registry.get("cand").state == CANARY
            # traffic still resolves through the incumbent
            out = plane(_lc_df([b"hello"]))
            assert list(out.collect()["reply"]) == [b"hello"]
            clock[0] += 1.0
            plane.tick(0.01)  # seam passes -> promotion completes
        assert plane.registry.live.version == "cand"

    def test_crash_mid_swap_e2e_incumbent_replies_bitwise(self):
        """Through a live server with a DIVERGING candidate and the swap
        seam raising on every attempt: clients only ever see the
        incumbent's bytes (the candidate never takes traffic at share 0,
        and the repeated failed promotions never half-install it)."""
        pytest.importorskip("jax")
        from mmlspark_tpu.serving.server import ServingServer

        def echo(df):
            return df.with_column("reply", lambda p: p["value"])

        def diverging(df):
            return df.with_column("reply",
                                  lambda p: [b"WRONG" for _ in p["id"]])

        srv = ServingServer(echo, port=0, max_wait_ms=1.0,
                            lifecycle={"shadow_fraction": 0.0,
                                       "steps": (0.0,), "hold_s": 0.0,
                                       "min_step_requests": 0,
                                       "check_interval_s": 0.0,
                                       "objective_ms": 60_000.0})
        with FaultInjector(seed=CHAOS_SEED).plan(
                faults.LIFECYCLE_SWAP, every=1, times=-1):
            with srv:
                plane = srv._lifecycle
                plane.deploy(diverging, version="bad")
                deadline = time.monotonic() + 20.0
                failed = 0
                i = 0
                while time.monotonic() < deadline:
                    body = json.dumps({"i": i}).encode()
                    req = urllib.request.Request(srv.address, data=body,
                                                 method="POST")
                    with urllib.request.urlopen(req, timeout=15) as resp:
                        assert resp.read() == body  # incumbent, bitwise
                    i += 1
                    failed = sum(1 for e in plane.controller.journal
                                 if e["action"] == "swap_failed")
                    if failed >= 2:
                        break
                assert failed >= 2
                assert plane.registry.live.version != "bad"
                assert plane.controller.promotions == 0

    def test_checkpoint_crash_resume_is_bitwise(self):
        """Crash before checkpoint k's write: the on-disk checkpoint stays
        at k-1, and a fresh trainer's resume + journal replay reproduces
        the uninterrupted run's state bitwise. The chaos seed picks k."""
        pytest.importorskip("jax")
        import tempfile

        from mmlspark_tpu.serving.lifecycle import (OnlineTrainer,
                                                    VWOnlineAdapter)
        from mmlspark_tpu.vw.learner import LearnerConfig

        cfg = LearnerConfig(num_bits=8)
        rows, labels = _lc_sparse_rows(24, seed=CHAOS_SEED)
        crash_at = 2 + CHAOS_SEED % 3

        with tempfile.TemporaryDirectory() as td:
            ref = OnlineTrainer(VWOnlineAdapter(cfg),
                                os.path.join(td, "ref.jsonl"),
                                os.path.join(td, "ref.ck"), batch_rows=4)
            ref.feed(rows, labels)
            ref.train_pending()
            ref_state = ref.adapter.to_json(ref.state)
            ref.stop()

            t1 = OnlineTrainer(VWOnlineAdapter(cfg),
                               os.path.join(td, "fb.jsonl"),
                               os.path.join(td, "ck.json"), batch_rows=4)
            t1.feed(rows, labels)
            with FaultInjector(seed=CHAOS_SEED).plan(
                    faults.LIFECYCLE_CHECKPOINT, at=(crash_at,)):
                with pytest.raises(InjectedFault):
                    t1.train_pending()
            t1.journal.close()  # crash: no stop(), no further writes
            with open(os.path.join(td, "ck.json"),
                      encoding="utf-8") as fh:
                assert json.load(fh)["step"] == crash_at - 1

            t2 = OnlineTrainer(VWOnlineAdapter(cfg),
                               os.path.join(td, "fb.jsonl"),
                               os.path.join(td, "ck.json"), batch_rows=4)
            assert t2.resume() is True
            assert t2.step == crash_at - 1
            t2.train_pending()
            assert t2.consumed == 24
            assert t2.adapter.to_json(t2.state) == ref_state
            t2.stop()


def _lc_df(values):
    from mmlspark_tpu.core.dataframe import DataFrame

    h = np.empty(len(values), dtype=object)
    for i in range(len(values)):
        h[i] = {}
    return DataFrame.from_dict({
        "id": np.arange(len(values), dtype=np.int64),
        "value": np.asarray(values, dtype=object),
        "headers": h,
    })
