"""REAL multi-process distributed tests: two OS processes, jax.distributed
rendezvous, gloo collectives over the inter-process (DCN-stand-in) link.

This is the multi-host story the reference implements with a driver-socket
rendezvous + native comm rings (LightGBMUtils.scala:105-173, VW spanning
tree): here `make_mesh` bootstraps `jax.distributed` from MMLSPARK_* env
vars and XLA collectives span the processes. The 8-device virtual-CPU mesh
used everywhere else in the suite exercises multi-DEVICE semantics in one
process; this file proves the multi-PROCESS layer (coordinator rendezvous,
cross-process collectives, per-process input sharding) actually works.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

WORKER = r"""
import os, sys
pid = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["MMLSPARK_COORDINATOR"] = f"localhost:{port}"
os.environ["MMLSPARK_NUM_PROCESSES"] = "2"
os.environ["MMLSPARK_PROCESS_ID"] = str(pid)
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh, process_shard

# 1. mesh construction bootstraps jax.distributed from the env
mesh = make_mesh(MeshSpec(data=8))
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert jax.local_device_count() == 4

# 2. cross-process collective: global sum of a row-sharded array
x_global = np.arange(8.0, dtype=np.float32)
sharding = NamedSharding(mesh, P("data"))
off = jax.process_index() * 4
arrs = [jax.device_put(x_global[off + i:off + i + 1], d)
        for i, d in enumerate(mesh.local_devices)]
x = jax.make_array_from_single_device_arrays((8,), sharding, arrs)
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
got = float(np.asarray(total.addressable_data(0)))
assert got == 28.0, got

# 3. per-process input sharding: round-robin partitions
df = DataFrame.from_dict({"v": np.arange(12.0)}, num_partitions=4)
mine = process_shard(df)
assert mine.num_partitions == 2, mine.num_partitions
local_sum = float(np.sum(mine.column("v")))

# 4. the local sums from (3) recombine across processes (allgather)
from jax.experimental import multihost_utils
all_sums = multihost_utils.process_allgather(np.float32(local_sum))
assert float(np.sum(all_sums)) == 66.0, all_sums  # sum(0..11)

print(f"WORKER {pid} OK", flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_two_workers(tmp_path, script: str, ok_marker: str,
                     timeout: int = 240):
    """Launch the worker template in 2 OS processes sharing a rendezvous
    port; assert both exit 0 and print their ok marker."""
    worker = tmp_path / "worker.py"
    worker.write_text(script.replace("{repo!r}", repr(str(REPO))))
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("MMLSPARK_", "XLA_", "JAX_"))}
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(pid), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for pid in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert ok_marker.format(pid=pid) in out


def test_two_process_mesh_collectives_and_input_sharding(tmp_path):
    _run_two_workers(tmp_path, WORKER, "WORKER {pid} OK")


GBDT_WORKER = r"""
import os, sys
pid = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["MMLSPARK_COORDINATOR"] = f"localhost:{port}"
os.environ["MMLSPARK_NUM_PROCESSES"] = "2"
os.environ["MMLSPARK_PROCESS_ID"] = str(pid)
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
from mmlspark_tpu.gbdt.booster import TrainParams
from mmlspark_tpu.gbdt import booster as B
from mmlspark_tpu.gbdt.sparse import SparseDataset, train_sparse, predict_csr

mesh = make_mesh(MeshSpec(data=8))
assert jax.process_count() == 2

# DENSE: row-sharded whole-tree growth across 2 OS processes (psum'd
# histograms over the inter-process link) == the single-device fit run
# in the SAME process (the reference's distributed-vs-local parity,
# TrainUtils.scala:383-418)
rng = np.random.default_rng(0)
X = rng.normal(size=(2048, 8))
y = (X[:, 0] + X[:, 1] * 0.5 + 0.2 * rng.normal(size=2048) > 0
     ).astype(np.float64)
params = TrainParams(objective="binary", num_iterations=3, num_leaves=7,
                     min_data_in_leaf=5, seed=0)
b_mp = B.train(params, X, y, mesh=mesh)
b_single = B.train(params, X, y)
np.testing.assert_allclose(b_mp.raw_predict(X), b_single.raw_predict(X),
                           atol=2e-4)

# SPARSE: nnz-balanced row shards, psum'd flat histograms across the
# processes; prediction parity vs the single-device CSR fit
n, f = 1200, 12
Xs = rng.normal(size=(n, f)) * (rng.random((n, f)) < 0.3)
ys = (Xs[:, 0] * 2 - Xs[:, 1] + Xs[:, 2]
      + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
indptr = np.zeros(n + 1, np.int64); idxs = []; vals = []
for i in range(n):
    nz = np.nonzero(Xs[i])[0]; idxs.append(nz); vals.append(Xs[i][nz])
    indptr[i + 1] = indptr[i] + len(nz)
idx = np.concatenate(idxs); val = np.concatenate(vals)
ds = SparseDataset.from_csr(indptr, idx, val, f)
b_sp = train_sparse(params, ds, ys, mesh=mesh)
b_sp1 = train_sparse(params, ds, ys)
p_mp = predict_csr(b_sp.trees, indptr, idx, val, 1)[:, 0]
p_1 = predict_csr(b_sp1.trees, indptr, idx, val, 1)[:, 0]
acc_mp = float((((p_mp + b_sp.base_score[0]) > 0) == ys).mean())
acc_1 = float((((p_1 + b_sp1.base_score[0]) > 0) == ys).mean())
assert abs(acc_mp - acc_1) <= 0.02, (acc_mp, acc_1)
# the established sharded-sparse contract (test_gbdt_sparse sharded gate):
# scores approximately equal, not bit-equal (psum'd shard histograms)
assert float(np.mean(np.abs(p_mp - p_1))) < 0.05, \
    float(np.mean(np.abs(p_mp - p_1)))

# VW: per-shard sequential scans with psum-averaged weights between
# passes (the AllReduce spanning-tree parity) across the 2 processes
from mmlspark_tpu.vw.learner import (LearnerConfig, predict_linear,
                                     train_linear)
from mmlspark_tpu.vw.learner import SparseDataset as VWDataset

nv = 512
rows = []
yv = np.zeros(nv)
for i in range(nv):
    feats = rng.integers(0, 1 << 10, size=6)
    vals = np.ones(6, dtype=np.float32)
    rows.append({"indices": feats, "values": vals})
    yv[i] = 1.0 if (feats % 7 == 0).any() else -1.0   # VW {-1,+1} labels
vds = VWDataset.from_rows(rows, yv, num_bits=12)
cfg = LearnerConfig(loss_function="logistic", num_passes=3, num_bits=12,
                    learning_rate=0.5)
w_mp, _ = train_linear(cfg, vds, mesh=mesh)
w_1, _ = train_linear(cfg, vds)
pred_mp = predict_linear(w_mp, vds)
pred_1 = predict_linear(w_1, vds)
acc_vw_mp = float(((pred_mp > 0) == (yv > 0)).mean())
acc_vw_1 = float(((pred_1 > 0) == (yv > 0)).mean())
assert abs(acc_vw_mp - acc_vw_1) <= 0.05, (acc_vw_mp, acc_vw_1)

# FTRL: the weight transform runs on fetched host state (eager jnp ops on
# non-addressable multi-process state raised before the fetch was hoisted)
import dataclasses
cfg_f = dataclasses.replace(cfg, ftrl=True)
w_f, _ = train_linear(cfg_f, vds, mesh=mesh)
assert np.isfinite(w_f).all() and w_f.shape == w_mp.shape

print(f"GBDT WORKER {pid} OK", flush=True)
"""


def test_two_process_gbdt_training_parity(tmp_path):
    """REAL multi-process distributed training: dense + sparse row-sharded
    GBDT and psum-averaged VW across 2 OS processes (fetch_global
    allgathers the sharded routing) match the single-device fits."""
    _run_two_workers(tmp_path, GBDT_WORKER, "GBDT WORKER {pid} OK",
                     timeout=420)
