"""REAL multi-process distributed tests: two OS processes, jax.distributed
rendezvous, gloo collectives over the inter-process (DCN-stand-in) link.

This is the multi-host story the reference implements with a driver-socket
rendezvous + native comm rings (LightGBMUtils.scala:105-173, VW spanning
tree): here `make_mesh` bootstraps `jax.distributed` from MMLSPARK_* env
vars and XLA collectives span the processes. The 8-device virtual-CPU mesh
used everywhere else in the suite exercises multi-DEVICE semantics in one
process; this file proves the multi-PROCESS layer (coordinator rendezvous,
cross-process collectives, per-process input sharding) actually works.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

WORKER = r"""
import os, sys
pid = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["MMLSPARK_COORDINATOR"] = f"localhost:{port}"
os.environ["MMLSPARK_NUM_PROCESSES"] = "2"
os.environ["MMLSPARK_PROCESS_ID"] = str(pid)
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh, process_shard

# 1. mesh construction bootstraps jax.distributed from the env
mesh = make_mesh(MeshSpec(data=8))
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert jax.local_device_count() == 4

# 2. cross-process collective: global sum of a row-sharded array
x_global = np.arange(8.0, dtype=np.float32)
sharding = NamedSharding(mesh, P("data"))
off = jax.process_index() * 4
arrs = [jax.device_put(x_global[off + i:off + i + 1], d)
        for i, d in enumerate(mesh.local_devices)]
x = jax.make_array_from_single_device_arrays((8,), sharding, arrs)
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
got = float(np.asarray(total.addressable_data(0)))
assert got == 28.0, got

# 3. per-process input sharding: round-robin partitions
df = DataFrame.from_dict({"v": np.arange(12.0)}, num_partitions=4)
mine = process_shard(df)
assert mine.num_partitions == 2, mine.num_partitions
local_sum = float(np.sum(mine.column("v")))

# 4. the local sums from (3) recombine across processes (allgather)
from jax.experimental import multihost_utils
all_sums = multihost_utils.process_allgather(np.float32(local_sum))
assert float(np.sum(all_sums)) == 66.0, all_sums  # sum(0..11)

print(f"WORKER {pid} OK", flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_mesh_collectives_and_input_sharding(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER.replace("{repo!r}", repr(str(REPO))))
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("MMLSPARK_", "XLA_", "JAX_"))}
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(pid), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for pid in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"WORKER {pid} OK" in out
