"""GBDT engine tests: binning, histogram/split kernels, boosting, stages."""

import dataclasses

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.gbdt import (
    BinMapper,
    Booster,
    LightGBMClassifier,
    LightGBMRanker,
    LightGBMRegressor,
    TrainParams,
)
from mmlspark_tpu.gbdt import booster as B
from mmlspark_tpu.gbdt import histogram as H
from mmlspark_tpu.gbdt.predict import DeviceEnsemble, predict_ensemble
from mmlspark_tpu.gbdt.tree import GrowerConfig, grow_tree


def synth_binary(n=500, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    logit = X[:, 0] * 2 - X[:, 1] + 0.5 * X[:, 2] * X[:, 0]
    y = (logit + rng.normal(scale=0.3, size=n) > 0).astype(np.float64)
    return X, y


def feature_df(X, y, extra=None, parts=2):
    rows = [X[i] for i in range(len(X))]
    d = {"features": rows, "label": y}
    if extra:
        d.update(extra)
    return DataFrame.from_dict(d, num_partitions=parts)


def fm(bins_nf):
    """Row-major [N, F] host bins -> the feature-major [F, N] device layout
    the histogram kernels take (column store, no XLA lane padding)."""
    import jax.numpy as jnp

    return jnp.asarray(np.ascontiguousarray(np.asarray(bins_nf).T))


class TestBinning:
    def test_fit_transform_shapes(self):
        X = np.random.default_rng(0).normal(size=(100, 5))
        m = BinMapper.fit(X, max_bin=16)
        bins = m.transform(X)
        assert bins.shape == X.shape
        assert bins.min() >= 1  # no missing
        assert bins.max() < m.max_num_bins

    def test_missing_goes_to_bin0(self):
        X = np.array([[1.0], [np.nan], [2.0]])
        m = BinMapper.fit(X, max_bin=8)
        bins = m.transform(X)
        assert bins[1, 0] == 0 and bins[0, 0] >= 1

    def test_categorical_nan_warning_free(self):
        # NaN in a categorical column: no NaN->int cast (platform-defined,
        # warns), missing -> bin 0, unseen category -> bin 0
        import warnings

        X = np.array([[1.0], [np.nan], [4.0], [2.0], [99.0], [np.inf]])
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            m = BinMapper.fit(X, max_bin=8, categorical_indexes=[0])
            bins = m.transform(X)
        assert bins[1, 0] == 0          # missing
        assert bins[5, 0] == 0          # inf: not a representable category
        assert bins[0, 0] >= 1 and bins[2, 0] >= 1 and bins[3, 0] >= 1

    def test_monotonic(self):
        X = np.linspace(0, 1, 50).reshape(-1, 1)
        m = BinMapper.fit(X, max_bin=8)
        bins = m.transform(X)[:, 0]
        assert (np.diff(bins) >= 0).all()

    def test_categorical(self):
        X = np.array([[3.0], [7.0], [3.0], [9.0]])
        m = BinMapper.fit(X, max_bin=8, categorical_indexes=[0])
        bins = m.transform(X)[:, 0]
        assert bins[0] == bins[2] and bins[0] != bins[1]

    def test_json_roundtrip(self):
        X = np.random.default_rng(0).normal(size=(50, 3))
        m = BinMapper.fit(X, max_bin=8)
        m2 = BinMapper.from_json(m.to_json())
        np.testing.assert_array_equal(m.transform(X), m2.transform(X))


class TestHistogram:
    def test_matches_numpy(self):
        rng = np.random.default_rng(0)
        n, f, b = 200, 4, 16
        bins = rng.integers(0, b, size=(n, f)).astype(np.int32)
        grad = rng.normal(size=n).astype(np.float32)
        hess = rng.uniform(0.1, 1, size=n).astype(np.float32)
        mask = rng.random(n) < 0.7
        hist = np.asarray(H.compute_histogram(fm(bins), grad, hess, mask, b))
        for fi in range(f):
            for bi in range(b):
                sel = (bins[:, fi] == bi) & mask
                np.testing.assert_allclose(hist[fi, bi, 0], grad[sel].sum(), atol=1e-3)
                np.testing.assert_allclose(hist[fi, bi, 1], hess[sel].sum(), atol=1e-3)
                np.testing.assert_allclose(hist[fi, bi, 2], sel.sum(), atol=1e-3)

    def test_split_finds_perfect_separator(self):
        # feature 1 perfectly separates grad sign at bin <= 4
        n, f, b = 100, 3, 8
        rng = np.random.default_rng(0)
        bins = rng.integers(1, b, size=(n, f)).astype(np.int32)
        grad = np.where(bins[:, 1] <= 4, -1.0, 1.0).astype(np.float32)
        hess = np.ones(n, dtype=np.float32)
        mask = np.ones(n, dtype=bool)
        hist = H.compute_histogram(fm(bins), grad, hess, mask, b)
        split = H.find_best_split(hist, 0.0, 0.0, 1e-3, 1)
        assert int(split.feature) == 1
        assert int(split.bin) == 4

    def test_subtraction_trick(self):
        rng = np.random.default_rng(1)
        n, f, b = 300, 5, 16
        bins = rng.integers(0, b, size=(n, f)).astype(np.int32)
        grad = rng.normal(size=n).astype(np.float32)
        hess = rng.uniform(0.1, 1, size=n).astype(np.float32)
        all_mask = np.ones(n, dtype=bool)
        sub_mask = rng.random(n) < 0.5
        parent = np.asarray(H.compute_histogram(fm(bins), grad, hess, all_mask, b))
        child = np.asarray(H.compute_histogram(fm(bins), grad, hess, sub_mask, b))
        sibling = np.asarray(H.subtract_histogram(parent, child))
        direct = np.asarray(H.compute_histogram(fm(bins), grad, hess, ~sub_mask, b))
        np.testing.assert_allclose(sibling, direct, atol=1e-2)


class TestTreeGrowth:
    def test_tree_reduces_loss(self):
        import jax.numpy as jnp
        X, y = synth_binary(400)
        m = BinMapper.fit(X, max_bin=32)
        bins = m.transform(X)
        p = np.full_like(y, y.mean())
        grad = (p - y).astype(np.float32)
        hess = np.maximum(p * (1 - p), 1e-6).astype(np.float32)
        tree, leaf_of_row = grow_tree(
            fm(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.ones(len(y), dtype=bool), m.max_num_bins,
            GrowerConfig(num_leaves=15, min_data_in_leaf=5), m)
        assert tree.num_leaves > 1
        # leaf updates move scores toward labels
        delta = tree.value[leaf_of_row]
        corr = np.corrcoef(delta, y - p)[0, 1]
        assert corr > 0.5

    def test_leaf_of_row_matches_predict(self):
        import jax.numpy as jnp
        X, y = synth_binary(200)
        m = BinMapper.fit(X, max_bin=32)
        bins = m.transform(X)
        grad = (0.5 - y).astype(np.float32)
        hess = np.full(len(y), 0.25, dtype=np.float32)
        tree, leaf_of_row = grow_tree(
            fm(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.ones(len(y), dtype=bool), m.max_num_bins,
            GrowerConfig(num_leaves=8, min_data_in_leaf=5), m)
        from mmlspark_tpu.gbdt.tree import predict_tree_binned
        pred_binned = predict_tree_binned(tree, bins)
        np.testing.assert_allclose(tree.value[leaf_of_row] * tree.shrinkage,
                                   pred_binned, atol=1e-9)

    def test_raw_threshold_predict_matches_binned(self):
        import jax.numpy as jnp
        from mmlspark_tpu.gbdt.predict import predict_single_tree
        from mmlspark_tpu.gbdt.tree import predict_tree_binned
        X, y = synth_binary(300, seed=3)
        m = BinMapper.fit(X, max_bin=64)
        bins = m.transform(X)
        grad = (0.5 - y).astype(np.float32)
        hess = np.full(len(y), 0.25, dtype=np.float32)
        tree, _ = grow_tree(
            fm(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.ones(len(y), dtype=bool), m.max_num_bins,
            GrowerConfig(num_leaves=16, min_data_in_leaf=5), m)
        np.testing.assert_allclose(predict_single_tree(tree, X),
                                   predict_tree_binned(tree, bins), atol=1e-9)


class TestFusedTreeGrower:
    """The one-dispatch-per-tree device grower must produce the SAME tree as
    the host-orchestrated per-split path (same kernels, same pop order)."""

    def _grow_both(self, monkeypatch, config, seed=0, with_mask=False,
                   with_feature_mask=False, with_missing=False):
        import jax.numpy as jnp

        rng = np.random.default_rng(seed)
        X, y = synth_binary(500, seed=seed)
        if with_missing:
            X[rng.random(X.shape) < 0.1] = np.nan
        m = BinMapper.fit(X, max_bin=32)
        bins = fm(m.transform(X))
        p = np.full_like(y, y.mean())
        grad = jnp.asarray((p - y).astype(np.float32))
        hess = jnp.asarray(np.maximum(p * (1 - p), 1e-6).astype(np.float32))
        mask = jnp.asarray(rng.random(len(y)) < 0.8) if with_mask \
            else jnp.ones(len(y), dtype=bool)
        fmask = None
        if with_feature_mask:
            fmask_np = np.ones(X.shape[1], dtype=bool)
            fmask_np[rng.choice(X.shape[1], size=2, replace=False)] = False
            fmask = jnp.asarray(fmask_np)

        monkeypatch.delenv("MMLSPARK_TPU_NO_FUSED_TREE", raising=False)
        monkeypatch.setenv("MMLSPARK_TPU_FUSED_TREE", "1")
        fused, fused_rows = grow_tree(bins, grad, hess, mask, m.max_num_bins,
                                      config, m, fmask)
        monkeypatch.setenv("MMLSPARK_TPU_NO_FUSED_TREE", "1")
        host, host_rows = grow_tree(bins, grad, hess, mask, m.max_num_bins,
                                    config, m, fmask)
        return fused, fused_rows, host, host_rows

    def _assert_trees_equal(self, fused, fused_rows, host, host_rows):
        np.testing.assert_array_equal(fused.feature, host.feature)
        np.testing.assert_array_equal(fused.threshold_bin, host.threshold_bin)
        np.testing.assert_array_equal(fused.default_left, host.default_left)
        np.testing.assert_array_equal(fused.left, host.left)
        np.testing.assert_array_equal(fused.right, host.right)
        np.testing.assert_array_equal(fused.count, host.count)
        np.testing.assert_allclose(fused.threshold, host.threshold)
        np.testing.assert_allclose(fused.value, host.value, rtol=1e-5,
                                   atol=1e-7)
        np.testing.assert_allclose(fused.gain, host.gain, rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_array_equal(fused_rows, host_rows)

    def test_matches_host_default_config(self, monkeypatch):
        out = self._grow_both(
            monkeypatch, GrowerConfig(num_leaves=15, min_data_in_leaf=5))
        self._assert_trees_equal(*out)

    def test_matches_host_regularized_masked(self, monkeypatch):
        out = self._grow_both(
            monkeypatch,
            GrowerConfig(num_leaves=31, min_data_in_leaf=3, lambda_l1=0.5,
                         lambda_l2=1.0, min_gain_to_split=0.01),
            seed=1, with_mask=True, with_feature_mask=True)
        self._assert_trees_equal(*out)

    def test_matches_host_max_depth_missing(self, monkeypatch):
        out = self._grow_both(
            monkeypatch,
            GrowerConfig(num_leaves=31, max_depth=3, min_data_in_leaf=5),
            seed=2, with_missing=True)
        fused = out[0]
        self._assert_trees_equal(*out)
        # max_depth actually bound the tree
        assert fused.num_leaves <= 8

    def test_unsplittable_root_value_zero(self, monkeypatch):
        import jax.numpy as jnp

        monkeypatch.delenv("MMLSPARK_TPU_NO_FUSED_TREE", raising=False)
        monkeypatch.setenv("MMLSPARK_TPU_FUSED_TREE", "1")
        # 4 rows with min_data_in_leaf=20: no split can satisfy constraints
        bins = jnp.asarray(np.array([[1, 2, 3, 4]], dtype=np.int32))  # [F=1, N=4]
        grad = jnp.asarray(np.array([1, -1, 1, -1], dtype=np.float32))
        hess = jnp.ones(4, dtype=jnp.float32)
        m = BinMapper.fit(np.array([[1.0], [2.0], [3.0], [4.0]]), max_bin=8)
        tree, rows = grow_tree(bins, grad, hess, jnp.ones(4, dtype=bool), 8,
                               GrowerConfig(num_leaves=7, min_data_in_leaf=20),
                               m)
        assert tree.num_leaves == 1
        assert tree.value[0] == 0.0
        np.testing.assert_array_equal(rows, np.zeros(4))

    def test_train_end_to_end_matches(self, monkeypatch):
        X, y = synth_binary(400, seed=4)
        params = TrainParams(objective="binary", num_iterations=10,
                             num_leaves=15, min_data_in_leaf=5)
        monkeypatch.delenv("MMLSPARK_TPU_NO_FUSED_TREE", raising=False)
        monkeypatch.setenv("MMLSPARK_TPU_FUSED_TREE", "1")
        b_fused = B.train(params, X, y)
        monkeypatch.setenv("MMLSPARK_TPU_NO_FUSED_TREE", "1")
        b_host = B.train(params, X, y)
        np.testing.assert_allclose(b_fused.raw_predict(X),
                                   b_host.raw_predict(X), rtol=1e-4, atol=1e-5)

    def test_gather_tiers_match_full_scan(self, monkeypatch):
        """Tiered small-child row compaction must grow the same tree as the
        full-row-scan histogram (summation association differs by ulps at
        most; structure and predictions must agree)."""
        import jax.numpy as jnp

        X, y = synth_binary(9000, seed=13)
        m = BinMapper.fit(X, max_bin=64)
        bins = fm(m.transform(X))
        p = np.full_like(y, y.mean())
        grad = jnp.asarray((p - y).astype(np.float32))
        hess = jnp.asarray(np.maximum(p * (1 - p), 1e-6).astype(np.float32))
        mask = jnp.ones(len(y), dtype=bool)
        config = GrowerConfig(num_leaves=15, min_data_in_leaf=5)
        monkeypatch.delenv("MMLSPARK_TPU_NO_FUSED_TREE", raising=False)
        monkeypatch.setenv("MMLSPARK_TPU_FUSED_TREE", "1")
        monkeypatch.delenv("MMLSPARK_TPU_NO_GATHER_HIST", raising=False)
        gat, rows_g = grow_tree(bins, grad, hess, mask, m.max_num_bins,
                                config, m)
        monkeypatch.setenv("MMLSPARK_TPU_NO_GATHER_HIST", "1")
        full, rows_f = grow_tree(bins, grad, hess, mask, m.max_num_bins,
                                 config, m)
        np.testing.assert_array_equal(gat.feature, full.feature)
        np.testing.assert_array_equal(gat.threshold_bin, full.threshold_bin)
        np.testing.assert_array_equal(gat.left, full.left)
        np.testing.assert_array_equal(gat.count, full.count)
        np.testing.assert_allclose(gat.value, full.value, rtol=1e-4, atol=1e-7)
        np.testing.assert_array_equal(rows_g, rows_f)

    def test_pallas_select_matches_nonzero_gather(self):
        """The Pallas stream-select kernel (one-hot MXU compaction + offset
        DMA, interpret mode here) must reproduce nonzero(size)+gather
        BIT-EXACTLY — same rows, same order, f32 pass-through untouched —
        because tier histogram summation order depends on it."""
        import jax.numpy as jnp

        from mmlspark_tpu.gbdt.pallas_select import select_rows

        rng = np.random.default_rng(5)
        N, F, CAP = 5000, 9, 2048
        bins = jnp.asarray(rng.integers(0, 255, size=(F, N), dtype=np.uint8))
        g = jnp.asarray(rng.normal(size=N).astype(np.float32))
        h = jnp.asarray(rng.random(N).astype(np.float32))
        for p, cap in [(0.25, CAP), (0.0, 512), (1.0, N + 512)]:
            mask = jnp.asarray(rng.random(N) < p)
            cnt = int(mask.sum())
            bc, gc, hc = select_rows(bins, g, h, mask, cap, interpret=True)
            assert bc.shape == (F, cap) and gc.shape == (cap,)
            idx = jnp.nonzero(mask, size=cap, fill_value=0)[0]
            np.testing.assert_array_equal(
                np.asarray(bc)[:, :cnt],
                np.asarray(jnp.take(bins, idx, axis=1))[:, :cnt])
            np.testing.assert_array_equal(np.asarray(gc)[:cnt],
                                          np.asarray(jnp.take(g, idx))[:cnt])
            np.testing.assert_array_equal(np.asarray(hc)[:cnt],
                                          np.asarray(jnp.take(h, idx))[:cnt])

    def test_select_tier_growth_matches_xla_path(self, monkeypatch):
        """Whole-tree growth with the select-kernel tier compaction
        (interpret mode, opted in) must match the XLA nonzero-tier path:
        row order is preserved, so trees agree beyond ulps. A call-count
        spy proves the kernel actually ran (the integration is gated three
        ways — a silently-dead gate would make this test vacuous)."""
        from mmlspark_tpu.gbdt import pallas_select

        X, y = synth_binary(40960, seed=3)
        params = TrainParams(objective="binary", num_iterations=2,
                             num_leaves=7, min_data_in_leaf=5)
        calls = []
        real = pallas_select.select_rows

        def spy(*a, **k):
            calls.append(1)
            return real(*a, **k)

        monkeypatch.setattr(pallas_select, "select_rows", spy)
        monkeypatch.setenv("MMLSPARK_TPU_SCAN_TRAIN", "1")
        monkeypatch.setenv("MMLSPARK_TPU_PALLAS_INTERPRET", "1")
        monkeypatch.setenv("MMLSPARK_TPU_SELECT_MIN_ROWS", "1000")
        b_sel = B.train(params, X, y)
        assert calls, "select kernel was never dispatched (gate went dead)"
        monkeypatch.setenv("MMLSPARK_TPU_PALLAS_INTERPRET", "0")
        monkeypatch.setenv("MMLSPARK_TPU_NO_PALLAS", "1")
        monkeypatch.setenv("MMLSPARK_TPU_NO_PALLAS_SELECT", "1")
        b_xla = B.train(params, X, y)
        np.testing.assert_allclose(b_sel.raw_predict(X),
                                   b_xla.raw_predict(X), rtol=2e-4, atol=1e-5)

    def test_scan_train_matches_host_path(self, monkeypatch):
        """The whole-run lax.scan path (all iterations in one dispatch) must
        agree with the host per-tree loop to float-rounding tolerance: the
        saved trees recompute leaf values in f64 from the same sums; only the
        running f32 score stream can differ by ulps."""
        X, y = synth_binary(400, seed=4)
        params = TrainParams(objective="binary", num_iterations=10,
                             num_leaves=15, min_data_in_leaf=5)
        monkeypatch.setenv("MMLSPARK_TPU_SCAN_TRAIN", "1")
        monkeypatch.delenv("MMLSPARK_TPU_NO_SCAN_TRAIN", raising=False)
        b_scan = B.train(params, X, y)
        monkeypatch.delenv("MMLSPARK_TPU_SCAN_TRAIN", raising=False)
        monkeypatch.setenv("MMLSPARK_TPU_NO_SCAN_TRAIN", "1")
        b_host = B.train(params, X, y)
        assert len(b_scan.trees) == len(b_host.trees)
        np.testing.assert_allclose(b_scan.raw_predict(X),
                                   b_host.raw_predict(X), rtol=1e-3, atol=1e-4)
        # accuracy must be indistinguishable
        acc_scan = np.mean((b_scan.raw_predict(X) > 0) == y)
        acc_host = np.mean((b_host.raw_predict(X) > 0) == y)
        assert abs(acc_scan - acc_host) < 0.01

    def test_scan_train_bagging_feature_fraction(self, monkeypatch):
        """Scan path with precomputed bagging + feature masks: the masks
        replicate the host loop's RNG draws exactly, so trees match the
        host path's structure on the first iterations."""
        X, y = synth_binary(600, seed=11)
        params = TrainParams(objective="binary", num_iterations=6,
                             num_leaves=7, min_data_in_leaf=5,
                             bagging_fraction=0.7, bagging_freq=2,
                             feature_fraction=0.8, seed=5)
        monkeypatch.setenv("MMLSPARK_TPU_SCAN_TRAIN", "1")
        monkeypatch.delenv("MMLSPARK_TPU_NO_SCAN_TRAIN", raising=False)
        b_scan = B.train(params, X, y)
        monkeypatch.delenv("MMLSPARK_TPU_SCAN_TRAIN", raising=False)
        monkeypatch.setenv("MMLSPARK_TPU_NO_SCAN_TRAIN", "1")
        b_host = B.train(params, X, y)
        # same RNG stream -> same masks -> first tree structurally identical
        np.testing.assert_array_equal(b_scan.trees[0][0].feature,
                                      b_host.trees[0][0].feature)
        np.testing.assert_array_equal(b_scan.trees[0][0].threshold_bin,
                                      b_host.trees[0][0].threshold_bin)
        np.testing.assert_allclose(b_scan.raw_predict(X),
                                   b_host.raw_predict(X), rtol=1e-3, atol=1e-4)

    def test_scan_train_bagging_compaction(self, monkeypatch):
        """Compacted bagging (rows gathered to the buffer front, full-row
        routing by split replay) must match the masked path: identical
        masks -> identical histograms up to f32 reassociation -> same
        model quality; first tree structurally identical on this data."""
        X, y = synth_binary(600, seed=11)
        params = TrainParams(objective="binary", num_iterations=6,
                             num_leaves=7, min_data_in_leaf=5,
                             bagging_fraction=0.5, bagging_freq=1, seed=5)
        monkeypatch.setenv("MMLSPARK_TPU_SCAN_TRAIN", "1")
        monkeypatch.delenv("MMLSPARK_TPU_NO_SCAN_TRAIN", raising=False)
        monkeypatch.setenv("MMLSPARK_TPU_NO_DENSE_BAG_COMPACT", "1")
        b_mask = B.train(params, X, y)
        monkeypatch.delenv("MMLSPARK_TPU_NO_DENSE_BAG_COMPACT")
        monkeypatch.setenv("MMLSPARK_TPU_DENSE_BAG_COMPACT", "1")
        b_comp = B.train(params, X, y)
        monkeypatch.delenv("MMLSPARK_TPU_DENSE_BAG_COMPACT")
        assert len(b_comp.trees) == len(b_mask.trees)
        np.testing.assert_array_equal(b_comp.trees[0][0].feature,
                                      b_mask.trees[0][0].feature)
        np.testing.assert_array_equal(b_comp.trees[0][0].count,
                                      b_mask.trees[0][0].count)
        acc_m = np.mean((b_mask.raw_predict(X) > 0) == y)
        acc_c = np.mean((b_comp.raw_predict(X) > 0) == y)
        assert abs(acc_m - acc_c) <= 0.02, (acc_m, acc_c)

    def test_scan_train_chunked_dispatch(self, monkeypatch):
        """Forcing tiny per-dispatch budgets must produce the same model:
        chunks share one compiled program, surplus overgrown trees are
        dropped, and the score carry stays consistent across chunks."""
        X, y = synth_binary(400, seed=6)
        params = TrainParams(objective="binary", num_iterations=7,
                             num_leaves=7, min_data_in_leaf=5)
        monkeypatch.setenv("MMLSPARK_TPU_SCAN_TRAIN", "1")
        monkeypatch.delenv("MMLSPARK_TPU_NO_SCAN_TRAIN", raising=False)
        b_one = B.train(params, X, y)
        # 3 chunks of 3 (last one overgrows 2 surplus trees)
        monkeypatch.setenv("MMLSPARK_TPU_SCAN_CHUNK_ROWS", str(3 * 512))
        b_chunked = B.train(params, X, y)
        assert len(b_chunked.trees) == 7
        np.testing.assert_allclose(b_chunked.raw_predict(X),
                                   b_one.raw_predict(X), rtol=1e-5, atol=1e-6)

    def test_scan_train_multiclass(self, monkeypatch):
        rng = np.random.default_rng(3)
        X = rng.normal(size=(300, 6))
        y = (X[:, 0] + X[:, 1] > 0.5).astype(np.float64) \
            + (X[:, 2] > 0.3).astype(np.float64)
        params = TrainParams(objective="multiclass", num_class=3,
                             num_iterations=5, num_leaves=7,
                             min_data_in_leaf=5)
        monkeypatch.setenv("MMLSPARK_TPU_SCAN_TRAIN", "1")
        monkeypatch.delenv("MMLSPARK_TPU_NO_SCAN_TRAIN", raising=False)
        b_scan = B.train(params, X, y)
        monkeypatch.delenv("MMLSPARK_TPU_SCAN_TRAIN", raising=False)
        monkeypatch.setenv("MMLSPARK_TPU_NO_SCAN_TRAIN", "1")
        b_host = B.train(params, X, y)
        np.testing.assert_allclose(b_scan.raw_predict(X),
                                   b_host.raw_predict(X), rtol=1e-3, atol=1e-4)

    def test_scan_train_goss_matches_host_accuracy(self, monkeypatch):
        """In-scan GOSS (exact-count top-k selection + compacted growth +
        full-row split replay) is a different sampler from the host loop's
        argsort/rng.choice, so trees differ — but it must land at the same
        accuracy, and the full-gbdt accuracy must be within GOSS's expected
        loss."""
        rng = np.random.default_rng(0)
        X = rng.normal(size=(4000, 10))
        logit = X[:, 0] * 2 + X[:, 1] - X[:, 2] * 0.5 \
            + 0.3 * rng.normal(size=4000)
        y = (logit > 0).astype(np.float64)
        params = TrainParams(objective="binary", num_iterations=15,
                             num_leaves=15, min_data_in_leaf=5,
                             boosting_type="goss", top_rate=0.2,
                             other_rate=0.1, seed=7)
        monkeypatch.setenv("MMLSPARK_TPU_SCAN_TRAIN", "1")
        monkeypatch.delenv("MMLSPARK_TPU_NO_SCAN_TRAIN", raising=False)
        b_scan = B.train(params, X, y)
        assert len(b_scan.trees) == 15
        monkeypatch.delenv("MMLSPARK_TPU_SCAN_TRAIN", raising=False)
        monkeypatch.setenv("MMLSPARK_TPU_NO_SCAN_TRAIN", "1")
        b_host = B.train(params, X, y)
        acc_scan = np.mean((b_scan.raw_predict(X) > 0) == y)
        acc_host = np.mean((b_host.raw_predict(X) > 0) == y)
        assert abs(acc_scan - acc_host) < 0.02, (acc_scan, acc_host)

    def test_scan_train_goss_deterministic(self, monkeypatch):
        """Same seed -> bit-identical model (the in-scan sampler draws from
        a params.seed-keyed counter PRNG, not host RNG state)."""
        X, y = synth_binary(2000, seed=9)
        params = TrainParams(objective="binary", num_iterations=6,
                             num_leaves=7, min_data_in_leaf=5,
                             boosting_type="goss", seed=11)
        monkeypatch.setenv("MMLSPARK_TPU_SCAN_TRAIN", "1")
        monkeypatch.delenv("MMLSPARK_TPU_NO_SCAN_TRAIN", raising=False)
        b1 = B.train(params, X, y)
        b2 = B.train(params, X, y)
        np.testing.assert_array_equal(b1.raw_predict(X), b2.raw_predict(X))
        # a different seed must change the sampled subsets (and the model)
        b3 = B.train(dataclasses.replace(params, seed=12), X, y)
        assert not np.array_equal(b1.raw_predict(X), b3.raw_predict(X))

    def test_scan_train_goss_multiclass(self, monkeypatch):
        """Multiclass GOSS selects ONE row subset per iteration from the
        summed |grad| across classes (host-path/LightGBM semantics) and
        grows all k trees on it."""
        rng = np.random.default_rng(3)
        X = rng.normal(size=(3000, 8))
        y = np.digitize(X[:, 0] + X[:, 1], [-0.8, 0.8]).astype(np.float64)
        params = TrainParams(objective="multiclass", num_class=3,
                             num_iterations=8, num_leaves=15,
                             min_data_in_leaf=5, boosting_type="goss",
                             seed=4)
        monkeypatch.setenv("MMLSPARK_TPU_SCAN_TRAIN", "1")
        monkeypatch.delenv("MMLSPARK_TPU_NO_SCAN_TRAIN", raising=False)
        b = B.train(params, X, y)
        acc = np.mean(np.argmax(b.raw_predict(X), axis=1) == y)
        assert acc > 0.8, acc

    def test_scan_train_goss_exact_count_with_padding(self, monkeypatch):
        """Selection is exactly top_n + other_n rows every iteration —
        observable as every tree's root count — and CHUNK padding rows
        (403 % 1024 != 0) are never selected (the exclude branch)."""
        X, y = synth_binary(403, seed=8)
        params = TrainParams(objective="binary", num_iterations=4,
                             num_leaves=7, min_data_in_leaf=5,
                             boosting_type="goss", top_rate=0.2,
                             other_rate=0.1, seed=3)
        monkeypatch.setenv("MMLSPARK_TPU_SCAN_TRAIN", "1")
        monkeypatch.delenv("MMLSPARK_TPU_NO_SCAN_TRAIN", raising=False)
        b = B.train(params, X, y)
        expect = int(403 * 0.2) + int(403 * 0.1)
        for group in b.trees:
            assert int(group[0].count[0]) == expect

    def test_sharded_fused_matches_single_device(self, mesh8, monkeypatch):
        """Whole-tree growth under shard_map (psum'd histograms) must produce
        the SAME tree as single-device fused growth."""
        import jax
        import jax.numpy as jnp

        from mmlspark_tpu.parallel.mesh import data_sharding

        monkeypatch.delenv("MMLSPARK_TPU_NO_FUSED_TREE", raising=False)
        monkeypatch.setenv("MMLSPARK_TPU_FUSED_TREE", "1")
        X, y = synth_binary(512, seed=7)
        m = BinMapper.fit(X, max_bin=32)
        bins = m.transform(X)
        p = np.full_like(y, y.mean())
        grad = (p - y).astype(np.float32)
        hess = np.maximum(p * (1 - p), 1e-6).astype(np.float32)
        mask = np.ones(len(y), dtype=bool)
        config = GrowerConfig(num_leaves=15, min_data_in_leaf=5)

        single, rows_single = grow_tree(
            fm(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(mask), m.max_num_bins, config, m)

        from jax.sharding import NamedSharding, PartitionSpec as P

        from mmlspark_tpu.parallel.mesh import DATA_AXIS

        shard = data_sharding(mesh8)
        bins_sh = NamedSharding(mesh8, P(None, DATA_AXIS))
        put = lambda a: jax.device_put(jnp.asarray(a), shard)  # noqa: E731
        sharded, rows_sharded = grow_tree(
            jax.device_put(fm(bins.astype(np.int32)), bins_sh),
            put(grad), put(hess), put(mask),
            m.max_num_bins, config, m)

        np.testing.assert_array_equal(sharded.feature, single.feature)
        np.testing.assert_array_equal(sharded.threshold_bin,
                                      single.threshold_bin)
        np.testing.assert_array_equal(sharded.left, single.left)
        np.testing.assert_array_equal(sharded.count, single.count)
        np.testing.assert_allclose(sharded.value, single.value, rtol=1e-4,
                                   atol=1e-6)
        np.testing.assert_array_equal(rows_sharded, rows_single)

    def test_sharded_fused_pallas_interpret_matches_xla(self, mesh8,
                                                        monkeypatch):
        """The psum'd MXU branch (what real TPU meshes run) must produce the
        same tree as the psum'd XLA-scatter branch — exercised on CPU via the
        Pallas interpreter."""
        import jax
        import jax.numpy as jnp

        from mmlspark_tpu.parallel.mesh import data_sharding

        monkeypatch.delenv("MMLSPARK_TPU_NO_FUSED_TREE", raising=False)
        monkeypatch.setenv("MMLSPARK_TPU_FUSED_TREE", "1")
        X, y = synth_binary(512, seed=9)
        m = BinMapper.fit(X, max_bin=16)
        bins = m.transform(X).astype(np.int32)
        grad = (0.5 - y).astype(np.float32)
        hess = np.full(len(y), 0.25, dtype=np.float32)
        config = GrowerConfig(num_leaves=7, min_data_in_leaf=5)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from mmlspark_tpu.parallel.mesh import DATA_AXIS

        shard = data_sharding(mesh8)
        bins_sh = NamedSharding(mesh8, P(None, DATA_AXIS))
        put = lambda a: jax.device_put(jnp.asarray(a), shard)  # noqa: E731
        args = (jax.device_put(fm(bins), bins_sh), put(grad), put(hess),
                put(np.ones(len(y), dtype=bool)), m.max_num_bins, config, m)

        xla_tree, xla_rows = grow_tree(*args)
        monkeypatch.setenv("MMLSPARK_TPU_PALLAS_INTERPRET", "1")
        mxu_tree, mxu_rows = grow_tree(*args)

        np.testing.assert_array_equal(mxu_tree.feature, xla_tree.feature)
        np.testing.assert_array_equal(mxu_tree.threshold_bin,
                                      xla_tree.threshold_bin)
        np.testing.assert_allclose(mxu_tree.value, xla_tree.value, rtol=1e-4,
                                   atol=1e-6)
        np.testing.assert_array_equal(mxu_rows, xla_rows)

    def test_sharded_fused_end_to_end_train(self, mesh8, monkeypatch):
        monkeypatch.delenv("MMLSPARK_TPU_NO_FUSED_TREE", raising=False)
        monkeypatch.setenv("MMLSPARK_TPU_FUSED_TREE", "1")
        X, y = synth_binary(403, seed=8)  # pad path: 403 % 8 != 0
        params = TrainParams(objective="binary", num_iterations=8,
                             num_leaves=7, min_data_in_leaf=5)
        b_mesh = B.train(params, X, y, mesh=mesh8)
        b_single = B.train(params, X, y)
        p1 = b_single.predict_proba(X)[:, 1]
        p2 = b_mesh.predict_proba(X)[:, 1]
        assert np.mean((p2 > 0.5) == y) > 0.88
        np.testing.assert_allclose(p1, p2, rtol=1e-3, atol=1e-4)

    def test_memory_budget_falls_back(self, monkeypatch):
        from mmlspark_tpu.gbdt.tree import _fused_tree_enabled

        monkeypatch.setenv("MMLSPARK_TPU_FUSED_TREE", "1")
        monkeypatch.setenv("MMLSPARK_TPU_FUSED_TREE_BYTES", "1000")
        assert not _fused_tree_enabled(63, 28, 256)  # budget wins over force-on
        monkeypatch.delenv("MMLSPARK_TPU_FUSED_TREE_BYTES")
        assert _fused_tree_enabled(63, 28, 256)


class TestDeviceScores:
    """The accelerator fast path keeps running scores on device in
    Kahan-compensated f32 — small updates must not vanish against a large
    base the way naive f32 accumulation loses them."""

    def test_kahan_preserves_small_updates(self):
        import jax.numpy as jnp

        score = jnp.full(4, 1.0e6, dtype=jnp.float32)
        comp = jnp.zeros(4, dtype=jnp.float32)
        naive = score
        vals = jnp.asarray(np.full(3, 0.01, dtype=np.float32))
        rows = jnp.zeros(4, dtype=jnp.int32)
        for _ in range(1000):
            score, comp = B._add_leaf_values(score, comp, vals, rows)
            naive = naive + vals[rows]
        want = 1.0e6 + 1000 * 0.01
        got = np.float64(score[0]) + np.float64(comp[0])
        assert abs(got - want) < 1e-3, got
        # the naive f32 sum demonstrably loses the updates (f32 eps@1e6 ~ 0.06)
        assert abs(float(naive[0]) - want) > 1.0

    def test_kahan_multiclass_column(self):
        import jax.numpy as jnp

        score = jnp.zeros((5, 3), dtype=jnp.float32)
        comp = jnp.zeros((5, 3), dtype=jnp.float32)
        vals = jnp.asarray(np.array([0.5, -0.25], dtype=np.float32))
        rows = jnp.asarray(np.array([0, 1, 1, 0, 1], dtype=np.int32))
        score, comp = B._add_leaf_values(score, comp, vals, rows, 2)
        got = np.asarray(score)
        np.testing.assert_allclose(got[:, 2], [0.5, -0.25, -0.25, 0.5, -0.25])
        assert np.all(got[:, :2] == 0)

    def test_fast_scores_train_matches_host(self, monkeypatch):
        """Force the fast path on CPU: predictions must match the f64 host
        accumulation within f32 tolerance."""
        X, y = synth_binary(400, seed=5)
        params = TrainParams(objective="binary", num_iterations=12,
                             num_leaves=15, min_data_in_leaf=5)
        b_host = B.train(params, X, y)
        monkeypatch.setattr("jax.default_backend", lambda: "tpu")
        monkeypatch.setenv("MMLSPARK_TPU_NO_PALLAS", "1")  # XLA hist on CPU
        b_fast = B.train(params, X, y)
        np.testing.assert_allclose(b_fast.raw_predict(X),
                                   b_host.raw_predict(X), rtol=1e-4, atol=1e-5)

    def test_fast_scores_with_validation(self, monkeypatch):
        """Early stopping reads valid-set metrics (host predict) — must work
        identically with device-resident train scores."""
        X, y = synth_binary(400, seed=6)
        params = TrainParams(objective="binary", num_iterations=30,
                             num_leaves=7, min_data_in_leaf=5,
                             early_stopping_round=3)
        b_host = B.train(params, X[:300], y[:300], valid=(X[300:], y[300:]))
        monkeypatch.setattr("jax.default_backend", lambda: "tpu")
        monkeypatch.setenv("MMLSPARK_TPU_NO_PALLAS", "1")  # XLA hist on CPU
        b_fast = B.train(params, X[:300], y[:300], valid=(X[300:], y[300:]))
        assert b_fast.best_iteration == b_host.best_iteration


class TestBooster:
    def test_binary_training_fits(self):
        X, y = synth_binary(600)
        params = TrainParams(objective="binary", num_iterations=30,
                             learning_rate=0.2, num_leaves=15, min_data_in_leaf=5)
        booster = B.train(params, X, y)
        p = booster.predict_proba(X)[:, 1]
        acc = np.mean((p > 0.5) == y)
        assert acc > 0.93, acc

    def test_regression_fits(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(500, 5))
        y = 3 * X[:, 0] + np.sin(3 * X[:, 1]) + 0.1 * rng.normal(size=500)
        params = TrainParams(objective="regression", num_iterations=50,
                             learning_rate=0.15, num_leaves=15, min_data_in_leaf=5)
        booster = B.train(params, X, y)
        pred = booster.raw_predict(X)
        r2 = 1 - np.var(pred - y) / np.var(y)
        assert r2 > 0.9, r2

    def test_multiclass_fits(self):
        rng = np.random.default_rng(0)
        n = 600
        X = rng.normal(size=(n, 4))
        y = (np.digitize(X[:, 0] + 0.3 * X[:, 1], [-0.5, 0.5])).astype(np.float64)
        params = TrainParams(objective="multiclass", num_class=3,
                             num_iterations=20, learning_rate=0.2,
                             num_leaves=7, min_data_in_leaf=5)
        booster = B.train(params, X, y)
        pred = np.argmax(booster.predict_proba(X), axis=1)
        assert np.mean(pred == y) > 0.9

    def test_early_stopping(self):
        X, y = synth_binary(400)
        Xv, yv = synth_binary(200, seed=9)
        params = TrainParams(objective="binary", num_iterations=200,
                             learning_rate=0.3, num_leaves=31,
                             min_data_in_leaf=2, early_stopping_round=5)
        booster = B.train(params, X, y, valid=(Xv, yv))
        assert booster.best_iteration > 0
        assert len(booster.trees) < 200

    def test_save_load_roundtrip(self):
        X, y = synth_binary(300)
        params = TrainParams(objective="binary", num_iterations=10,
                             num_leaves=7, min_data_in_leaf=5)
        booster = B.train(params, X, y)
        restored = Booster.from_string(booster.to_string())
        np.testing.assert_allclose(restored.raw_predict(X), booster.raw_predict(X),
                                   atol=1e-12)

    def test_merge(self):
        X, y = synth_binary(300)
        params = TrainParams(objective="binary", num_iterations=5,
                             num_leaves=7, min_data_in_leaf=5)
        b1 = B.train(params, X, y)
        b2 = B.train(params, X, y, init_model=b1)
        assert len(b2.trees) == 10
        merged = b1.merge(b1)
        assert len(merged.trees) == 10

    def test_shared_prefix_continuations_no_cache_collision(self, monkeypatch):
        # Two boosters continued from ONE init_model share their prefix
        # Tree objects, have equal length and equal shrinkages — the
        # forest memo must distinguish them by the identity of EVERY
        # tree, or the native predict path returns the other model's
        # scores (round-4 advisor finding).
        import os

        if os.environ.get("MMLSPARK_TPU_NO_NATIVE_PREDICT", "") not in ("", "0"):
            pytest.skip("native predict disabled in this environment")
        X, y = synth_binary(400, seed=0)
        X2, y2 = synth_binary(400, seed=7)
        params = TrainParams(objective="binary", num_iterations=5,
                             num_leaves=7, min_data_in_leaf=5)
        base = B.train(params, X, y)
        c1 = B.train(params, X, y, init_model=base)
        c2 = B.train(params, X2, y2, init_model=base)
        assert len(c1.trees) == len(c2.trees)
        r1 = c1.raw_predict(X)   # populates the forest memo for c1
        r2 = c2.raw_predict(X)   # must NOT hit c1's cache entry
        # both forests must cache simultaneously (distinct keys), not
        # mutually evict — alternating serving of the two models would
        # otherwise rebuild the SoA layout on every call
        from mmlspark_tpu.gbdt.predict import _FOREST_MEMO
        keys_before = set(_FOREST_MEMO)
        c1.raw_predict(X)
        c2.raw_predict(X)
        assert set(_FOREST_MEMO) == keys_before
        monkeypatch.setenv("MMLSPARK_TPU_NO_NATIVE_PREDICT", "1")
        ref1 = c1.raw_predict(X)
        ref2 = c2.raw_predict(X)
        np.testing.assert_allclose(r1, ref1, atol=1e-12)
        np.testing.assert_allclose(r2, ref2, atol=1e-12)
        assert np.abs(ref1 - ref2).max() > 0  # the two models DO differ

    @pytest.mark.parametrize("boosting", ["rf", "dart", "goss"])
    def test_boosting_variants_run(self, boosting):
        X, y = synth_binary(300)
        params = TrainParams(objective="binary", boosting_type=boosting,
                             num_iterations=8, num_leaves=7, min_data_in_leaf=5,
                             bagging_fraction=0.8, bagging_freq=1)
        booster = B.train(params, X, y)
        p = booster.predict_proba(X)[:, 1]
        assert np.mean((p > 0.5) == y) > 0.8

    def test_device_ensemble_matches_host(self):
        X, y = synth_binary(300)
        params = TrainParams(objective="binary", num_iterations=12,
                             num_leaves=15, min_data_in_leaf=5)
        booster = B.train(params, X, y)
        host = predict_ensemble(booster.trees, X, 1)
        dev = DeviceEnsemble(booster.trees, 1).predict_raw(X)
        np.testing.assert_allclose(dev, host, atol=1e-4)

    def test_feature_importance_identifies_signal(self):
        X, y = synth_binary(500)
        params = TrainParams(objective="binary", num_iterations=15,
                             num_leaves=15, min_data_in_leaf=5)
        booster = B.train(params, X, y)
        imp = booster.feature_importances("gain")
        assert imp[0] == imp.max()  # feature 0 dominates the synthetic logit


class TestStages:
    def test_classifier_stage(self):
        X, y = synth_binary(400)
        df = feature_df(X, y)
        clf = LightGBMClassifier(numIterations=20, numLeaves=15, minDataInLeaf=5,
                                 learningRate=0.2)
        model = clf.fit(df)
        out = model.transform(df)
        pred = out.column("prediction")
        assert np.mean(pred == y) > 0.9
        proba = out.column("probability")[0]
        assert proba.shape == (2,) and abs(proba.sum() - 1) < 1e-6

    def test_classifier_validation_early_stop(self):
        X, y = synth_binary(500)
        vmask = np.zeros(500, dtype=bool)
        vmask[400:] = True
        df = feature_df(X, y, extra={"isVal": vmask})
        clf = LightGBMClassifier(numIterations=100, numLeaves=31, minDataInLeaf=2,
                                 learningRate=0.3, earlyStoppingRound=5,
                                 validationIndicatorCol="isVal")
        model = clf.fit(df)
        assert len(model.booster.trees) < 100

    def test_regressor_stage(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(400, 5))
        y = 2 * X[:, 0] - X[:, 3] + 0.05 * rng.normal(size=400)
        df = feature_df(X, y)
        model = LightGBMRegressor(numIterations=40, numLeaves=15,
                                  minDataInLeaf=5, learningRate=0.15).fit(df)
        pred = model.transform(df).column("prediction")
        r2 = 1 - np.var(pred - y) / np.var(y)
        assert r2 > 0.85, r2

    def test_ranker_stage(self):
        rng = np.random.default_rng(0)
        n, n_groups = 300, 30
        X = rng.normal(size=(n, 4))
        groups = np.repeat(np.arange(n_groups), n // n_groups)
        rel = np.clip(np.round(X[:, 0] + 0.2 * rng.normal(size=n)) + 1, 0, 3)
        df = feature_df(X, rel, extra={"query": groups})
        model = LightGBMRanker(numIterations=15, numLeaves=7, minDataInLeaf=3,
                               groupCol="query").fit(df)
        scores = model.transform(df).column("prediction")
        # ranker should score high-relevance rows higher within groups
        corr = np.corrcoef(scores, rel)[0, 1]
        assert corr > 0.4, corr

    def test_save_native_model(self, tmp_path):
        X, y = synth_binary(200)
        df = feature_df(X, y)
        model = LightGBMClassifier(numIterations=5, numLeaves=7,
                                   minDataInLeaf=5).fit(df)
        p = str(tmp_path / "model.txt")
        model.save_native_model(p)
        # saveNativeModel emits the real LightGBM v3 text format
        # (LightGBMBooster.scala:96-148), not the internal JSON
        from mmlspark_tpu.gbdt.lgbm_format import from_lightgbm_string

        restored = from_lightgbm_string(open(p).read())
        np.testing.assert_allclose(restored.raw_predict(X),
                                   model.booster.raw_predict(X),
                                   rtol=1e-9, atol=1e-9)

    def test_stage_save_load(self, tmp_path):
        X, y = synth_binary(200)
        df = feature_df(X, y)
        model = LightGBMClassifier(numIterations=5, numLeaves=7,
                                   minDataInLeaf=5).fit(df)
        model.save(str(tmp_path / "m"))
        from mmlspark_tpu.core.pipeline import PipelineStage
        loaded = PipelineStage.load(str(tmp_path / "m"))
        np.testing.assert_allclose(
            np.asarray(loaded.transform(df).column("prediction"), dtype=float),
            np.asarray(model.transform(df).column("prediction"), dtype=float))

    def test_num_batches_incremental(self):
        X, y = synth_binary(400)
        df = feature_df(X, y)
        model = LightGBMClassifier(numIterations=5, numLeaves=7, minDataInLeaf=5,
                                   numBatches=2).fit(df)
        assert len(model.booster.trees) == 10  # 5 per batch, merged


class TestDistributed:
    """Data-parallel GBDT over the 8-device CPU mesh (socket-ring allreduce parity)."""

    def test_sharded_training_matches_single_device(self, mesh8):
        X, y = synth_binary(400)
        params = TrainParams(objective="binary", num_iterations=15,
                             learning_rate=0.2, num_leaves=15, min_data_in_leaf=5)
        b_single = B.train(params, X, y)
        b_mesh = B.train(params, X, y, mesh=mesh8)
        p1 = b_single.predict_proba(X)[:, 1]
        p2 = b_mesh.predict_proba(X)[:, 1]
        acc1 = np.mean((p1 > 0.5) == y)
        acc2 = np.mean((p2 > 0.5) == y)
        assert acc2 > 0.92, acc2
        assert abs(acc1 - acc2) < 0.03
        # histograms are psum'd exactly -> identical split structure
        assert len(b_single.trees) == len(b_mesh.trees)

    def test_sharded_training_with_padding(self, mesh8):
        # 403 rows: not divisible by 8 -> pad path
        X, y = synth_binary(403)
        params = TrainParams(objective="binary", num_iterations=8,
                             num_leaves=7, min_data_in_leaf=5)
        booster = B.train(params, X, y, mesh=mesh8)
        p = booster.predict_proba(X)[:, 1]
        assert np.mean((p > 0.5) == y) > 0.88

    def test_parallelism_param_parity(self, mesh8):
        """tree_learner parity: voting_parallel is accepted and runs the
        exact data-parallel algorithm (which strictly dominates the voting
        approximation); invalid values are rejected."""
        X, y = synth_binary(300)
        df = feature_df(X, y)
        model = LightGBMClassifier(numIterations=6, numLeaves=7,
                                   minDataInLeaf=5,
                                   parallelism="voting_parallel").fit(df)
        pred = model.transform(df).column("prediction")
        assert np.mean(pred == y) > 0.85
        assert model.booster.params.parallelism == "voting_parallel"
        # model-string round trip keeps it; old strings default cleanly
        b2 = Booster.from_string(model.booster.to_string())
        assert b2.params.parallelism == "voting_parallel"
        with pytest.raises(Exception):
            LightGBMClassifier(parallelism="tree_parallel")

    def test_max_bin_by_feature(self):
        X = np.random.default_rng(0).normal(size=(3000, 3))
        m = BinMapper.fit(X, max_bin=64, max_bin_by_feature=[8, 128, 16])
        assert m.num_bins(0) <= 9    # 8 value bins + missing
        assert m.num_bins(2) <= 17
        assert m.num_bins(1) > 65    # overrides max_bin UPWARD too

    def test_max_delta_step_clamps_leaves(self, monkeypatch):
        X, y = synth_binary(300)
        y = y * 100.0  # large targets -> large unclamped leaf values
        for env in ("1", "0"):  # host-orchestrated and fused paths
            monkeypatch.setenv("MMLSPARK_TPU_NO_FUSED_TREE", env)
            if env == "0":
                monkeypatch.setenv("MMLSPARK_TPU_FUSED_TREE", "1")
            b = B.train(TrainParams(objective="regression", num_iterations=3,
                                    num_leaves=7, min_data_in_leaf=5,
                                    max_delta_step=0.1), X, y)
            for grp in b.trees:
                for t in grp:
                    leaves = t.value[t.feature == -1]
                    assert np.all(np.abs(leaves) <= 0.1 + 1e-9)

    def test_class_aware_bagging(self):
        X, y = synth_binary(400)
        params = TrainParams(objective="binary", num_iterations=8,
                             num_leaves=7, min_data_in_leaf=5,
                             bagging_freq=1, pos_bagging_fraction=0.9,
                             neg_bagging_fraction=0.3)
        b = B.train(params, X, y)
        p = b.predict_proba(X)[:, 1]
        assert np.mean((p > 0.5) == y) > 0.85

    def test_metric_param_early_stopping(self):
        X, y = synth_binary(400)
        params = TrainParams(objective="binary", num_iterations=40,
                             num_leaves=7, min_data_in_leaf=5, metric="auc",
                             early_stopping_round=5)
        b = B.train(params, X[:300], y[:300], valid=(X[300:], y[300:]))
        assert b.best_iteration > 0  # auc is higher-better; stopping worked

    def test_is_provide_training_metric_logs_with_validation(self, caplog):
        """The training metric must be logged even when a validation split
        exists (it used to be unreachable in the early-stopping setup)."""
        import logging

        X, y = synth_binary(300)
        df = feature_df(X, y, extra={"isVal": np.arange(300) >= 240})
        with caplog.at_level(logging.INFO, logger="mmlspark_tpu.gbdt"):
            LightGBMClassifier(numIterations=5, numLeaves=7, minDataInLeaf=5,
                               validationIndicatorCol="isVal",
                               isProvideTrainingMetric=True).fit(df)
        msgs = [r.message for r in caplog.records]
        assert any("train binary_logloss" in m for m in msgs), msgs
        assert any("valid binary_logloss" in m for m in msgs), msgs

    def test_categorical_slot_names_via_metadata(self):
        from mmlspark_tpu.featurize import AssembleFeatures

        rng = np.random.default_rng(3)
        n = 300
        cat = rng.integers(0, 4, n).astype(float)
        num = rng.normal(size=n)
        y = ((cat >= 2).astype(float) + 0.1 * num > 0.5).astype(float)
        df = DataFrame.from_dict({"cat": cat, "num": num, "label": y},
                                 num_partitions=2)
        feats = AssembleFeatures(inputCols=["cat", "num"],
                                 outputCol="features").fit(df).transform(df)
        assert feats.schema.metadata["features"]["slot_names"] == \
            ["cat", "num"]
        model = LightGBMClassifier(numIterations=8, numLeaves=7,
                                   minDataInLeaf=5,
                                   categoricalSlotNames=["cat"]).fit(feats)
        assert 0 in model.booster.params.categorical_feature
        assert np.mean(model.transform(feats).column("prediction") == y) > 0.9
        with pytest.raises(KeyError, match="nope"):
            LightGBMClassifier(numIterations=2,
                               categoricalSlotNames=["nope"]).fit(feats)

    def test_stage_uses_default_mesh(self, mesh8):
        from mmlspark_tpu.parallel.mesh import MeshContext
        MeshContext.set(mesh8)
        try:
            X, y = synth_binary(300)
            df = feature_df(X, y)
            model = LightGBMClassifier(numIterations=8, numLeaves=7,
                                       minDataInLeaf=5).fit(df)
            pred = model.transform(df).column("prediction")
            assert np.mean(pred == y) > 0.85
        finally:
            MeshContext.reset()


class TestReviewRegressions:
    def test_quantile_init_score_uses_alpha(self):
        """init_score for the quantile objective must start at the CONFIGURED
        quantile — it was hardcoded to 0.9, so low-alpha fits started at the
        90th percentile and barely converged."""
        rng = np.random.default_rng(0)
        y = rng.standard_normal(500)
        lo = B.init_score("quantile", y, alpha=0.2)[0]
        hi = B.init_score("quantile", y, alpha=0.8)[0]
        assert lo == pytest.approx(np.quantile(y, 0.2))
        assert hi == pytest.approx(np.quantile(y, 0.8))
        # end-to-end: empirical coverage brackets the requested quantiles
        X = rng.normal(size=(300, 4))
        y = X @ rng.normal(size=4) + rng.standard_t(df=3, size=300)
        cov = {}
        for alpha in (0.2, 0.8):
            params = TrainParams(objective="quantile", alpha=alpha,
                                 num_iterations=30, learning_rate=0.1,
                                 num_leaves=15, min_data_in_leaf=10)
            booster = B.train(params, X, y)
            cov[alpha] = float(np.mean(y < booster.raw_predict(X)))
        assert 0.05 < cov[0.2] < 0.45, cov
        assert 0.55 < cov[0.8] < 0.95, cov
        assert cov[0.2] < cov[0.8]

    def test_categorical_feature_end_to_end(self):
        rng = np.random.default_rng(0)
        n = 400
        cat = rng.integers(0, 6, size=n).astype(np.float64)
        noise = rng.normal(size=(n, 2))
        X = np.column_stack([cat, noise])
        y = np.where(np.isin(cat, [1, 3, 5]), 2.0, -1.0)  # value-dependent target
        params = TrainParams(objective="regression", num_iterations=30,
                             learning_rate=0.3, num_leaves=15, min_data_in_leaf=5,
                             categorical_feature=(0,))
        booster = B.train(params, X, y)
        mse = np.mean((booster.raw_predict(X) - y) ** 2)
        assert mse < 0.05, mse  # was ~0.3 (predicting the mean) before the fix

    def test_ranker_with_validation_indicator(self):
        rng = np.random.default_rng(0)
        n, n_groups = 200, 20
        X = rng.normal(size=(n, 4))
        groups = np.repeat(np.arange(n_groups), n // n_groups)
        rel = np.clip(np.round(X[:, 0]) + 1, 0, 3)
        vmask = groups >= 15
        df = feature_df(X, rel, extra={"query": groups, "isVal": vmask})
        model = LightGBMRanker(numIterations=10, numLeaves=7, minDataInLeaf=3,
                               groupCol="query", earlyStoppingRound=3,
                               validationIndicatorCol="isVal").fit(df)
        assert model.booster.num_total_model > 0  # no IndexError crash

    def test_init_score_col(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(300, 4))
        y = 2 * X[:, 0] + 100.0  # large offset carried by init score
        init = np.full(300, 100.0)
        df = feature_df(X, y, extra={"init": init})
        model = LightGBMRegressor(numIterations=20, numLeaves=7, minDataInLeaf=5,
                                  learningRate=0.3, initScoreCol="init").fit(df)
        # model itself learns only the residual; add init back externally
        pred = model.transform(df).column("prediction") + init
        assert np.mean((pred - y) ** 2) < 1.0

    def test_continued_training_smaller_max_bin(self):
        X, y = synth_binary(300)
        p1 = TrainParams(objective="binary", num_iterations=5, num_leaves=7,
                         min_data_in_leaf=5, max_bin=255)
        b1 = B.train(p1, X, y)
        p2 = TrainParams(objective="binary", num_iterations=5, num_leaves=7,
                         min_data_in_leaf=5, max_bin=16)  # inherits b1's mapper
        b2 = B.train(p2, X, y, init_model=b1)
        p = b2.predict_proba(X)[:, 1]
        assert np.mean((p > 0.5) == y) > 0.9  # histograms not corrupted


class TestLambdaRankInternals:
    def _gh(self, scores, labels, groups):
        import jax.numpy as jnp
        return B._lambdarank_grad_hess(
            jnp.asarray(scores, dtype=jnp.float32),
            jnp.asarray(labels, dtype=jnp.float32), groups)

    def test_noncontiguous_groups_raise(self):
        with pytest.raises(ValueError, match="contiguous"):
            B.segment_groups(np.array([0, 1, 0, 1]))

    def test_skewed_group_sizes_bucketed(self):
        """Many singletons + one large group: buckets keep padding local, and
        singleton rows get zero gradient (no pairs)."""
        rng = np.random.default_rng(1)
        sizes = [1] * 50 + [64]
        groups = np.repeat(np.arange(len(sizes)), sizes)
        n = len(groups)
        scores = rng.normal(size=n)
        labels = rng.integers(0, 3, size=n).astype(np.float64)
        g, h = self._gh(scores, labels, groups)
        g, h = np.asarray(g), np.asarray(h)
        assert g.shape == (n,) and h.shape == (n,)
        np.testing.assert_array_equal(g[:50], 0.0)   # singletons: no pairs
        assert np.abs(g[50:]).sum() > 0              # big group: real lambdas
        seg = B.segment_groups(groups)
        assert sorted(gb for gb, *_ in seg.buckets) == [1, 64]

    def test_chunked_matches_unchunked(self, monkeypatch):
        """Shrinking the pair budget (forcing lax.map chunking) must not
        change the lambdas."""
        rng = np.random.default_rng(2)
        n_groups, gsize = 12, 8
        groups = np.repeat(np.arange(n_groups), gsize)
        n = len(groups)
        scores = rng.normal(size=n)
        labels = rng.integers(0, 4, size=n).astype(np.float64)
        g1, h1 = self._gh(scores, labels, groups)
        monkeypatch.setattr(B, "_LAMBDARANK_PAIR_BUDGET", 2 * gsize * gsize)
        g2, h2 = self._gh(scores, labels, groups)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-6)
        np.testing.assert_allclose(np.asarray(h1), np.asarray(h2), atol=1e-6)


class TestFusedSplitStep:
    """The one-dispatch split iteration must be semantically identical to the
    multi-call sequence it replaced (partition -> histogram -> subtraction ->
    two split evals)."""

    def test_fused_equals_multicall(self):
        import jax
        import jax.numpy as jnp

        from mmlspark_tpu.gbdt import histogram as H

        rng = np.random.default_rng(0)
        n, f, num_bins = 500, 6, 16
        bins = fm(rng.integers(0, num_bins, size=(n, f)))
        grad = jnp.asarray(rng.normal(size=n).astype(np.float32))
        hess = jnp.asarray(np.ones(n, dtype=np.float32))
        row_mask = jnp.asarray(rng.random(n) < 0.9)
        node_of_row = jnp.zeros(n, dtype=jnp.int32)

        parent_hist = H.compute_histogram(bins, grad, hess, row_mask, num_bins)
        s = jax.device_get(H.find_best_split(parent_hist, 0.0, 1.0, 1e-3, 5))
        fsel, t, dleft = int(s.feature), int(s.bin), bool(s.default_left)
        lid, rid = 1, 2
        small_id = lid if float(s.left_sum[2]) <= float(s.right_sum[2]) else rid

        # multi-call reference
        nor_ref = H.partition_rows(bins[fsel], node_of_row, np.int32(0),
                                   np.int32(t), dleft, np.int32(lid),
                                   np.int32(rid))
        small_mask = row_mask & (nor_ref == small_id)
        small_ref = H.compute_histogram(bins, grad, hess, small_mask, num_bins)
        big_ref = H.subtract_histogram(parent_hist, small_ref)
        ss_ref = jax.device_get(H.find_best_split(small_ref, 0.0, 1.0, 1e-3, 5))
        sb_ref = jax.device_get(H.find_best_split(big_ref, 0.0, 1.0, 1e-3, 5))

        # fused
        nor, small, big, ss, sb = H.fused_split_step(
            bins, grad, hess, row_mask, node_of_row, parent_hist,
            np.int32(fsel), np.int32(t), dleft, np.int32(0),
            np.int32(lid), np.int32(rid), np.int32(small_id),
            0.0, 1.0, 1e-3, np.zeros(0, dtype=bool),
            num_bins=num_bins, min_data_in_leaf=5, use_mxu=False,
            has_feature_mask=False)
        ss, sb = jax.device_get((ss, sb))

        np.testing.assert_array_equal(np.asarray(nor), np.asarray(nor_ref))
        np.testing.assert_allclose(np.asarray(small), np.asarray(small_ref),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(big), np.asarray(big_ref),
                                   atol=1e-5)
        for got, want in ((ss, ss_ref), (sb, sb_ref)):
            assert int(got.feature) == int(want.feature)
            assert int(got.bin) == int(want.bin)
            np.testing.assert_allclose(float(got.gain), float(want.gain),
                                       rtol=1e-5)
            np.testing.assert_allclose(np.asarray(got.left_sum),
                                       np.asarray(want.left_sum), atol=1e-4)

    def test_feature_mask_respected_in_fused_step(self):
        import jax
        import jax.numpy as jnp

        from mmlspark_tpu.gbdt import histogram as H

        rng = np.random.default_rng(1)
        n, f, num_bins = 300, 4, 8
        bins = fm(rng.integers(0, num_bins, size=(n, f)))
        grad = jnp.asarray(rng.normal(size=n).astype(np.float32))
        hess = jnp.asarray(np.ones(n, dtype=np.float32))
        row_mask = jnp.ones(n, dtype=bool)
        nor = jnp.zeros(n, dtype=jnp.int32)
        parent = H.compute_histogram(bins, grad, hess, row_mask, num_bins)
        mask = np.array([True, False, False, False])
        _, _, _, ss, sb = H.fused_split_step(
            bins, grad, hess, row_mask, nor, parent,
            np.int32(0), np.int32(3), True, np.int32(0),
            np.int32(1), np.int32(2), np.int32(1),
            0.0, 1.0, 1e-3, mask,
            num_bins=num_bins, min_data_in_leaf=2, use_mxu=False,
            has_feature_mask=True)
        ss, sb = jax.device_get((ss, sb))
        assert int(ss.feature) == 0 and int(sb.feature) == 0  # only unmasked


class TestNativeDensePredict:
    def test_native_matches_numpy_path(self, monkeypatch):
        """The C++ f64 SoA traversal is bit-equal to the per-tree numpy
        loop, including NaN default-direction routing and multiclass
        columns."""
        from mmlspark_tpu import native_loader

        if not native_loader.available():
            pytest.skip("native toolchain unavailable")
        rng = np.random.default_rng(5)
        X = rng.normal(size=(400, 8))
        y = np.digitize(X[:, 0] + X[:, 1], [-0.5, 0.5]).astype(np.float64)
        X[rng.random(X.shape) < 0.1] = np.nan   # exercise default_left
        params = TrainParams(objective="multiclass", num_class=3,
                             num_iterations=5, num_leaves=7,
                             min_data_in_leaf=5, seed=0)
        b = B.train(params, X, y)
        monkeypatch.setenv("MMLSPARK_TPU_NO_NATIVE_PREDICT", "1")
        ref = b.raw_predict(X)
        monkeypatch.delenv("MMLSPARK_TPU_NO_NATIVE_PREDICT")
        fast = b.raw_predict(X)
        np.testing.assert_array_equal(fast, ref)

    def test_dart_shrinkage_rescale_invalidates_cache(self, monkeypatch):
        """Dart rescales tree shrinkage in place between predicts; the
        padded-forest cache must not serve stale values."""
        from mmlspark_tpu import native_loader
        from mmlspark_tpu.gbdt.predict import predict_ensemble

        if not native_loader.available():
            pytest.skip("native toolchain unavailable")
        X, y = synth_binary(300, seed=3)
        params = TrainParams(objective="binary", num_iterations=3,
                             num_leaves=7, min_data_in_leaf=5)
        b = B.train(params, X, y)
        p1 = predict_ensemble(b.trees, X, 1)
        for g in b.trees:
            for t in g:
                t.shrinkage = t.shrinkage * 0.5
        p2 = predict_ensemble(b.trees, X, 1)
        np.testing.assert_allclose(p2, p1 * 0.5, rtol=1e-12)
