"""Single-copy ingress-to-device: deposit staging, spanning views, mega-K.

Covers the three coordinated pieces of the slot-staging path:

  - ``deposit_frame`` / ``decode_frame(out=...)``: wire payloads land in
    caller-provided staging buffers; hostile frames (truncated, misaligned
    dtype/shape, read-only or non-contiguous destinations) raise
    ``FrameError`` BEFORE any slot byte is written.
  - ``rows_to_batch``: the strided-view fast path across rows of ONE frame
    and across rows spanning MULTIPLE pipelined frames of one connection
    buffer; zero-copy vs copied batches are counted in ``IngestStats``.
  - slot deposit through the fused executor: bitwise parity against the
    allocating path across wire x fused x async-exec modes, and the
    deposits/copies counters that make "exactly one host copy" auditable.
  - AOT mega-dispatch: K>1 parity, K=1 uncalibrated bitwise identity, the
    Tuner's journaled/rollback-able K knob, and the serving watchdog's
    K-scaled budget.
"""

import json
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.fusion import CompileCache, FusedPipelineModel
from mmlspark_tpu.core.pipeline import PipelineModel
from mmlspark_tpu.core.schema import ImageSchema
from mmlspark_tpu.io.binary import (FRAME_CONTENT_TYPE, FrameError,
                                    decode_frame, deposit_frame,
                                    encode_frame)
from mmlspark_tpu.parallel.ingest import IngestStats, SlotPool, rows_to_batch

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def _post(address, body, headers=None, timeout=15):
    req = urllib.request.Request(address, data=body, method="POST",
                                 headers=dict(headers or {}))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _image_chain():
    """The flagship image chain (ImageTransformer -> tiny CNN featurizer)."""
    from mmlspark_tpu.image.featurizer import ImageFeaturizer
    from mmlspark_tpu.image.stages import ImageTransformer
    from mmlspark_tpu.models.module import (Dense, FunctionModel,
                                            GlobalAvgPool, Sequential)

    size = 12
    mod = Sequential([("pool", GlobalAvgPool()), ("head", Dense(3))],
                     name="tinycnn")
    params, _ = mod.init(jax.random.PRNGKey(0), (size, size, 3))
    backbone = FunctionModel(mod, params, (size, size, 3),
                             layer_names=["head", "pool"], name="tinycnn")
    return PipelineModel([
        ImageTransformer().resize(size, size).flip(1),
        ImageFeaturizer(scaleFactor=1 / 255., batchSize=16)
        .set_model(backbone)])


def _image_df(rows=22, parts=2, seed=0):
    rng = np.random.default_rng(seed)
    obj = np.empty(rows, dtype=object)
    for i in range(rows):
        obj[i] = ImageSchema.make(
            rng.integers(0, 256, (16, 16, 3), dtype=np.uint8), f"img{i}")
    return DataFrame.from_dict({"image": obj}, num_partitions=parts)


def _feature_matrix(df_out):
    pdf = df_out.to_pandas()
    col = next(c for c in pdf.columns if c != "image")
    return np.stack([np.asarray(v) for v in pdf[col].to_list()])


# ---------------------------------------------------------------------------
# deposit_frame: the socket-to-slot primitive
# ---------------------------------------------------------------------------


class TestDepositFrame:
    COLS = {"img": np.arange(2 * 4 * 4 * 3, dtype=np.uint8)
            .reshape(2, 4, 4, 3),
            "y": np.array([1.5, -2.0], dtype=np.float32)}

    def _slots(self):
        return {"img": np.zeros((2, 4, 4, 3), np.uint8),
                "y": np.zeros((2,), np.float32)}

    def test_deposit_bitwise_matches_decode(self):
        buf = encode_frame(self.COLS)
        out = self._slots()
        got = deposit_frame(buf, out)
        dec = decode_frame(buf)
        for name in self.COLS:
            np.testing.assert_array_equal(got[name], dec[name])
            assert got[name] is out[name]  # landed in MY buffer

    def test_decode_frame_out_kwarg_delegates(self):
        buf = encode_frame(self.COLS)
        out = self._slots()
        got = decode_frame(buf, out=out)
        np.testing.assert_array_equal(got["img"], self.COLS["img"])

    @pytest.mark.parametrize("mutate", [
        lambda b: b[: len(b) // 2],            # truncated payload
        lambda b: b"XXXX" + b[4:],             # bad magic
        lambda b: b[:-1],                      # short by one byte
    ])
    def test_hostile_frames_raise_before_any_slot_write(self, mutate):
        buf = encode_frame(self.COLS)
        out = self._slots()
        for a in out.values():
            a.fill(7)  # sentinel: any write would disturb it
        before = {k: v.copy() for k, v in out.items()}
        with pytest.raises(FrameError):
            deposit_frame(bytes(mutate(bytearray(buf))), out)
        for k in out:
            np.testing.assert_array_equal(out[k], before[k])

    @pytest.mark.parametrize("bad", [
        {"img": "wrong_dtype"}, {"img": "wrong_shape"},
        {"img": "readonly"}, {"img": "noncontig"}, {"img": "missing"},
    ])
    def test_bad_destinations_raise_before_any_slot_write(self, bad):
        buf = encode_frame(self.COLS)
        out = self._slots()
        kind = bad["img"]
        if kind == "wrong_dtype":
            out["img"] = np.zeros((2, 4, 4, 3), np.float32)
        elif kind == "wrong_shape":
            out["img"] = np.zeros((2, 4, 4), np.uint8)
        elif kind == "readonly":
            ro = np.zeros((2, 4, 4, 3), np.uint8)
            ro.setflags(write=False)
            out["img"] = ro
        elif kind == "noncontig":
            out["img"] = np.zeros((2, 4, 4, 6), np.uint8)[..., ::2]
        elif kind == "missing":
            del out["img"]
        out["y"].fill(9)
        before_y = out["y"].copy()
        with pytest.raises(FrameError):
            deposit_frame(buf, out)
        # the OTHER column's slot is untouched: validation is all-or-nothing
        np.testing.assert_array_equal(out["y"], before_y)


# ---------------------------------------------------------------------------
# rows_to_batch: spanning views and the slot-fill mode
# ---------------------------------------------------------------------------


class TestRowsToBatchSpanning:
    def test_rows_of_one_frame_stay_zero_copy(self):
        batch = np.arange(3 * 8 * 8, dtype=np.uint8).reshape(3, 8, 8)
        rows = list(decode_frame(encode_frame({"x": batch}))["x"])
        st = IngestStats()
        out = rows_to_batch(rows, stats=st)
        np.testing.assert_array_equal(out, batch)
        assert out.base is not None  # a view, not a copy
        assert st.zero_copy_batches == 1 and st.copied_batches == 0

    def test_rows_spanning_pipelined_frames_share_one_view(self):
        """Pipelined requests on one connection land back-to-back in one
        recv buffer; equal-shape single-row frames decode to views at a
        CONSTANT stride (the frame length) over the same base — the
        spanning fast path stitches them without a copy."""
        rng = np.random.default_rng(3)
        imgs = [rng.integers(0, 256, (8, 8, 3), dtype=np.uint8)
                for _ in range(4)]
        frames = [encode_frame({"img": im}) for im in imgs]
        flen = len(frames[0])
        assert all(len(f) == flen for f in frames)
        wire = b"".join(frames)  # one connection buffer
        rows = [decode_frame(wire[i * flen:(i + 1) * flen])["img"]
                for i in range(len(frames))]
        # slicing a bytes keeps the copies rooted per-slice; use a
        # memoryview so every row's base chain ends at the SAME buffer
        mv = memoryview(wire)
        rows = [decode_frame(mv[i * flen:(i + 1) * flen])["img"]
                for i in range(len(frames))]
        st = IngestStats()
        out = rows_to_batch(rows, stats=st)
        np.testing.assert_array_equal(out, np.stack(imgs))
        assert out.base is not None, "spanning view expected, got a copy"
        assert st.zero_copy_batches == 1

    def test_rows_from_unrelated_buffers_are_copied_and_counted(self):
        rng = np.random.default_rng(4)
        imgs = [rng.integers(0, 256, (8, 8, 3), dtype=np.uint8)
                for _ in range(3)]
        rows = [decode_frame(encode_frame({"img": im}))["img"]
                for im in imgs]  # three separate wire buffers
        st = IngestStats()
        out = rows_to_batch(rows, stats=st)
        np.testing.assert_array_equal(out, np.stack(imgs))
        assert st.copied_batches == 1 and st.zero_copy_batches == 0

    def test_out_mode_fills_slot_without_allocation(self):
        rng = np.random.default_rng(5)
        rows = [rng.integers(0, 256, (6, 6), dtype=np.uint8)
                for _ in range(3)]
        slot = np.zeros((8, 6, 6), np.uint8)
        st = IngestStats()
        got = rows_to_batch(rows, out=slot, stats=st)
        assert got.base is slot or got is slot
        np.testing.assert_array_equal(got, np.stack(rows))
        assert st.copied_batches == 1  # the one accounted host copy

    def test_out_mode_validates_shape_and_dtype(self):
        rows = [np.zeros((4, 4), np.uint8)] * 2
        with pytest.raises(ValueError):
            rows_to_batch(rows, out=np.zeros((8, 4, 4), np.float32))
        with pytest.raises(ValueError):
            rows_to_batch(rows, out=np.zeros((1, 4, 4), np.uint8))


class TestSlotPool:
    def test_acquire_release_cycle_and_stats(self):
        pool = SlotPool(buffers_per_bucket=2)
        spec = {"x": ((8, 4), np.float32)}
        a = pool.acquire(spec)
        b = pool.acquire(spec)
        assert a is not None and b is not None
        # both buffers leased: the next acquire times out to the fallback
        assert pool.acquire(spec, timeout=0.05) is None
        a.release()
        c = pool.acquire(spec, timeout=1.0)
        assert c is not None
        b.release()
        c.release()
        assert pool.stats()["buckets"] == 1

    def test_oversized_spec_falls_back(self):
        pool = SlotPool(max_slot_bytes=64)
        assert pool.acquire({"x": ((1024, 1024), np.float32)}) is None

    def test_overlap_accounting_records_fill_transfer_intersection(self):
        pool = SlotPool()
        st = IngestStats()
        lease = pool.acquire({"x": ((4, 4), np.float32)}, stats=st)
        lease.fill_begin()
        lease.fill_end()
        lease.transfer_begin()
        lease.transfer_end()
        s = st.summary()
        assert s["slot_fill_s"] >= 0 and s["slot_transfer_s"] >= 0
        assert 0.0 <= s["slot_overlap_ratio"] <= 1.0

    def test_overlap_counts_only_own_bucket_fills(self):
        """A transfer's overlap is measured against ITS bucket's sibling
        fills — fills from unrelated leases elsewhere in the shared pool
        must not inflate slot_overlap_ratio."""
        pool = SlotPool()
        a = pool.acquire({"x": ((4, 4), np.float32)})
        b = pool.acquire({"y": ((4, 4), np.float32)})
        pool._note_fill(a._held, (1.0, 2.0))
        assert pool._overlap(b._held, 0.0, 10.0) == 0.0
        assert pool._overlap(a._held, 0.0, 10.0) == pytest.approx(1.0)
        a.release()
        b.release()

    def test_abandoned_lease_is_finalized_back_to_pool(self):
        """A lease dropped without release() (any abort path the explicit
        cleanup misses) returns its buffers via the weakref finalizer —
        the never-replenished pool must not shrink permanently."""
        import gc

        pool = SlotPool(buffers_per_bucket=1)
        spec = {"x": ((4, 4), np.float32)}
        lease = pool.acquire(spec)
        assert lease is not None
        del lease
        gc.collect()
        again = pool.acquire(spec, timeout=0.5)
        assert again is not None
        again.release()

    def test_total_bytes_cap_evicts_lru_free_buckets(self):
        # bucket A: 2 x 64B; bucket B: 2 x 128B — together over the cap,
        # so inserting B evicts the fully-free A instead of growing
        pool = SlotPool(buffers_per_bucket=2, max_total_bytes=300)
        a = pool.acquire({"x": ((4, 4), np.float32)})
        a.release()
        b = pool.acquire({"x": ((8, 4), np.float32)})
        assert b is not None
        s = pool.stats()
        assert s["buckets"] == 1 and s["bytes"] == 256
        assert s["evictions"] == 1
        b.release()

    def test_leased_buckets_are_never_evicted(self):
        """When in-use buckets pin the pool at the byte cap, a new shape
        falls back to the copy path (None) instead of yanking live
        buffers or growing without bound."""
        pool = SlotPool(buffers_per_bucket=2, max_total_bytes=300)
        a = pool.acquire({"x": ((4, 4), np.float32)})
        assert pool.acquire({"x": ((8, 4), np.float32)},
                            timeout=0.05) is None
        s = pool.stats()
        assert s["buckets"] == 1 and s["evictions"] == 0
        a.release()

    def test_multi_column_spec_over_cap_falls_back(self):
        """A spec whose buckets jointly exceed the cap returns None (copy
        fallback) instead of evicting its own sibling buckets in a
        build/evict livelock."""
        pool = SlotPool(buffers_per_bucket=2, max_total_bytes=300)
        spec = {"x": ((4, 4), np.float32),   # 128B
                "y": ((8, 4), np.float32)}   # 256B -> jointly over cap
        assert pool.acquire(spec, timeout=0.2) is None


class TestLeaseReleaseOnAbort:
    def test_prefetcher_close_releases_queued_leases(self):
        """DevicePrefetcher.close() must hand queued batches' SlotPool
        leases back: an early abort (fault, fallback, watchdog kill) that
        drops queued items otherwise removes buffers from the shared pool
        forever, and every later acquire for that shape eats the full
        acquire timeout before falling back."""
        import time

        from mmlspark_tpu.parallel.batching import Batch, DevicePrefetcher

        pool = SlotPool(buffers_per_bucket=2)
        spec = {"x": ((4, 4), np.float32)}

        def batches():
            while True:
                lease = pool.acquire(spec, timeout=1.0)
                if lease is None:
                    return
                yield Batch({"x": lease.arrays["x"]},
                            np.ones(4, dtype=bool), 4, staging=lease)

        pf = DevicePrefetcher(batches(), depth=2)
        deadline = time.monotonic() + 2.0
        while pf._q.qsize() < 2 and time.monotonic() < deadline:
            time.sleep(0.01)  # let the producer queue both leased batches
        pf.close()
        pf._thread.join(timeout=5.0)
        assert not pf._thread.is_alive()
        a = pool.acquire(spec, timeout=1.0)
        b = pool.acquire(spec, timeout=1.0)
        assert a is not None and b is not None  # nothing leaked
        a.release()
        b.release()


# ---------------------------------------------------------------------------
# Deposit path through the fused executor: parity + counters
# ---------------------------------------------------------------------------


class TestFusedDepositParity:
    def test_transform_bitwise_parity_and_counters(self):
        pm = _image_chain()
        df = _image_df()
        copy = FusedPipelineModel(pm.stages, cache=CompileCache(),
                                  slot_staging=False)
        dep = FusedPipelineModel(pm.stages, cache=CompileCache())
        ref = _feature_matrix(copy.transform(df))
        got = _feature_matrix(dep.transform(df))
        np.testing.assert_array_equal(got, ref)
        s_copy = copy.last_ingest_stats.summary()
        s_dep = dep.last_ingest_stats.summary()
        assert "slot_deposits" not in s_copy
        assert s_dep["slot_deposits"] > 0
        assert s_dep.get("fallback_copies", 0) == 0

    def test_async_submit_bitwise_parity(self):
        pm = _image_chain()
        df = _image_df(rows=20, parts=1, seed=2)
        copy = FusedPipelineModel(pm.stages, cache=CompileCache(),
                                  slot_staging=False)
        dep = FusedPipelineModel(pm.stages, cache=CompileCache())
        ref = _feature_matrix(copy.transform_submit(df)())
        got = _feature_matrix(dep.transform_submit(df)())
        np.testing.assert_array_equal(got, ref)
        assert dep.last_ingest_stats.summary()["slot_deposits"] > 0

    def test_slot_contention_falls_back_with_accounted_copy(self):
        pm = _image_chain()
        df = _image_df(rows=10, parts=1, seed=3)
        dep = FusedPipelineModel(pm.stages, cache=CompileCache())
        _ = dep.transform(df)  # warm the pool with THIS df's buckets
        pool = dep._get_slot_pool()
        # lease every buffer of every bucket so the transform's acquire
        # must time out into the accounted copy fallback
        held = []
        specs = [{key[0]: (key[1], np.dtype(key[2]))}
                 for key in list(pool._buckets)]
        for spec in specs:
            while True:
                lease = pool.acquire(spec, timeout=0.01)
                if lease is None:
                    break
                held.append(lease)
        pool._timeout = 0.01  # keep the fallback fast under test
        ref = _feature_matrix(
            FusedPipelineModel(pm.stages, cache=CompileCache(),
                               slot_staging=False).transform(df))
        got = _feature_matrix(dep.transform(df))
        np.testing.assert_array_equal(got, ref)
        s = dep.last_ingest_stats.summary()
        assert s.get("fallback_copies", 0) > 0  # accounted, not silent
        for lease in held:
            lease.release()


# ---------------------------------------------------------------------------
# AOT mega-dispatch
# ---------------------------------------------------------------------------


class TestMegaDispatch:
    def _label(self, fused):
        _ = fused.transform(_image_df(rows=4, parts=1))
        return next(iter(fused.fusion_stats()["per_segment"]))

    @pytest.mark.parametrize("k", [2, 3])
    def test_k_step_parity(self, k):
        pm = _image_chain()
        df = _image_df(rows=48, parts=1, seed=1)
        base = FusedPipelineModel(pm.stages, cache=CompileCache())
        ref = _feature_matrix(base.transform_submit(df)())
        mega = FusedPipelineModel(pm.stages, cache=CompileCache())
        label = self._label(mega)
        mega.set_tuning(mega_k={label: k})
        assert mega.mega_k_max == k
        got = _feature_matrix(mega.transform_submit(df)())
        np.testing.assert_array_equal(got, ref)

    def test_k1_uncalibrated_is_bitwise_identical(self):
        """K=1 + no deposit-eligible frames == the pre-slot-staging path:
        same bytes out, batch for batch."""
        pm = _image_chain()
        df = _image_df(rows=22, parts=2, seed=0)
        plain = FusedPipelineModel(pm.stages, cache=CompileCache(),
                                   slot_staging=False)
        ref = _feature_matrix(plain.transform_submit(df)())
        again = _feature_matrix(
            FusedPipelineModel(pm.stages, cache=CompileCache(),
                               slot_staging=False).transform_submit(df)())
        np.testing.assert_array_equal(ref, again)
        assert plain.mega_k_max == 1
        assert "tuning" not in plain.fusion_stats()

    def test_partial_group_dispatches_singly(self):
        """Row count chosen so the last group is SHORTER than K: the
        leftover batches ride the normal per-batch step and outputs still
        match."""
        pm = _image_chain()
        df = _image_df(rows=42, parts=1, seed=6)  # 3 batches of 16: 2+1
        base = FusedPipelineModel(pm.stages, cache=CompileCache())
        ref = _feature_matrix(base.transform_submit(df)())
        mega = FusedPipelineModel(pm.stages, cache=CompileCache())
        label = self._label(mega)
        mega.set_tuning(mega_k={label: 2})
        got = _feature_matrix(mega.transform_submit(df)())
        np.testing.assert_array_equal(got, ref)

    def test_mega_stages_in_sliding_groups_of_k(self):
        """The K>1 submit path must NOT stage the whole partition before
        dispatching (unbounded device memory): groups of K stage, dispatch,
        and drop — at the first mega dispatch only K items may have been
        pulled from the staging iterator."""
        from mmlspark_tpu.core.fusion import SegmentExecutor
        from mmlspark_tpu.parallel.ingest import BatchTiming

        ex = object.__new__(SegmentExecutor)
        pulled = [0]
        dispatch_pulls = []

        def staged_items():
            for _ in range(6):
                pulled[0] += 1
                yield ({"x": np.zeros((4, 2), np.float32)}, 4), \
                    BatchTiming(rows=4)

        def mega(group):
            dispatch_pulls.append(pulled[0])
            return [(np.zeros(1),)] * len(group)

        ex._make_mega_step = lambda params, state, k: mega
        handles = []
        ex._dispatch_mega(staged_items(), None, {"ext": ["x"]}, None, 2,
                          handles)
        assert dispatch_pulls == [2, 4, 6]  # eager staging would be [6,...]
        assert len(handles) == 6
        assert all(t.mega_k == 2 for _h, t in handles)


class TestMegaKnob:
    def test_cost_model_chooses_k_from_dispatch_ratio(self):
        from mmlspark_tpu.core.costmodel import SegmentCostModel
        from mmlspark_tpu.parallel.ingest import BatchTiming

        model = SegmentCostModel(peaks={"flops": 1e9, "bytes_per_s": 1e9,
                                        "peak_source": "test"}, min_obs=2)
        # dispatch dominates: 5ms fixed vs 1ms device work per batch
        for _ in range(4):
            model.observe_batch("seg", BatchTiming(
                h2d_s=0.0004, dispatch_s=0.005, compute_s=0.0005,
                readback_s=0.0001, rows=16, padded_rows=16))
        k = model.choose_mega_k("seg")
        assert k is not None and k > 1
        # dispatch negligible: stay at 1
        cheap = SegmentCostModel(peaks={"flops": 1e9, "bytes_per_s": 1e9,
                                        "peak_source": "test"}, min_obs=2)
        for _ in range(4):
            cheap.observe_batch("seg", BatchTiming(
                h2d_s=0.004, dispatch_s=0.0001, compute_s=0.005,
                readback_s=0.001, rows=16, padded_rows=16))
        assert cheap.choose_mega_k("seg") == 1
        # uncalibrated: None
        assert SegmentCostModel().choose_mega_k("other") is None

    def test_choose_mega_k_stable_under_amortized_timings(self):
        """Once K>1 is active, recorded dispatch_s is the per-batch SHARE
        of one mega dispatch. choose_mega_k must de-amortize via the
        mega_k tag — otherwise the tuner sees cheap dispatch, proposes
        K=1, the cost reappears, and K oscillates every cycle."""
        from mmlspark_tpu.core.costmodel import SegmentCostModel
        from mmlspark_tpu.parallel.ingest import BatchTiming

        model = SegmentCostModel(peaks={"flops": 1e9, "bytes_per_s": 1e9,
                                        "peak_source": "test"}, min_obs=2)
        for _ in range(4):
            model.observe_batch("seg", BatchTiming(
                h2d_s=0.0004, dispatch_s=0.005, compute_s=0.0005,
                readback_s=0.0001, rows=16, padded_rows=16))
        k = model.choose_mega_k("seg")
        assert k is not None and k > 1
        # mega active: per-batch dispatch share = fixed cost / K, tagged
        for _ in range(16):
            model.observe_batch("seg", BatchTiming(
                h2d_s=0.0004, dispatch_s=0.005 / k, compute_s=0.0005,
                readback_s=0.0001, rows=16, padded_rows=16, mega_k=k))
        assert model.choose_mega_k("seg") == k
        # the de-amortized EWMA survives serialization
        restored = SegmentCostModel.from_dict(
            model.to_dict(), peaks={"flops": 1e9, "bytes_per_s": 1e9,
                                    "peak_source": "test"})
        assert restored.choose_mega_k("seg") == k

    def test_knobset_round_trips_and_rollback(self):
        from mmlspark_tpu.core.tune import KnobSet

        k = KnobSet(mega_k={"seg": 4})
        assert not k.is_default()
        assert KnobSet.from_dict(k.to_dict()).mega_k == {"seg": 4}
        assert KnobSet().is_default()

    def test_tuner_apply_and_rollback_drive_mega_k(self):
        from mmlspark_tpu.core.tune import KnobSet, Tuner

        pm = _image_chain()
        fused = FusedPipelineModel(pm.stages, cache=CompileCache())
        _ = fused.transform(_image_df(rows=4, parts=1))
        label = next(iter(fused.fusion_stats()["per_segment"]))
        tuner = Tuner(fused=fused)
        tuner.apply(KnobSet(mega_k={label: 3}))
        assert fused.mega_k_max == 3
        assert tuner.rollback("test")
        assert fused.mega_k_max == 1  # previous (default) set re-applied

    def test_watchdog_budget_scales_with_k_batches(self):
        from mmlspark_tpu.serving.supervisor import DispatchWatchdog

        wd = DispatchWatchdog(k=2.0, min_budget_s=0.0)
        assert wd.budget_s(16) is None  # unarmed
        wd.observe(1.0)
        b1 = wd.budget_s(16)
        b4 = wd.budget_s(16, batches=4)
        assert b1 == pytest.approx(2.0)
        assert b4 == pytest.approx(8.0)  # EWMA fallback scales by K
        # the cost-model path prices rows directly: no K scaling
        wd2 = DispatchWatchdog(k=2.0, min_budget_s=0.0,
                               predict_ms_fn=lambda rows: 100.0)
        assert wd2.budget_s(16, batches=4) == pytest.approx(0.2)


# ---------------------------------------------------------------------------
# Serving e2e: binary wire -> fused chain -> exactly one host copy
# ---------------------------------------------------------------------------


def _serve_frame_image_chain(slot_staging=True, mega_k=None,
                             async_exec=False, http_mode="thread"):
    """serve_pipeline over the fused image chain fed by BINARY frames:
    each request body is one single-column frame carrying a (16,16,3)
    uint8 image. Returns (started server, fused model)."""
    from mmlspark_tpu.serving import serve_pipeline
    from mmlspark_tpu.stages import UDFTransformer

    pm = _image_chain()
    fused = FusedPipelineModel(pm.stages, cache=CompileCache(),
                               slot_staging=slot_staging)
    if mega_k:
        _ = fused.transform(_image_df(rows=4, parts=1))
        label = next(iter(fused.fusion_stats()["per_segment"]))
        fused.set_tuning(mega_k={label: int(mega_k)})
    in_cols = {"data", "image", "id", "value", "headers", "origin"}

    def decode_rows(col):
        out = np.empty(len(col), dtype=object)
        for i, v in enumerate(col):
            out[i] = ImageSchema.make(np.asarray(v, dtype=np.uint8),
                                      f"req{i}")
        return out

    decode = UDFTransformer(inputCol="data", outputCol="image",
                            vectorizedUdf=decode_rows)

    class _Chain:
        def transform(self, df):
            out = fused.transform(decode.transform(df))
            feat = next((c for c in out.schema.names
                         if c not in in_cols), None)
            if feat is not None and "reply" not in out.schema:
                out = out.with_column(
                    "reply",
                    lambda p, _c=feat: [
                        None if v is None else np.asarray(v).tolist()
                        for v in p[_c]])
            return out

        def set_tuning(self, **kw):
            fused.set_tuning(**kw)

        cost_model = property(lambda self: fused.cost_model)
        last_ingest_stats = property(lambda self: fused.last_ingest_stats)
        mega_k_max = property(lambda self: fused.mega_k_max)
        _seg_stats = property(lambda self: fused._seg_stats)
        _cache = property(lambda self: fused._cache)
        _last_plan = property(lambda self: fused._last_plan)

        def fusion_stats(self):
            return fused.fusion_stats()

        def has_param(self, name):
            return False

    srv = serve_pipeline(_Chain(), "data", parse="json", port=0,
                         max_wait_ms=0.0, http_mode=http_mode,
                         async_exec=async_exec)
    return srv.start(), fused


def _frame_body(seed=11):
    rng = np.random.default_rng(seed)
    img = rng.integers(0, 256, (16, 16, 3), dtype=np.uint8)
    return encode_frame({"img": img})


class TestServingSingleCopyE2E:
    def test_binary_wire_reaches_device_with_one_host_copy(self):
        srv, fused = _serve_frame_image_chain()
        try:
            body = _frame_body()
            for _ in range(4):
                status, reply = _post(srv.address, body,
                                      {"Content-Type": FRAME_CONTENT_TYPE})
                assert status == 200, reply
        finally:
            srv.stop()
        s = fused.last_ingest_stats.summary()
        # every batch deposited: exactly ONE host copy (the slot fill);
        # zero accounted fallback copies
        assert s["slot_deposits"] > 0
        assert s.get("fallback_copies", 0) == 0

    def test_deposit_vs_copy_reply_parity_across_modes(self):
        body = _frame_body(seed=12)
        replies = {}
        for staging in (False, True):
            for async_exec in (False, True):
                srv, _ = _serve_frame_image_chain(
                    slot_staging=staging, async_exec=async_exec)
                try:
                    status, reply = _post(
                        srv.address, body,
                        {"Content-Type": FRAME_CONTENT_TYPE})
                finally:
                    srv.stop()
                assert status == 200, reply
                replies[(staging, async_exec)] = reply
        assert len(set(replies.values())) == 1, replies

    def test_mega_k_serving_reply_parity(self):
        body = _frame_body(seed=13)
        srv, _ = _serve_frame_image_chain(mega_k=None)
        try:
            _, ref = _post(srv.address, body,
                           {"Content-Type": FRAME_CONTENT_TYPE})
        finally:
            srv.stop()
        srv, fused = _serve_frame_image_chain(mega_k=2)
        try:
            status, got = _post(srv.address, body,
                                {"Content-Type": FRAME_CONTENT_TYPE})
        finally:
            srv.stop()
        assert status == 200 and got == ref
        assert fused.mega_k_max == 2
