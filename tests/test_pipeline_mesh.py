"""Pipeline-parallel mesh execution tests (parallel/pipeplan.py + wiring).

Covers:
  - the pipeline view: ``split_segments`` re-cuts a fused chain at clean
    d2d boundaries into chainable sub-segments (host stages, single-stage
    and stitched segments pass through), and ``chainable``/
    ``chainable_runs`` enforce the handoff contract;
  - plan derivation: disjoint pipe-axis sub-meshes preserving non-pipe
    axes, predict_ms-balanced contiguous stage grouping (equal-count
    while uncalibrated), and ``build_pipe_plan``'s serial-stay gates;
  - the cost model's pipelined clock: ``predict_pipelined_ms`` /
    ``choose_pipe_depth`` calibration gates (None while cold — plans
    from an uncalibrated model are bitwise-identical to serial);
  - the bitwise contract: knob off / pipe_depth=1 / no pipe axis all run
    the exact serial path (no ``pipeline`` stats key, byte-identical
    metrics exposition), and the pipelined stream over a forced
    4-device ``pipe=2`` mesh matches the serial fused chain BITWISE;
  - the Tuner's journaled ``pipe_depth`` knob with one-step rollback
    restoring the serial path bitwise;
  - stage quarantine: ``set_pipe_stages``/``note_stage_wedged`` eject a
    wedged stage's whole sub-mesh, and the ``pipe.stage_wedge`` chaos
    point drives a depth N-1 re-plan that drops no in-flight request;
  - the fleet cache fingerprint: a pipelined executable can never
    warm-load onto a different pipe layout (clean counted miss), while
    non-pipe fingerprints stay byte-identical.
"""

import os

import numpy as np
import pytest

import jax

from mmlspark_tpu.core import faults
from mmlspark_tpu.core.costmodel import SegmentCostModel
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.device_stage import CompileCache
from mmlspark_tpu.core.fusion import FusedPipelineModel, HostStage
from mmlspark_tpu.core.pipeline import PipelineModel
from mmlspark_tpu.core.schema import ImageSchema
from mmlspark_tpu.core.tune import KnobSet, Tuner
from mmlspark_tpu.image.featurizer import ImageFeaturizer
from mmlspark_tpu.image.stages import ImageTransformer
from mmlspark_tpu.models.dnn_model import DNNModel
from mmlspark_tpu.models.module import (Conv2D, Dense, FunctionModel,
                                        GlobalAvgPool, Sequential, relu)
from mmlspark_tpu.obs.bridge import _fusion_families
from mmlspark_tpu.parallel import pipeplan
from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
from mmlspark_tpu.parallel.pipeplan import (PipeStageSharding,
                                            PipeSupervision, balance_stages,
                                            build_pipe_plan, chainable,
                                            chainable_runs, pipe_submeshes,
                                            split_segments)
from mmlspark_tpu.serving.fleet.cache import (PersistentCompileCache,
                                              content_key, env_fingerprint)
from mmlspark_tpu.serving.supervisor import (HEALTHY, QUARANTINED,
                                             ReplicaSupervisor)

#: seeded chaos lane (docs/faults.md): MMLSPARK_CHAOS_SEED replays the
#: -m faults classes under a different but deterministic fault schedule
CHAOS_SEED = int(os.environ.get("MMLSPARK_CHAOS_SEED", "0"))

PEAKS = {"flops": 1e9, "bytes_per_s": 1e9, "peak_source": "test"}


def _make_chain(rows=16, partitions=2, deep=False):
    """Fused image chain (ImageTransformer -> CNN featurizer -> DNN head
    [-> second DNN head with ``deep=True``]): splits at the d2d
    boundaries into 2 (3 with ``deep``) chainable sub-segments.
    Returns (fused model, DataFrame)."""
    size = 16
    mod = Sequential([("conv", Conv2D(4, (3, 3))), ("act", relu()),
                      ("pool", GlobalAvgPool()), ("head", Dense(4))],
                     name="pipecnn")
    params, _ = mod.init(jax.random.PRNGKey(0), (size, size, 3))
    backbone = FunctionModel(mod, params, (size, size, 3),
                             layer_names=["head", "pool"], name="pipecnn")
    head = Sequential([("d1", Dense(8)), ("a", relu()), ("d2", Dense(3))],
                      name="pipehead")
    hp, _ = head.init(jax.random.PRNGKey(1), (4,))
    dnn = DNNModel(inputCol="features", outputCol="emb", batchSize=8)
    dnn.set_model(FunctionModel(head, hp, (4,), name="pipehead"))
    stages = [ImageTransformer().resize(size, size),
              ImageFeaturizer(scaleFactor=1 / 255., batchSize=8)
              .set_model(backbone), dnn]
    if deep:
        head2 = Sequential([("d3", Dense(5))], name="pipehead2")
        hp2, _ = head2.init(jax.random.PRNGKey(2), (3,))
        dnn2 = DNNModel(inputCol="emb", outputCol="emb2", batchSize=8)
        dnn2.set_model(FunctionModel(head2, hp2, (3,), name="pipehead2"))
        stages.append(dnn2)
    rng = np.random.default_rng(4)
    obj = np.empty(rows, dtype=object)
    for i in range(rows):
        obj[i] = ImageSchema.make(
            rng.integers(0, 256, (20, 20, 3), dtype=np.uint8), f"img{i}")
    df = DataFrame.from_dict({"image": obj}, num_partitions=partitions)
    pm = PipelineModel(stages)
    return FusedPipelineModel(pm.stages, cache=CompileCache()), df


def _col(out, name="emb"):
    return np.stack([np.asarray(v) for v in out.column(name)])


def _pipe_mesh(n=4, pipe=2):
    return make_mesh(MeshSpec(data=n // pipe, pipe=pipe),
                     device_list=jax.devices()[:n])


def _pipe_metric_lines(fused):
    return [f.name for f in _fusion_families(fused.fusion_stats())
            if f.name.startswith("mmlspark_pipe_")]


# -- the pipeline view + handoff contract ------------------------------------


class TestSplitAndChainable:
    def test_fused_chain_splits_at_d2d_boundaries(self):
        fused, df = _make_chain()
        fused.transform(df)
        nodes = fused._last_plan
        assert [type(n).__name__ for n in nodes] == ["Segment"]
        view = split_segments(nodes)
        assert [n.label for n in view] == [
            "ImageTransformer+ImageFeaturizer", "DNNModel"]
        assert chainable(view[0], view[1])
        runs = chainable_runs(view)
        assert len(runs) == 1 and [j for j, _ in runs[0]] == [0, 1]
        # the original plan fuses everything: no runs before the re-cut
        assert chainable_runs(nodes) == []

    def test_deep_chain_splits_into_three(self):
        fused, df = _make_chain(deep=True)
        fused.transform(df)
        view = split_segments(fused._last_plan)
        assert [n.label for n in view] == [
            "ImageTransformer+ImageFeaturizer", "DNNModel", "DNNModel"]
        assert len(chainable_runs(view)[0]) == 3

    def test_host_and_single_stage_nodes_pass_through(self):
        fused, df = _make_chain()
        fused.transform(df)
        seg = fused._last_plan[0]
        host = HostStage(ImageTransformer())
        view = split_segments([host, seg])
        assert view[0] is host
        single = view[2]
        assert split_segments([single]) == [single]

    def test_prepare_headed_stage_cannot_head_a_subsegment(self):
        # ImageTransformer's DeviceFn carries a host ``prepare`` (raw
        # image staging): the cut before it is illegal, so it stays
        # glued to whatever precedes it — here the segment head
        fused, df = _make_chain()
        fused.transform(df)
        seg = fused._last_plan[0]
        assert seg.dfns[0].prepare is not None
        view = split_segments([seg])
        assert view[0].label == "ImageTransformer+ImageFeaturizer"

    def test_serial_view_is_bitwise_identical(self):
        fused, df = _make_chain()
        want = _col(fused.transform(df))
        fused2, df2 = _make_chain()
        fused2.transform(df2)  # build the plan
        # running the re-cut view serially (what a pipelined stream
        # degrades to per-partition) matches the fused chain bitwise
        view = split_segments(fused2._last_plan)
        assert len(view) == 2
        got = df2
        from mmlspark_tpu.parallel.ingest import IngestStats
        for node in view:
            got = fused2._make_executor(node).run(got, IngestStats())
        assert np.array_equal(_col(got), want)


class TestSubmeshesAndBalance:
    def test_submeshes_partition_the_pipe_axis(self):
        mesh = _pipe_mesh(4, pipe=2)
        subs = pipe_submeshes(mesh, 2)
        assert len(subs) == 2
        ids = [sorted(d.id for d in np.asarray(s.devices).flat)
               for s in subs]
        assert ids[0] and ids[1] and not (set(ids[0]) & set(ids[1]))
        assert sorted(ids[0] + ids[1]) == \
            sorted(d.id for d in np.asarray(mesh.devices).flat)
        for s in subs:
            assert dict(s.shape)["data"] == 2 and dict(s.shape)["pipe"] == 1

    def test_submeshes_none_without_pipe_axis(self):
        assert pipe_submeshes(make_mesh(
            MeshSpec(data=4), device_list=jax.devices()[:4]), 2) is None
        assert pipe_submeshes(_pipe_mesh(4, pipe=2), 1) is None
        assert pipe_submeshes(_pipe_mesh(4, pipe=2), 3) is None

    def test_balance_equal_count_while_uncalibrated(self):
        assert balance_stages([None, None, None], 2) == [2, 1]
        assert balance_stages([1.0, None], 2) == [1, 1]

    def test_balance_minimizes_the_clock(self):
        assert balance_stages([4.0, 1.0, 1.0], 2) == [1, 2]
        assert balance_stages([1.0, 1.0, 4.0], 2) == [2, 1]
        assert balance_stages([1.0] * 4, 5) == [1, 1, 1, 1]

    def test_build_pipe_plan_serial_gates(self):
        fused, df = _make_chain()
        fused.transform(df)
        nodes = fused._last_plan
        assert build_pipe_plan(nodes, None, 2) is None
        assert build_pipe_plan(
            nodes, make_mesh(MeshSpec(data=4),
                             device_list=jax.devices()[:4]), 2) is None
        assert build_pipe_plan(nodes, _pipe_mesh(), 1) is None
        pplan = build_pipe_plan(nodes, _pipe_mesh(), 2)
        assert pplan is not None and pplan.depth == 2
        assert (pplan.first, pplan.last) == (0, 2)
        assert [st.labels for st in pplan.stages] == [
            ("ImageTransformer+ImageFeaturizer",), ("DNNModel",)]
        assert pplan.nodes is not None and len(pplan.nodes) == 2

    def test_stage_cache_keys_are_disjoint(self):
        mesh = _pipe_mesh(4, pipe=2)
        subs = pipe_submeshes(mesh, 2)
        a = PipeStageSharding(None, subs[0], 0, 2)
        b = PipeStageSharding(None, subs[1], 1, 2)
        assert a.cache_key() != b.cache_key()
        assert a.shape_prefix() == "pipe=s0of2;"
        # replicated default placement: GSPMD degenerates to the original
        # program, and donation MUST stay off (the staged input is the
        # upstream stage's output buffer, still read at drain)
        kw = a.jit_kwargs()
        assert "donate_argnums" not in kw
        assert "in_shardings" in kw and "out_shardings" in kw


# -- the cost model's pipelined clock ----------------------------------------


class _Timing:
    def __init__(self, compute_ms, rows=8):
        self.queue_s = 0.0
        self.h2d_s = 1e-4
        self.dispatch_s = 1e-4
        self.compute_s = compute_ms / 1e3
        self.readback_s = 1e-4
        self.bytes_in = 1024
        self.rows = rows
        self.padded_rows = rows
        self.mega_k = 1


def _calibrated_model(labels_ms, handoff=True):
    model = SegmentCostModel(peaks=PEAKS, min_obs=2)
    for label, ms in labels_ms.items():
        for _ in range(3):
            model.observe_batch(label, _Timing(ms))
    if handoff:
        model.observe_collective(pipeplan.PIPE_HANDOFF_OP, 1024, 1e-4)
        model.observe_collective(pipeplan.PIPE_HANDOFF_OP, 4096, 2e-4)
    return model


class TestPipelinedClock:
    def test_uncalibrated_predicts_nothing(self):
        model = SegmentCostModel(peaks=PEAKS, min_obs=2)
        assert model.predict_pipelined_ms(["a", "b"], 8) is None
        assert model.choose_pipe_depth(["a", "b"], 8, 2) is None

    def test_unfitted_handoff_gates_the_prediction(self):
        model = _calibrated_model({"a": 10.0, "b": 10.0}, handoff=False)
        assert model.predict_pipelined_ms(
            ["a", "b"], 8, handoff_bytes=1024) is None
        assert model.predict_pipelined_ms(["a", "b"], 8) is not None

    def test_gpipe_clock_shape(self):
        model = _calibrated_model({"a": 10.0, "b": 10.0})
        a = model.predict_ms("a", batch=8)
        serial = 8 * (a + model.predict_ms("b", batch=8))
        piped = model.predict_pipelined_ms(["a", "b"], 8, microbatches=8)
        # (M + S - 1) * clock vs M * sum: near-2x at equal stage costs
        assert piped < serial * 0.65

    def test_choose_pipe_depth(self):
        model = _calibrated_model({"a": 10.0, "b": 10.0})
        assert model.choose_pipe_depth(["a", "b"], 8, 2) == 2
        assert model.choose_pipe_depth(["a", "b"], 8, 1) is None
        assert model.choose_pipe_depth(["a"], 8, 2) is None
        # one dominant stage: the clock never drops below it, so the
        # fill/drain overhead can't pay for itself
        skew = _calibrated_model({"a": 100.0, "b": 0.05})
        assert skew.choose_pipe_depth(["a", "b"], 8, 2) is None


# -- bitwise contract --------------------------------------------------------


class TestColdStartParity:
    def test_mesh_without_knob_stays_serial(self):
        fused, df = _make_chain()
        want = _col(fused.transform(df))
        fused2, df2 = _make_chain()
        fused2.set_mesh(_pipe_mesh())
        got = _col(fused2.transform(df2))
        stats = fused2.fusion_stats()
        assert "pipeline" not in stats
        assert _pipe_metric_lines(fused2) == []
        assert np.array_equal(want, got)

    def test_pipe_depth_one_clears_the_knob(self):
        fused, df = _make_chain()
        want = _col(fused.transform(df))
        fused2, df2 = _make_chain()
        fused2.set_mesh(_pipe_mesh())
        fused2.set_tuning(pipe_depth=2)
        fused2.set_tuning(pipe_depth=1)
        assert fused2._pipe_depth is None
        got = _col(fused2.transform(df2))
        assert "pipeline" not in fused2.fusion_stats()
        assert np.array_equal(want, got)

    def test_knob_without_pipe_axis_stays_serial(self):
        fused, df = _make_chain()
        want = _col(fused.transform(df))
        fused2, df2 = _make_chain()
        fused2.set_mesh(make_mesh(MeshSpec(data=4),
                                  device_list=jax.devices()[:4]))
        fused2.set_tuning(pipe_depth=2)
        got = _col(fused2.transform(df2))
        assert "pipeline" not in fused2.fusion_stats()
        assert np.array_equal(want, got)


class TestPipelinedParity:
    def test_pipelined_bitwise_equals_serial(self):
        fused, df = _make_chain()
        want_emb = _col(fused.transform(df))
        want_feat = _col(fused.transform(df), "features")
        fused2, df2 = _make_chain()
        fused2.set_mesh(_pipe_mesh())
        fused2.set_tuning(pipe_depth=2)
        out = fused2.transform(df2)
        assert np.array_equal(_col(out), want_emb)
        assert np.array_equal(_col(out, "features"), want_feat)
        pipe = fused2.fusion_stats()["pipeline"]
        assert pipe["depth"] == 2 and pipe["replans"] == 0
        assert pipe["serial_fallback_partitions"] == 0
        assert pipe["micro_batches"] >= 2
        assert pipe["handoff_bytes"] > 0
        devs = [set(st["devices"]) for st in pipe["stages"]]
        assert devs[0] and devs[1] and not (devs[0] & devs[1])
        assert 0.0 < pipe["bubble_ratio"] < 1.0
        for st in pipe["stages"]:
            assert 0.0 <= st["busy_ratio"] <= 1.0

    def test_deep_chain_three_stages(self):
        fused, df = _make_chain(deep=True)
        want = _col(fused.transform(df), "emb2")
        fused2, df2 = _make_chain(deep=True)
        fused2.set_mesh(make_mesh(MeshSpec(pipe=3),
                                  device_list=jax.devices()[:3]))
        fused2.set_tuning(pipe_depth=3)
        got = _col(fused2.transform(df2), "emb2")
        assert np.array_equal(want, got)
        pipe = fused2.fusion_stats()["pipeline"]
        assert pipe["depth"] == 3
        assert [len(st["segments"]) for st in pipe["stages"]] == [1, 1, 1]

    def test_pipe_metric_families_only_when_active(self):
        fused, df = _make_chain()
        fused.set_mesh(_pipe_mesh())
        fused.set_tuning(pipe_depth=2)
        fused.transform(df)
        names = _pipe_metric_lines(fused)
        assert names == [
            "mmlspark_pipe_depth", "mmlspark_pipe_bubble_ratio",
            "mmlspark_pipe_stage_busy_ratio",
            "mmlspark_pipe_handoff_bytes_total",
            "mmlspark_pipe_stage_requeues_total"]
        fams = {f.name: f for f in _fusion_families(fused.fusion_stats())}
        assert [s.labels.get("stage") for s in
                fams["mmlspark_pipe_stage_busy_ratio"].samples] == ["0", "1"]
        # knob back off: the families vanish with the stats key
        fused.set_tuning(pipe_depth=1)
        fused.transform(df)
        assert _pipe_metric_lines(fused) == []


# -- the Tuner knob ----------------------------------------------------------


class _ForcedDepthModel(SegmentCostModel):
    """Always proposes depth 2 — pins the Tuner-side plumbing under test
    (choose_pipe_depth's decision surface has its own tests above)."""

    def choose_pipe_depth(self, chain_labels, batch, max_depth,
                          microbatches=8, handoff_bytes=0.0,
                          op="pipe_handoff", margin=0.95):
        return 2 if max_depth >= 2 and len(chain_labels) >= 2 else None


def _depth_tuner(**tuner_kw):
    fused, df = _make_chain()
    fused.transform(df)
    fused.set_mesh(_pipe_mesh())
    model = _ForcedDepthModel(peaks=PEAKS, min_obs=2)
    return fused, Tuner(fused=fused, model=model, **tuner_kw), df


class TestTunerKnob:
    def test_knobset_round_trip(self):
        k = KnobSet(pipe_depth=2)
        assert not k.is_default()
        assert k.to_dict()["pipe_depth"] == 2
        assert KnobSet.from_dict(k.to_dict()).pipe_depth == 2
        assert KnobSet.from_dict(KnobSet().to_dict()).is_default()

    def test_propose_carries_pipe_depth(self):
        fused, t, df = _depth_tuner()
        assert t.propose().pipe_depth == 2
        # no pipe axis -> no proposal, whatever the chooser says
        fused.set_mesh(make_mesh(MeshSpec(data=4),
                                 device_list=jax.devices()[:4]))
        assert t.propose().pipe_depth is None

    def test_apply_journals_and_pipelines(self):
        fused, t, df = _depth_tuner()
        result = t.tune(lambda: 100.0, steps=1, warmup=0)
        assert result["rollbacks"] == 0
        assert fused._pipe_depth == 2
        applied = [e for e in t.journal if e["action"] == "apply"]
        assert applied and applied[-1]["knobs"]["pipe_depth"] == 2
        fused.transform(df)
        assert fused.fusion_stats()["pipeline"]["depth"] == 2

    def test_rollback_restores_serial_bitwise(self):
        fused, t, df = _depth_tuner()
        want = _col(fused.transform(df))
        t.tolerance = 0.05
        with faults.FaultInjector(seed=3).plan(
                faults.TUNER_MEASURE, at=(2,), delay_s=0.2, exc=None):
            result = t.tune(lambda: 100.0, steps=3, warmup=0)
        assert t.rollbacks >= 1
        assert result["steps"][1]["accepted"] is False
        assert any(e["action"].startswith("rollback") for e in t.journal)
        # one-step rollback: the knob cleared, the serial path is bitwise
        assert fused._pipe_depth is None
        assert np.array_equal(_col(fused.transform(df)), want)
        assert "pipeline" not in fused.fusion_stats()


# -- stage quarantine + chaos ------------------------------------------------


class TestStageQuarantine:
    def test_wedge_ejects_the_stage_submesh(self):
        sup = ReplicaSupervisor(4, quarantine_s=60.0)
        sup.set_pipe_stages([[0, 2], [1, 3]])
        assert sup.pipe_stage(1) == (1, 3)
        sup.note_stage_wedged(1)
        rows = {r["replica"]: r for r in sup.describe()}
        assert rows[1]["state"] == QUARANTINED
        assert rows[3]["state"] == QUARANTINED
        assert rows[1]["last_reason"] == "pipe_stage:1"
        assert rows[0]["state"] == HEALTHY
        assert rows[2]["state"] == HEALTHY


@pytest.mark.faults
class TestWedgeChaos:
    def test_full_wedge_degrades_to_serial_bitwise(self):
        fused, df = _make_chain()
        want = _col(fused.transform(df))
        fused2, df2 = _make_chain()
        mesh = _pipe_mesh()
        sup = ReplicaSupervisor(4, quarantine_s=60.0)
        PipeSupervision(fused2, mesh, supervisor=sup)
        fused2.set_tuning(pipe_depth=2)
        with faults.FaultInjector(seed=CHAOS_SEED).plan(
                faults.PIPE_STAGE_WEDGE, every=1,
                message="chaos: stage wedged") as inj:
            got = _col(fused2.transform(df2))
        assert inj.fired(faults.PIPE_STAGE_WEDGE)
        # depth 2 - 1 = serial on the survivors; nothing dropped
        assert np.array_equal(want, got)
        assert "pipeline" not in fused2.fusion_stats()
        sview = fused2._pipe_supervision.describe()
        assert sview["replans"] == 1 and sview["depth"] == 1
        rows = {r["replica"]: r for r in sup.describe()}
        wedged = [i for i, r in rows.items()
                  if r["state"] == QUARANTINED]
        assert len(wedged) == 2  # exactly one stage's sub-mesh
        assert all(rows[i]["last_reason"].startswith("pipe_stage:")
                   for i in wedged)

    def test_mid_stream_wedge_replans_depth_two(self):
        fused, df = _make_chain(deep=True)
        want = _col(fused.transform(df), "emb2")
        fused2, df2 = _make_chain(deep=True)
        mesh = make_mesh(MeshSpec(pipe=3), device_list=jax.devices()[:3])
        PipeSupervision(fused2, mesh)
        fused2.set_tuning(pipe_depth=3)
        with faults.FaultInjector(seed=CHAOS_SEED).plan(
                faults.PIPE_STAGE_WEDGE, at=(5,),
                message="chaos: stage wedged"):
            got = _col(fused2.transform(df2), "emb2")
        assert np.array_equal(want, got)
        # the 2 surviving devices re-plan at depth 3 - 1 = 2 and the
        # re-run pipeline (not a serial fallback) carries the replan tally
        pipe = fused2.fusion_stats()["pipeline"]
        assert pipe["depth"] == 2 and pipe["replans"] == 1
        assert sum(st["requeues"] for st in pipe["stages"]) >= 0
        assert fused2._pipe_supervision.describe()["replans"] == 1


# -- fleet cache fingerprint -------------------------------------------------


class TestPipeFingerprint:
    def test_non_pipe_fingerprint_unchanged(self):
        fp = env_fingerprint(make_mesh(MeshSpec(data=4),
                                       device_list=jax.devices()[:4]))
        assert sorted(fp) == ["backend", "devices", "format", "jax",
                              "mesh"]
        assert "pipe_submesh" not in env_fingerprint()

    def test_pipe_fingerprint_carries_submesh_shape(self):
        fp = env_fingerprint(_pipe_mesh())
        assert fp["pipe_submesh"] == \
            "data=2;fsdp=1;tensor=1;seq=1;expert=1;pipe=2"
        other = env_fingerprint(make_mesh(MeshSpec(pipe=4),
                                          device_list=jax.devices()[:4]))
        assert fp["pipe_submesh"] != other["pipe_submesh"]
        assert content_key(("seg", 8), fp) != content_key(("seg", 8), other)

    def test_warm_load_on_other_pipe_layout_is_a_counted_miss(self,
                                                              tmp_path):
        t1 = PersistentCompileCache(str(tmp_path), mesh=_pipe_mesh())
        t1.store(("seg", 8), None, cost={"compute_ms": 1.0},
                 label="seg", shape="b8")
        t2 = PersistentCompileCache(
            str(tmp_path),
            mesh=make_mesh(MeshSpec(pipe=4), device_list=jax.devices()[:4]))
        assert t2.load(("seg", 8), label="seg", shape="b8") is None
        # clean counted miss: the entry was never even found
        assert t2.misses == 1 and t2.costs_only == 0
        # same layout: the entry is found again (cost-only tier here —
        # ``costs_only`` proves the content address matched)
        t3 = PersistentCompileCache(str(tmp_path), mesh=_pipe_mesh())
        assert t3.load(("seg", 8), label="seg", shape="b8") is None
        assert t3.costs_only == 1
