"""Model lifecycle plane suite (serving/lifecycle, docs/lifecycle.md).

Covers the registry state machine + two-phase swap, shadow scoring,
the canary controller's gated walk over a fake clock (divergence /
SLO-burn rollback, warm-before-swap promotion), the train-on-serve
journal-replay contract (bitwise checkpoint resume for the VW and GBDT
adapters), and the serving wiring: ``/_mmlspark/models``,
``/_mmlspark/feedback``, the stats section, per-version metric
families, an end-to-end shadow -> canary -> promote rollout through a
live server, and ``lifecycle=False`` bitwise parity. The chaos-lane
fault-injection cases (crash mid-swap / mid-checkpoint) live in
tests/test_faults.py (TestLifecycleChaos).
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from mmlspark_tpu.core.dataframe import DataFrame  # noqa: E402
from mmlspark_tpu.serving.lifecycle import (  # noqa: E402
    CANARY,
    CANDIDATE,
    LIVE,
    RETIRED,
    ROLLED_BACK,
    SHADOWING,
    CanaryConfig,
    CanaryController,
    FeedbackJournal,
    GBDTRefitAdapter,
    LifecyclePlane,
    ModelRegistry,
    OnlineTrainer,
    VWOnlineAdapter,
    make_lifecycle,
    score_outputs,
    structural_digest,
)
from mmlspark_tpu.vw.learner import LearnerConfig, LinearLearner  # noqa: E402


def _echo(df):
    return df.with_column("reply", lambda p: p["value"])


def _echo_twin(df):
    """A distinct callable with byte-identical behavior (a candidate
    that must pass the bitwise shadow gate)."""
    return df.with_column("reply", lambda p: p["value"])


def _diverging(df):
    return df.with_column("reply", lambda p: [b"WRONG" for _ in p["id"]])


def _df(ids, values, headers=None):
    n = len(ids)
    h = np.empty(n, dtype=object)
    for i in range(n):
        h[i] = (headers[i] if headers is not None else {})
    return DataFrame.from_dict({
        "id": np.asarray(ids, dtype=np.int64),
        "value": np.asarray(values, dtype=object),
        "headers": h,
    })


def _out(ids, replies, reply_col="reply"):
    return DataFrame.from_dict({
        "id": np.asarray(ids, dtype=np.int64),
        reply_col: np.asarray(replies, dtype=object),
    })


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_register_and_adopt(self):
        reg = ModelRegistry()
        live = reg.adopt_live(_echo, version="base")
        cand = reg.register(_echo_twin)
        assert live.state == LIVE and live.traffic_share == 1.0
        assert cand.state == CANDIDATE and cand.version == "v2"
        assert reg.live is live
        assert [v.version for v in reg.versions()] == ["base", "v2"]
        with pytest.raises(ValueError):
            reg.adopt_live(_echo)  # live already set
        with pytest.raises(ValueError):
            reg.register(_echo, version="base")  # duplicate id

    def test_state_machine_validation(self):
        reg = ModelRegistry()
        cand = reg.register(_echo_twin, version="c")
        reg.transition("c", SHADOWING)
        reg.transition("c", CANARY)
        with pytest.raises(ValueError):
            reg.transition("c", SHADOWING)  # no going back
        with pytest.raises(ValueError):
            reg.transition("c", "no_such_state")
        reg.transition("c", ROLLED_BACK)
        with pytest.raises(ValueError):
            reg.transition("c", CANARY)  # terminal
        assert cand.state == ROLLED_BACK

    def test_swap_live_two_phase(self):
        reg = ModelRegistry()
        reg.adopt_live(_echo, version="base")
        reg.register(_echo_twin, version="c")
        reg.transition("c", CANARY)
        applied = []

        def apply(new, old):
            # at apply time NOTHING has flipped yet: the incumbent is
            # still live (the crash-window contract)
            assert reg.live.version == "base"
            applied.append((new.version, old.version))

        reg.swap_live("c", apply=apply)
        assert applied == [("c", "base")]
        assert reg.live.version == "c"
        assert reg.get("base").state == RETIRED
        assert reg.get("base").traffic_share == 0.0
        assert reg.get("c").traffic_share == 1.0
        assert any(e["action"] == "promote" for e in reg.journal)

    def test_swap_apply_failure_aborts_cleanly(self):
        reg = ModelRegistry()
        reg.adopt_live(_echo, version="base")
        reg.register(_echo_twin, version="c")
        reg.transition("c", CANARY)

        def boom(new, old):
            raise RuntimeError("executor wedged")

        with pytest.raises(RuntimeError):
            reg.swap_live("c", apply=boom)
        assert reg.live.version == "base"
        assert reg.get("c").state == CANARY  # retriable, not corrupted

    def test_swap_from_illegal_state_refused(self):
        reg = ModelRegistry()
        reg.adopt_live(_echo, version="base")
        reg.register(_echo_twin, version="c")  # still candidate
        with pytest.raises(ValueError):
            reg.swap_live("c")

    def test_journal_bounded(self):
        reg = ModelRegistry(journal_cap=16)
        for i in range(200):
            reg.register(_echo_twin, version=f"v{i}x")
        assert len(reg.journal) <= 16
        assert reg.transitions["register"] == 200

    def test_summary_serializes(self):
        reg = ModelRegistry()
        reg.adopt_live(_echo, version="base", cost={"predict_ms": 3.0})
        s = reg.summary()
        assert s["live"] == "base"
        assert s["versions"][0]["state"] == LIVE
        assert s["versions"][0]["cost"] == {"predict_ms": 3.0}
        json.dumps(s)  # the /_mmlspark/models payload must serialize

    def test_structural_digest_fallbacks(self):
        class Tok:
            def cache_token(self):
                return "m:abc"

        assert structural_digest(Tok()) == "m:abc"
        assert structural_digest((1, 2, 3)).startswith("p:")
        # equal pickles -> equal digests; different -> different
        assert structural_digest((1, 2)) == structural_digest((1, 2))
        assert structural_digest((1, 2)) != structural_digest((1, 3))
        # unpicklable falls back to a process-local id
        assert structural_digest(lambda x: x).startswith("id:")


# ---------------------------------------------------------------------------
# Shadow scoring
# ---------------------------------------------------------------------------

class TestScoring:
    def test_bitwise_match(self):
        a = _out([1, 2, 3], [b"x", b"y", b"z"])
        b = _out([1, 2, 3], [b"x", b"y", b"z"])
        assert score_outputs(a, b) == (3, 0)

    def test_bytes_divergence(self):
        a = _out([1, 2], [b"x", b"y"])
        b = _out([1, 2], [b"x", b"NOPE"])
        assert score_outputs(a, b) == (2, 1)

    def test_float_tolerance(self):
        a = _out([1, 2], [1.0, 2.0])
        b = _out([1, 2], [1.0 + 1e-9, 2.0])
        assert score_outputs(a, b) == (2, 0)
        c = _out([1, 2], [1.5, 2.0])
        assert score_outputs(a, c) == (2, 1)

    def test_pairs_by_id_not_position(self):
        a = _out([1, 2], [b"x", b"y"])
        b = _out([2, 1], [b"y", b"x"])  # reordered, same payloads
        assert score_outputs(a, b) == (2, 0)

    def test_unmatched_rows_are_divergent(self):
        a = _out([1, 2], [b"x", b"y"])
        b = _out([1, 3], [b"x", b"z"])  # id 2 missing, id 3 extra
        scored, divergent = score_outputs(a, b)
        assert scored == 3 and divergent == 2

    def test_unreadable_output_scores_nothing(self):
        assert score_outputs(object(), object()) == (0, 0)


# ---------------------------------------------------------------------------
# Controller (fake clock)
# ---------------------------------------------------------------------------

def _controller(cfg=None, warm=None, apply_swap=None):
    clock = [1_000.0]
    cfg = cfg or CanaryConfig(shadow_min_scored=4, steps=(0.05, 1.0),
                              hold_s=5.0, min_step_requests=2,
                              check_interval_s=0.0, burn_gate=1.0)
    reg = ModelRegistry(clock=lambda: clock[0])
    reg.adopt_live(_echo, version="base")
    ctl = CanaryController(reg, cfg, warm=warm, apply_swap=apply_swap,
                           clock=lambda: clock[0])
    return ctl, reg, clock


class TestController:
    def test_shadow_gate_holds_until_scored(self):
        ctl, reg, clock = _controller()
        reg.register(_echo_twin, version="c")
        ctl.rollout("c")
        ver = reg.get("c")
        assert ver.state == SHADOWING
        clock[0] += 1.0
        ctl.check()
        assert ver.state == SHADOWING  # 0 scored < 4
        ver.shadow_scored = 4
        clock[0] += 1.0
        ctl.check()
        assert ver.state == CANARY
        assert ver.traffic_share == 0.05

    def test_shadow_divergence_rolls_back(self):
        ctl, reg, clock = _controller()
        reg.register(_diverging, version="c")
        ctl.rollout("c")
        ver = reg.get("c")
        ver.shadow_scored = 8
        ver.shadow_divergent = 2
        clock[0] += 1.0
        ctl.check()
        assert ver.state == ROLLED_BACK
        assert ver.traffic_share == 0.0
        assert ctl.rollbacks == 1
        assert any(e["action"] == "rollback"
                   and e["reason"] == "divergence" for e in ctl.journal)

    def test_shadow_errors_roll_back(self):
        ctl, reg, clock = _controller()
        reg.register(_echo_twin, version="c")
        ctl.rollout("c")
        reg.get("c").shadow_errors = 1
        clock[0] += 1.0
        ctl.check()
        assert reg.get("c").state == ROLLED_BACK

    def test_ramp_holds_then_advances_then_promotes(self):
        order = []
        ctl, reg, clock = _controller(
            warm=lambda ver: order.append("warm") or "warmed",
            apply_swap=lambda new, old: order.append("swap"))
        reg.register(_echo_twin, version="c")
        ctl.rollout("c")
        ver = reg.get("c")
        ver.shadow_scored = 4
        clock[0] += 1.0
        ctl.check()
        assert ver.traffic_share == 0.05  # step 0
        # hold_s not elapsed: no advance even with requests
        ver.requests["canary"] += 2
        clock[0] += 1.0
        ctl.check()
        assert ver.traffic_share == 0.05
        # hold elapsed -> step 1 (100%)
        clock[0] += 6.0
        ctl.check()
        assert ver.traffic_share == 1.0
        # final step held -> promote, warm strictly before swap
        ver.requests["canary"] += 2
        clock[0] += 6.0
        ctl.check()
        assert ver.state == LIVE
        assert reg.live is ver
        assert order == ["warm", "swap"]
        assert ctl.promotions == 1
        assert ctl.active_version() is None

    def test_burn_breach_rolls_back_without_hold(self):
        ctl, reg, clock = _controller()
        reg.register(_echo_twin, version="c")
        ctl.rollout("c")
        ver = reg.get("c")
        ver.shadow_scored = 4
        clock[0] += 1.0
        ctl.check()
        assert ver.state == CANARY
        # every canary batch breaches the 250ms objective
        ver.requests["canary"] += 4
        for _ in range(4):
            ver.slo.record(10.0)
        clock[0] += 1.0  # < hold_s: the breach must NOT wait for the hold
        ctl.check()
        assert ver.state == ROLLED_BACK
        assert any(e["reason"] == "slo_burn" for e in ctl.journal
                   if e["action"] == "rollback")

    def test_swap_failure_journaled_and_retried(self):
        calls = []

        def apply(new, old):
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError("transient")

        ctl, reg, clock = _controller(apply_swap=apply)
        reg.register(_echo_twin, version="c")
        ctl.rollout("c")
        ver = reg.get("c")
        ver.shadow_scored = 4
        clock[0] += 1.0
        ctl.check()
        ver.requests["canary"] += 2
        clock[0] += 6.0
        ctl.check()
        ver.requests["canary"] += 2
        clock[0] += 6.0
        ctl.check()  # promote attempt 1: swap raises
        assert reg.live.version == "base"  # incumbent keeps serving
        assert any(e["action"] == "swap_failed" for e in ctl.journal)
        clock[0] += 1.0
        ctl.check()  # retried on the next tick
        assert reg.live.version == "c"

    def test_one_rollout_at_a_time(self):
        ctl, reg, _clock = _controller()
        reg.register(_echo_twin, version="c1")
        reg.register(_echo_twin, version="c2")
        ctl.rollout("c1")
        with pytest.raises(ValueError):
            ctl.rollout("c2")

    def test_shadow_disabled_goes_straight_to_canary(self):
        cfg = CanaryConfig(shadow_fraction=0.0, steps=(1.0,), hold_s=0.0,
                           min_step_requests=0, check_interval_s=0.0)
        ctl, reg, clock = _controller(cfg=cfg)
        reg.register(_echo_twin, version="c")
        ctl.rollout("c")
        assert reg.get("c").state == CANARY
        assert reg.get("c").traffic_share == 1.0

    def test_summary_serializes(self):
        ctl, reg, _clock = _controller()
        reg.register(_echo_twin, version="c")
        ctl.rollout("c")
        s = ctl.summary()
        assert s["active"] == "c" and s["state"] == SHADOWING
        json.dumps(s)


# ---------------------------------------------------------------------------
# Plane (routing + shadow data path)
# ---------------------------------------------------------------------------

def _plane(**over):
    kw = dict(shadow_fraction=1.0, shadow_min_scored=2, steps=(1.0,),
              hold_s=0.0, min_step_requests=1, check_interval_s=0.0,
              objective_ms=60_000.0)
    kw.update(over)
    clock = [1_000.0]
    plane = LifecyclePlane(CanaryConfig(**kw), clock=lambda: clock[0])
    plane.registry.adopt_live(_echo, version="base")
    return plane, clock


class TestPlane:
    def test_routes_live_by_default(self):
        plane, _clock = _plane()
        out = plane(_df([1], [b"hello"]))
        assert list(out.collect()["reply"]) == [b"hello"]
        assert plane.registry.get("base").requests["live"] == 1

    def test_attr_forwarding_sees_live_transform(self):
        class T:
            mega_k = 7

            def __call__(self, df):
                return _echo(df)

        plane = LifecyclePlane(CanaryConfig())
        plane.registry.adopt_live(T(), version="base")
        assert plane.mega_k == 7
        with pytest.raises(AttributeError):
            plane.no_such_attr
        with pytest.raises(AttributeError):
            plane._private_probe

    def test_canary_share_routes_deterministically(self):
        plane, clock = _plane(shadow_fraction=0.0, steps=(1.0,),
                              min_step_requests=0)
        plane.deploy(_echo_twin, version="c")
        assert plane.registry.get("c").state == CANARY
        plane(_df([1], [b"x"]))
        # share 1.0: every draw routes to the canary
        assert plane.registry.get("c").requests["canary"] == 1
        assert plane.registry.get("base").requests["live"] == 0

    def test_shadow_duplicates_scored_not_fulfilled(self):
        plane, _clock = _plane()
        plane.deploy(_echo_twin, version="c")
        cand = plane.registry.get("c")
        assert cand.state == SHADOWING
        plane.start()
        try:
            for i in range(6):
                out = plane(_df([i], [b"payload%d" % i]))
                # the client reply is ALWAYS the incumbent's
                assert list(out.collect()["reply"]) == [b"payload%d" % i]
            deadline = time.monotonic() + 10.0
            while cand.shadow_scored < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            plane.stop()
        assert cand.shadow_issued >= cand.shadow_scored > 0
        assert cand.shadow_divergent == 0
        assert cand.requests["canary"] == 0  # shadow took no real traffic

    def test_shadow_divergence_counted(self):
        plane, _clock = _plane()
        plane.deploy(_diverging, version="c")
        cand = plane.registry.get("c")
        plane.start()
        try:
            for i in range(6):
                plane(_df([i], [b"x"]))
            deadline = time.monotonic() + 10.0
            while cand.shadow_scored < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            plane.stop()
        assert cand.shadow_divergent > 0

    def test_shadow_candidate_errors_counted(self):
        def broken(df):
            raise RuntimeError("bad model")

        plane, _clock = _plane()
        plane.deploy(broken, version="c")
        cand = plane.registry.get("c")
        plane.start()
        try:
            deadline = time.monotonic() + 10.0
            while cand.shadow_errors < 1 and time.monotonic() < deadline:
                plane(_df([1], [b"x"]))
                time.sleep(0.01)
        finally:
            plane.stop()
        assert cand.shadow_errors >= 1

    def test_full_promotion_via_ticks(self):
        plane, clock = _plane(shadow_fraction=0.0)
        plane.deploy(_echo_twin, version="c")
        clock[0] += 1.0
        plane(_df([1], [b"x"]))  # canary batch (share 1.0)
        clock[0] += 1.0
        plane.tick(0.01)
        assert plane.registry.live.version == "c"
        assert plane.registry.get("base").state == RETIRED
        # traffic keeps flowing through the new live
        out = plane(_df([2], [b"y"]))
        assert list(out.collect()["reply"]) == [b"y"]

    def test_make_lifecycle_coercions(self):
        assert make_lifecycle(None) is None
        assert make_lifecycle(False) is None
        p = make_lifecycle(True)
        assert isinstance(p, LifecyclePlane)
        assert make_lifecycle(p) is p
        p2 = make_lifecycle({"shadow_fraction": 0.5})
        assert p2.config.shadow_fraction == 0.5
        p3 = make_lifecycle(CanaryConfig(seed=3))
        assert p3.config.seed == 3
        with pytest.raises(TypeError):
            make_lifecycle(3)

    def test_summary_serializes(self):
        plane, _clock = _plane()
        json.dumps(plane.summary())


# ---------------------------------------------------------------------------
# Train-on-serve: journal, adapters, bitwise resume
# ---------------------------------------------------------------------------

def _sparse_rows(n, seed=0, nnz=3):
    rng = np.random.default_rng(seed)
    rows, labels = [], []
    for _ in range(n):
        idx = rng.choice(64, size=nnz, replace=False)
        rows.append({"indices": [int(i) for i in idx],
                     "values": [float(v) for v in
                                rng.normal(size=nnz).round(3)]})
        labels.append(float(rng.integers(0, 2)))
    return rows, labels


class TestFeedbackJournal:
    def test_append_read_count(self, tmp_path):
        j = FeedbackJournal(str(tmp_path / "fb.jsonl"))
        rows, labels = _sparse_rows(5)
        assert j.append(rows, labels) == 5
        assert j.count() == 5
        back = j.read(1, 3)
        assert len(back) == 3
        assert back[0] == (rows[1], labels[1])
        with pytest.raises(ValueError):
            j.append(rows, labels[:-1])
        j.close()

    def test_reopen_counts_existing(self, tmp_path):
        path = str(tmp_path / "fb.jsonl")
        j = FeedbackJournal(path)
        rows, labels = _sparse_rows(4)
        j.append(rows, labels)
        j.close()
        j2 = FeedbackJournal(path)
        assert j2.count() == 4
        j2.append(rows[:1], labels[:1])
        assert j2.count() == 5
        j2.close()


def _vw_cfg():
    return LearnerConfig(num_bits=8)


class TestLinearLearner:
    def test_chunked_equals_single_batch_bitwise(self):
        rows, labels = _sparse_rows(16, nnz=3)  # equal nnz: equal padding
        a = LinearLearner(_vw_cfg())
        a.partial_fit(rows, labels)
        b = LinearLearner(_vw_cfg())
        for k in range(0, 16, 4):
            b.partial_fit(rows[k:k + 4], labels[k:k + 4])
        sa, sb = a.state_dict(), b.state_dict()
        assert sa["t"] == sb["t"]
        np.testing.assert_array_equal(sa["w"], sb["w"])
        np.testing.assert_array_equal(sa["g2"], sb["g2"])

    def test_state_dict_round_trip_continues_bitwise(self):
        rows, labels = _sparse_rows(12)
        a = LinearLearner(_vw_cfg())
        a.partial_fit(rows[:8], labels[:8])
        b = LinearLearner(_vw_cfg()).load_state_dict(a.state_dict())
        a.partial_fit(rows[8:], labels[8:])
        b.partial_fit(rows[8:], labels[8:])
        np.testing.assert_array_equal(a.state_dict()["w"],
                                      b.state_dict()["w"])
        np.testing.assert_array_equal(a.state_dict()["g2"],
                                      b.state_dict()["g2"])

    def test_ftrl_state_round_trip(self):
        cfg = LearnerConfig(num_bits=8, ftrl=True,
                            loss_function="logistic")
        rows, labels = _sparse_rows(8)
        a = LinearLearner(cfg)
        a.partial_fit(rows, labels)
        sd = a.state_dict()
        assert sd["kind"] == "ftrl"
        b = LinearLearner(cfg).load_state_dict(sd)
        np.testing.assert_array_equal(a.weights, b.weights)
        with pytest.raises(ValueError):
            LinearLearner(_vw_cfg()).load_state_dict(sd)  # kind mismatch

    def test_predict_shape(self):
        rows, labels = _sparse_rows(6)
        lr = LinearLearner(_vw_cfg())
        lr.partial_fit(rows, labels)
        assert lr.predict(rows).shape == (6,)
        assert lr.examples_seen == 6


class _FakePlane:
    def __init__(self):
        self.deployed = []

    def attach_online(self, trainer):
        pass

    def deploy(self, transform, **kw):
        self.deployed.append((transform, kw))


class TestOnlineTrainer:
    def _trainer(self, tmp_path, adapter=None, **kw):
        adapter = adapter or VWOnlineAdapter(_vw_cfg())
        kw.setdefault("batch_rows", 4)
        return OnlineTrainer(adapter, str(tmp_path / "fb.jsonl"),
                             str(tmp_path / "ck.json"), **kw)

    def test_feed_then_train_full_batches_only(self, tmp_path):
        t = self._trainer(tmp_path)
        rows, labels = _sparse_rows(10)
        t.feed(rows, labels)
        assert t.pending() == 10
        steps = t.train_pending()
        assert steps == 2  # two full batches of 4; 2 rows remain
        assert t.consumed == 8 and t.pending() == 2
        assert t.train_pending(flush=True) == 1
        assert t.consumed == 10
        t.stop()

    def test_checkpoint_resume_is_bitwise(self, tmp_path):
        """Kill at checkpoint k, resume, replay -> state bitwise-equal to
        the uninterrupted run (the acceptance contract)."""
        rows, labels = _sparse_rows(16)

        # uninterrupted reference
        ref = OnlineTrainer(VWOnlineAdapter(_vw_cfg()),
                            str(tmp_path / "ref.jsonl"),
                            str(tmp_path / "ref.ck"), batch_rows=4)
        ref.feed(rows, labels)
        ref.train_pending()
        ref_state = ref.adapter.to_json(ref.state)
        ref.stop()

        # interrupted run: fold 2 steps (checkpointing each), then "crash"
        t1 = OnlineTrainer(VWOnlineAdapter(_vw_cfg()),
                           str(tmp_path / "fb.jsonl"),
                           str(tmp_path / "ck.json"), batch_rows=4)
        t1.feed(rows, labels)
        t1.train_pending(max_steps=2)
        assert t1.step == 2
        t1.journal.close()  # crash: no stop(), state object dropped

        # a fresh process resumes from the checkpoint and replays the tail
        t2 = OnlineTrainer(VWOnlineAdapter(_vw_cfg()),
                           str(tmp_path / "fb.jsonl"),
                           str(tmp_path / "ck.json"), batch_rows=4)
        assert t2.resume() is True
        assert t2.step == 2 and t2.consumed == 8
        t2.train_pending()
        assert t2.consumed == 16
        assert t2.adapter.to_json(t2.state) == ref_state  # bitwise
        t2.stop()

    def test_resume_without_checkpoint_replays_from_scratch(self, tmp_path):
        t = self._trainer(tmp_path, checkpoint_every=100)  # never ckpts
        rows, labels = _sparse_rows(8)
        t.feed(rows, labels)
        t.train_pending()
        state = t.adapter.to_json(t.state)
        t.journal.close()
        t2 = self._trainer(tmp_path, checkpoint_every=100)
        assert t2.resume() is False
        t2.train_pending()
        assert t2.adapter.to_json(t2.state) == state
        t2.stop()

    def test_bad_checkpoint_format_rejected(self, tmp_path):
        t = self._trainer(tmp_path)
        with open(t.checkpoint_path, "w", encoding="utf-8") as fh:
            json.dump({"format": "something_else"}, fh)
        with pytest.raises(ValueError):
            t.resume()
        t.stop()

    def test_publish_hands_off_to_plane(self, tmp_path):
        plane = _FakePlane()
        t = self._trainer(tmp_path, publish_after=8)
        t.attach_plane(plane)
        rows, labels = _sparse_rows(8)
        t.feed(rows, labels)
        t.train_pending()
        assert t.published == 1
        (transform, kw), = plane.deployed
        assert kw["version"] == "online-2"
        assert kw["digest"].startswith("o:")
        assert kw["cost"] == {"examples": 8}
        # the published transform serves sparse-row bodies
        bodies = np.asarray([json.dumps(r).encode() for r in rows[:2]],
                            dtype=object)
        out = transform(DataFrame.from_dict(
            {"id": np.asarray([0, 1]), "value": bodies}))
        assert len(out.collect()["reply"]) == 2
        t.stop()

    def test_publish_failure_counted_not_fatal(self, tmp_path):
        class Boom(_FakePlane):
            def deploy(self, transform, **kw):
                raise ValueError("rollout already active")

        t = self._trainer(tmp_path, publish_after=4)
        t.attach_plane(Boom())
        rows, labels = _sparse_rows(4)
        t.feed(rows, labels)
        assert t.train_pending() == 1
        assert t.publish_failed == 1 and t.published == 0
        t.stop()

    def test_gbdt_adapter_refit_and_resume(self, tmp_path):
        adapter = GBDTRefitAdapter(max_rows=64)
        rng = np.random.default_rng(3)
        rows = [[float(v) for v in rng.normal(size=3)] for _ in range(24)]
        labels = [float(r[0] > 0) for r in rows]
        t = OnlineTrainer(adapter, str(tmp_path / "fb.jsonl"),
                          str(tmp_path / "ck.json"), batch_rows=8)
        t.feed(rows, labels)
        t.train_pending(max_steps=1)
        t.journal.close()
        t2 = OnlineTrainer(GBDTRefitAdapter(max_rows=64),
                           str(tmp_path / "fb.jsonl"),
                           str(tmp_path / "ck.json"), batch_rows=8)
        assert t2.resume() is True
        t2.train_pending()
        assert t2.state["y"] == labels  # the buffer IS the state
        transform = t2.adapter.make_transform(t2.state)
        bodies = np.asarray([json.dumps(r).encode() for r in rows[:4]],
                            dtype=object)
        out = transform(DataFrame.from_dict(
            {"id": np.asarray([0, 1, 2, 3]), "value": bodies}))
        assert len(out.collect()["reply"]) == 4
        t2.stop()

    def test_gbdt_buffer_bounded(self):
        adapter = GBDTRefitAdapter(max_rows=4)
        state = adapter.fresh()
        for i in range(10):
            adapter.step(state, [[float(i)]], [float(i)])
        assert state["y"] == [6.0, 7.0, 8.0, 9.0]

    def test_gbdt_adapter_accepts_scalar_rows(self):
        # the header-labeled feedback path journals whatever the request
        # column held — a scalar feature must fold, not crash training
        adapter = GBDTRefitAdapter()
        state = adapter.step(adapter.fresh(), [3.0, {"values": 4.0}, [5.0]],
                             [1.0, 2.0, 3.0])
        assert state["X"] == [[3.0], [4.0], [5.0]]

    def test_summary_serializes(self, tmp_path):
        t = self._trainer(tmp_path)
        json.dumps(t.summary())
        t.stop()


# ---------------------------------------------------------------------------
# ONNX identity: cross-process digest stability (satellite)
# ---------------------------------------------------------------------------

def _tiny_onnx_blob():
    import mmlspark_tpu.onnx.proto as proto

    w = np.arange(12, dtype=np.float32).reshape(4, 3)
    return proto.make_model(
        [proto.make_node("Gemm", ["input", "w"], ["out"], name="g",
                         transB=1)],
        [proto.make_tensor("w", w)],
        [proto.make_value_info("input", [None, 3])],
        [proto.make_value_info("out", [None, 4])])


_DIGEST_SNIPPET = """
import sys
sys.path.insert(0, {repo!r})
from mmlspark_tpu.onnx import import_onnx
fm = import_onnx({path!r})
print(fm.cache_token())
"""


class TestOnnxDigest:
    def test_cache_token_stable_across_processes(self, tmp_path):
        """Two fresh interpreters (fresh PYTHONHASHSEED) agree on the
        imported model's cache_token — the digest the registry and the
        fleet's persistent compile cache both key on."""
        path = str(tmp_path / "m.onnx")
        with open(path, "wb") as fh:
            fh.write(_tiny_onnx_blob())
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        code = _DIGEST_SNIPPET.format(repo=repo, path=path)
        tokens = []
        for seed in ("1", "2"):
            env = dict(os.environ, PYTHONHASHSEED=seed,
                       JAX_PLATFORMS="cpu")
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, text=True,
                                  timeout=300, env=env)
            assert proc.returncode == 0, proc.stderr
            tokens.append(proc.stdout.strip().splitlines()[-1])
        assert tokens[0] == tokens[1]
        assert tokens[0].startswith("m:")

    def test_imported_model_registers_as_candidate(self, tmp_path):
        from mmlspark_tpu.onnx import import_onnx

        path = str(tmp_path / "m.onnx")
        with open(path, "wb") as fh:
            fh.write(_tiny_onnx_blob())
        fm = import_onnx(path)
        reg = ModelRegistry()
        ver = reg.register(fm, stage=fm)
        assert ver.state == CANDIDATE
        assert ver.digest == fm.cache_token()
        # re-importing the same bytes yields the same structural digest
        fm2 = import_onnx(path)
        assert structural_digest(fm2) == ver.digest


# ---------------------------------------------------------------------------
# Serving integration
# ---------------------------------------------------------------------------

def _post(address, body, headers=None):
    req = urllib.request.Request(address, data=body, method="POST",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=15) as resp:
        return resp.status, resp.read()


_E2E_CFG = {"shadow_fraction": 1.0, "shadow_min_scored": 3,
            "steps": (1.0,), "hold_s": 0.0, "min_step_requests": 1,
            "check_interval_s": 0.0, "objective_ms": 60_000.0}


class TestServingIntegration:
    def test_models_endpoint_and_stats_and_metrics(self):
        from mmlspark_tpu.serving.server import ServingServer

        srv = ServingServer(_echo, port=0, max_wait_ms=1.0,
                            lifecycle=True)
        with srv:
            base = f"http://127.0.0.1:{srv.port}"
            _post(srv.address, b'{"x":1}')
            models = json.loads(urllib.request.urlopen(
                base + "/_mmlspark/models", timeout=15).read())
            stats = json.loads(urllib.request.urlopen(
                base + "/_mmlspark/stats", timeout=15).read())
            metrics = urllib.request.urlopen(
                base + "/_mmlspark/metrics", timeout=15).read().decode()
        assert models["registry"]["live"] is not None
        assert models["registry"]["versions"][0]["state"] == LIVE
        assert "lifecycle" in stats
        assert "mmlspark_model_info" in metrics
        assert "mmlspark_model_requests_total" in metrics
        assert "mmlspark_model_transitions_total" in metrics

    def test_models_404_when_disabled(self):
        from mmlspark_tpu.serving.server import ServingServer

        srv = ServingServer(_echo, port=0, max_wait_ms=1.0)
        with srv:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/_mmlspark/models",
                    timeout=15)
            assert e.value.code == 404

    def test_lifecycle_false_is_bitwise_identical(self):
        """lifecycle=False (the default) serves byte-identical replies and
        an identical stats/metrics surface to a server built without the
        knob — the conditional-emission parity contract."""
        from mmlspark_tpu.serving.server import ServingServer

        bodies = [json.dumps({"i": i}).encode() for i in range(4)]

        def collect(srv):
            replies = []
            with srv:
                for b in bodies:
                    replies.append(_post(srv.address, b)[1])
                base = f"http://127.0.0.1:{srv.port}"
                stats = json.loads(urllib.request.urlopen(
                    base + "/_mmlspark/stats", timeout=15).read())
                metrics = urllib.request.urlopen(
                    base + "/_mmlspark/metrics",
                    timeout=15).read().decode()
            return replies, stats, metrics

        off = ServingServer(_echo, port=0, max_wait_ms=1.0,
                            lifecycle=False)
        plain = ServingServer(_echo, port=0, max_wait_ms=1.0)
        r_off, s_off, m_off = collect(off)
        r_plain, _s_plain, m_plain = collect(plain)
        assert r_off == r_plain
        assert off._lifecycle is None
        assert "lifecycle" not in s_off
        assert "mmlspark_model_" not in m_off
        def names(exposition):
            return sorted(ln.split("{")[0].split(" ")[0]
                          for ln in exposition.splitlines()
                          if ln and not ln.startswith("#"))

        assert names(m_off) == names(m_plain)

    def test_e2e_shadow_canary_promote(self):
        """The acceptance rollout: a byte-identical candidate moves
        shadow -> canary -> live through a LIVE server while every client
        reply stays exactly the incumbent's bytes; shadow counters prove
        traffic was duplicated with zero client effect."""
        from mmlspark_tpu.serving.server import ServingServer

        srv = ServingServer(_echo, port=0, max_wait_ms=1.0,
                            lifecycle=dict(_E2E_CFG))
        with srv:
            plane = srv._lifecycle
            assert isinstance(plane, LifecyclePlane)
            assert srv.transform is plane
            plane.deploy(_echo_twin, version="cand")
            cand = plane.registry.get("cand")
            deadline = time.monotonic() + 30.0
            i = 0
            while time.monotonic() < deadline:
                body = b"payload-%d" % i
                status, reply = _post(srv.address, body)
                assert (status, reply) == (200, body)
                i += 1
                if plane.registry.live.version == "cand":
                    break
                time.sleep(0.01)
            assert plane.registry.live.version == "cand"
            assert plane.registry.versions()[0].state == RETIRED
            assert cand.shadow_issued > 0 and cand.shadow_scored > 0
            assert cand.shadow_divergent == 0
            assert plane.controller.promotions == 1
            # and the promoted model keeps serving bitwise
            status, reply = _post(srv.address, b"after-promote")
            assert (status, reply) == (200, b"after-promote")

    def test_e2e_divergent_candidate_rolled_back(self):
        """The inverse: a diverging candidate is auto-rolled-back and the
        incumbent's replies never change."""
        from mmlspark_tpu.serving.server import ServingServer

        srv = ServingServer(_echo, port=0, max_wait_ms=1.0,
                            lifecycle=dict(_E2E_CFG))
        with srv:
            plane = srv._lifecycle
            plane.deploy(_diverging, version="bad")
            cand = plane.registry.get("bad")
            deadline = time.monotonic() + 30.0
            i = 0
            while time.monotonic() < deadline:
                body = b"p-%d" % i
                status, reply = _post(srv.address, body)
                assert (status, reply) == (200, body)  # incumbent bytes
                i += 1
                if cand.state == ROLLED_BACK:
                    break
                time.sleep(0.01)
            assert cand.state == ROLLED_BACK
            assert plane.registry.live.version != "bad"
            assert plane.controller.rollbacks == 1
            status, reply = _post(srv.address, b"still-fine")
            assert (status, reply) == (200, b"still-fine")

    def test_feedback_endpoint_and_label_header(self, tmp_path):
        from mmlspark_tpu.serving.server import ServingServer
        from mmlspark_tpu.serving.lifecycle import LABEL_HEADER

        srv = ServingServer(_echo, port=0, max_wait_ms=1.0,
                            lifecycle=True)
        with srv:
            trainer = OnlineTrainer(VWOnlineAdapter(_vw_cfg()),
                                    str(tmp_path / "fb.jsonl"),
                                    batch_rows=4)
            trainer.attach_plane(srv._lifecycle)
            rows, labels = _sparse_rows(3)
            status, body = _post(
                f"http://127.0.0.1:{srv.port}/_mmlspark/feedback",
                json.dumps({"rows": rows, "labels": labels}).encode())
            assert status == 200
            assert json.loads(body)["journaled"] == 3
            assert trainer.pending() == 3
            # in-band: a labeled prediction request is ALSO an example
            status, reply = _post(
                srv.address, json.dumps(rows[0]).encode(),
                {LABEL_HEADER: "1.0"})
            assert status == 200
            deadline = time.monotonic() + 10.0
            while trainer.pending() < 4 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert trainer.pending() == 4
            trainer.stop()

    def test_feedback_404_when_disabled(self):
        from mmlspark_tpu.serving.server import ServingServer

        srv = ServingServer(_echo, port=0, max_wait_ms=1.0)
        with srv:
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"http://127.0.0.1:{srv.port}/_mmlspark/feedback",
                      b'{"rows": [], "labels": []}')
            assert e.value.code == 404

    def test_serve_pipeline_wires_hooks(self):
        from mmlspark_tpu.serving.server import serve_pipeline
        from mmlspark_tpu.stages.basic import UDFTransformer

        stage = UDFTransformer(
            inputCol="data", outputCol="out",
            udf=lambda v: float(np.asarray(v).sum()))
        srv = serve_pipeline(stage, input_col="data", port=0,
                             max_wait_ms=0.0, lifecycle=True)
        try:
            assert srv._lifecycle_spec is True
            assert srv._lifecycle_hooks["live_stage"] is stage
            assert callable(srv._lifecycle_hooks["warm"])
        finally:
            srv.stop()

    def test_serve_pipeline_end_to_end(self):
        from mmlspark_tpu.serving.server import serve_pipeline
        from mmlspark_tpu.stages.basic import UDFTransformer

        stage = UDFTransformer(
            inputCol="data", outputCol="out",
            udf=lambda v: float(np.asarray(v).sum()) * 2)
        srv = serve_pipeline(stage, input_col="data", port=0,
                             max_wait_ms=0.0, lifecycle=True)
        with srv:
            assert isinstance(srv.transform, LifecyclePlane)
            status, reply = _post(srv.address,
                                  json.dumps({"data": [1.0, 2.0]}).encode())
            assert (status, reply) == (200, b"6.0")
            models = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/_mmlspark/models",
                timeout=15).read())
            assert models["registry"]["versions"][0]["requests"]["live"] >= 1
