"""Datagen framework tests (reference VerifyGenerateDataset.scala parity) +
generated-data fuzzing of featurize stages."""

import numpy as np
import pytest

from mmlspark_tpu.testing import (
    ColumnOptions,
    GenConstraints,
    MissingOptions,
    RandomGenConstraints,
    generate_dataset,
    generate_like,
)


class TestGenerateDataset:
    def test_shape_matches_constraints(self):
        df = generate_dataset(GenConstraints(num_rows=37, num_cols=5), seed=1)
        assert len(df) == 37
        assert len(df.columns) == 5

    def test_same_seed_same_dataset(self):
        a = generate_dataset(GenConstraints(num_rows=20, num_cols=4), seed=7)
        b = generate_dataset(GenConstraints(num_rows=20, num_cols=4), seed=7)
        assert a.columns == b.columns
        for c in a.columns:
            av, bv = a.column(c), b.column(c)
            assert all(
                (x is None and y is None) or np.array_equal(x, y)
                if isinstance(x, np.ndarray) else x == y or (x != x and y != y)
                for x, y in zip(av, bv))

    def test_different_seed_different_dataset(self):
        a = generate_dataset(GenConstraints(num_rows=50, num_cols=3), seed=1)
        b = generate_dataset(GenConstraints(num_rows=50, num_cols=3), seed=2)
        # column names are randomized, so differing names alone proves it
        assert a.columns != b.columns

    def test_random_constraints_resolve_in_range(self):
        spec = RandomGenConstraints(min_rows=5, max_rows=9, min_cols=2,
                                    max_cols=4)
        for seed in range(10):
            df = generate_dataset(spec, seed=seed)
            assert 5 <= len(df) <= 9
            assert 2 <= len(df.columns) <= 4

    def test_per_column_options_respected(self):
        df = generate_dataset(
            GenConstraints(num_rows=30, num_cols=2,
                           randomize_column_names=False),
            seed=3,
            per_column={0: ColumnOptions(data_kinds=("double",)),
                        1: ColumnOptions(data_kinds=("string",))})
        assert df.column("col_0").dtype == np.float64
        assert all(isinstance(v, str) for v in df.column("col_1"))

    def test_missing_injection_rate(self):
        opts = ColumnOptions(
            data_kinds=("double",),
            missing=MissingOptions(percent_missing=0.4,
                                   data_kinds=("double",)))
        df = generate_dataset(
            GenConstraints(num_rows=2000, num_cols=1,
                           randomize_column_names=False),
            seed=11, per_column={0: opts})
        col = df.column("col_0")
        frac = float(np.mean(np.isnan(col.astype(np.float64))))
        assert 0.3 < frac < 0.5  # ~40%

    def test_vector_columns(self):
        df = generate_dataset(
            GenConstraints(num_rows=10, num_cols=1, slots_per_col=(6,),
                           randomize_column_names=False),
            seed=5, per_column={0: ColumnOptions(column_kinds=("vector",))})
        col = df.column("col_0")
        assert all(isinstance(v, np.ndarray) and v.shape == (6,) for v in col)

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            ColumnOptions(data_kinds=("complex128",))

    def test_generate_like_matches_schema(self):
        from mmlspark_tpu.core.dataframe import DataFrame

        src = DataFrame.from_dict({
            "x": np.arange(5, dtype=np.float64),
            "label": np.array(["a", "b", "a", "b", "a"], dtype=object),
        })
        out = generate_like(src, num_rows=40, seed=9)
        assert out.columns == ["x", "label"]
        assert len(out) == 40
        assert out.column("x").dtype == np.float64
        assert all(isinstance(v, str) for v in out.column("label"))


class TestGeneratedDataFuzzing:
    """Featurize stages over randomly generated datasets — the reference's
    reason for the datagen framework (featurize fuzz suites)."""

    def test_clean_missing_data_over_generated(self):
        from mmlspark_tpu.featurize import CleanMissingData

        opts = ColumnOptions(
            data_kinds=("double",),
            missing=MissingOptions(percent_missing=0.3,
                                   data_kinds=("double",)))
        for seed in range(5):
            df = generate_dataset(
                GenConstraints(num_rows=50, num_cols=3,
                               randomize_column_names=False),
                seed=seed, per_column={i: opts for i in range(3)},
                num_partitions=2)
            cols = list(df.columns)
            model = CleanMissingData(inputCols=cols, outputCols=cols,
                                     cleaningMode="Mean").fit(df)
            out = model.transform(df)
            for c in cols:
                vals = out.column(c).astype(np.float64)
                assert not np.isnan(vals).any()

    def test_featurize_over_generated_mixed(self):
        from mmlspark_tpu.featurize import Featurize

        per_col = {0: ColumnOptions(data_kinds=("double",)),
                   1: ColumnOptions(data_kinds=("string",)),
                   2: ColumnOptions(data_kinds=("int",))}
        for seed in range(5):
            df = generate_dataset(
                GenConstraints(num_rows=30, num_cols=3,
                               randomize_column_names=False),
                seed=seed, per_column=per_col)
            model = Featurize(featureColumns={
                "features": list(df.columns)}).fit(df)
            out = model.transform(df)
            feats = out.column("features")
            assert len(feats) == 30
            widths = {np.asarray(v).shape for v in feats}
            assert len(widths) == 1  # consistent assembled width

    def test_value_indexer_over_generated_strings(self):
        from mmlspark_tpu.featurize import ValueIndexer

        for seed in range(5):
            df = generate_dataset(
                GenConstraints(num_rows=40, num_cols=1,
                               randomize_column_names=False),
                seed=seed,
                per_column={0: ColumnOptions(data_kinds=("string",))})
            model = ValueIndexer(inputCol="col_0", outputCol="idx").fit(df)
            out = model.transform(df)
            idx = out.column("idx")
            assert len(set(df.column("col_0"))) == len(set(int(i) for i in idx))
