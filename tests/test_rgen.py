"""R overlay generation (reference SparklyRWrapper/WrapperGenerator parity)."""

import re

import pytest

from mmlspark_tpu.codegen.docs import stage_inventory
from mmlspark_tpu.codegen.rgen import _r_name, generate_r_package


@pytest.fixture(scope="module")
def pkg(tmp_path_factory):
    out = tmp_path_factory.mktemp("rpkg")
    files = generate_r_package(str(out))
    return out, files


class TestRGen:
    def test_package_layout(self, pkg):
        out, files = pkg
        assert (out / "DESCRIPTION").exists()
        assert (out / "NAMESPACE").exists()
        assert (out / "R" / "mml_core.R").exists()
        assert (out / "R" / "stages.R").exists()
        assert len(files) == 4

    def test_every_stage_exported(self, pkg):
        """Reflection-enforced coverage: one export per registered stage."""
        out, _ = pkg
        ns = (out / "NAMESPACE").read_text()
        for name in stage_inventory():
            assert f"export({_r_name(name)})" in ns, name

    def test_every_stage_has_function_body(self, pkg):
        out, _ = pkg
        src = (out / "R" / "stages.R").read_text()
        for name in stage_inventory():
            assert f"{_r_name(name)} <- function(" in src, name
            assert f'.mml_run("{name}"' in src, name

    def test_r_source_is_balanced(self, pkg):
        """No R toolchain in this image: structural sanity instead — every
        emitted file has balanced braces/parens and roxygen export tags."""
        out, _ = pkg
        for rel in ("R/mml_core.R", "R/stages.R"):
            src = (out / rel).read_text()
            assert src.count("{") == src.count("}"), rel
            assert src.count("(") == src.count(")"), rel
        assert (out / "R" / "stages.R").read_text().count("#' @export") == \
            len(stage_inventory())

    def test_name_conversion(self):
        assert _r_name("LightGBMClassifier") == "mml_light_gbm_classifier"
        assert _r_name("SAR") == "mml_sar"
        assert _r_name("ValueIndexer") == "mml_value_indexer"
        assert _r_name("UDFTransformer") == "mml_udf_transformer"

    def test_params_become_arguments(self, pkg):
        out, _ = pkg
        src = (out / "R" / "stages.R").read_text()
        m = re.search(r"mml_light_gbm_classifier <- function\(([^)]*)\)", src)
        assert m, "wrapper missing"
        args = m.group(1)
        assert "numIterations = NULL" in args
        assert "labelCol = NULL" in args
