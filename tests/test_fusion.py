"""Device-resident pipeline fusion (core/fusion.py).

The load-bearing contract: ``PipelineModel.fuse()`` output is BITWISE
identical to the unfused stage-by-stage chain — same values, same dtypes,
same nulls — across image chains, featurize->GBDT, featurize->DNN, split
segments, and every fallback path. Plus: compile-cache reuse, bucketing,
profiler annotation, and the serving round trip.
"""

import json
import urllib.request

import numpy as np
import pytest

import jax

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.device_stage import CompileCache, compile_cache
from mmlspark_tpu.core.fusion import FusedPipelineModel, HostStage, Segment, plan
from mmlspark_tpu.core.pipeline import PipelineModel
from mmlspark_tpu.core.schema import ImageSchema
from mmlspark_tpu.featurize.assemble import FastVectorAssembler
from mmlspark_tpu.gbdt.stages import LightGBMClassifier, LightGBMRegressor
from mmlspark_tpu.image.featurizer import ImageFeaturizer
from mmlspark_tpu.image.stages import ImageTransformer, ResizeImageTransformer
from mmlspark_tpu.models.dnn_model import DNNModel
from mmlspark_tpu.models.module import (BatchNorm, Conv2D, Dense, FunctionModel,
                                        GlobalAvgPool, Sequential, relu)
from mmlspark_tpu.stages.basic import UDFTransformer


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------


def toy_cnn(size=16, c=3):
    mod = Sequential([("conv", Conv2D(8, (3, 3))), ("bn", BatchNorm()),
                      ("act", relu()), ("pool", GlobalAvgPool()),
                      ("head", Dense(4))], name="toycnn")
    params, _ = mod.init(jax.random.PRNGKey(0), (size, size, c))
    return FunctionModel(mod, params, (size, size, c),
                         layer_names=["head", "pool"], name="toycnn")


def toy_mlp(d_in=4):
    mod = Sequential([("d1", Dense(8)), ("act", relu()), ("d2", Dense(3))],
                     name="toymlp")
    params, _ = mod.init(jax.random.PRNGKey(1), (d_in,))
    return FunctionModel(mod, params, (d_in,), layer_names=["d2", "d1"],
                         name="toymlp")


def image_df(n=23, seed=3, parts=2, null_at=None):
    rng = np.random.default_rng(seed)
    rows = np.empty(n, dtype=object)
    for i in range(n):
        rows[i] = ImageSchema.make(
            rng.integers(0, 256, (20 + i % 3, 24, 3), dtype=np.uint8),
            f"img{i}")
    if null_at is not None:
        rows[null_at] = None
    return DataFrame.from_dict({"image": rows, "idx": np.arange(float(n))},
                               num_partitions=parts)


def tabular_df(n=120, seed=5, parts=3, dtype=np.float32):
    rng = np.random.default_rng(seed)
    a = rng.normal(size=n).astype(dtype)
    b = rng.normal(size=(n, 3)).astype(dtype)
    y = (a + b[:, 0] > 0).astype(np.float64)
    return DataFrame.from_dict(
        {"a": a, "b": [b[i] for i in range(n)], "label": y},
        num_partitions=parts)


def assert_bitwise(ref_df, got_df):
    """Exact equality: columns, row counts, values AND dtypes."""
    assert ref_df.columns == got_df.columns
    rc, gc = ref_df.collect(), got_df.collect()
    for name in ref_df.columns:
        a, b = rc[name], gc[name]
        assert len(a) == len(b), f"{name}: {len(a)} vs {len(b)} rows"
        if a.dtype != object and b.dtype != object:
            assert a.dtype == b.dtype, f"{name}: {a.dtype} vs {b.dtype}"
            np.testing.assert_array_equal(a, b, err_msg=name)
            continue
        for i, (x, y) in enumerate(zip(a, b)):
            if x is None or y is None:
                assert x is None and y is None, f"{name} row {i} null mismatch"
            elif ImageSchema.is_image(x) or ImageSchema.is_image(y):
                dx, dy = ImageSchema.to_array(x), ImageSchema.to_array(y)
                assert dx.dtype == dy.dtype, f"{name} row {i} image dtype"
                np.testing.assert_array_equal(dx, dy, err_msg=f"{name} row {i}")
                assert x["origin"] == y["origin"], f"{name} row {i} origin"
            elif isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
                x, y = np.asarray(x), np.asarray(y)
                assert x.dtype == y.dtype, \
                    f"{name} row {i}: {x.dtype} vs {y.dtype}"
                np.testing.assert_array_equal(x, y, err_msg=f"{name} row {i}")
            else:
                assert x == y, f"{name} row {i}: {x!r} != {y!r}"


def fused_of(pm, cache=None):
    return FusedPipelineModel(pm.stages, cache=cache or CompileCache())


# --------------------------------------------------------------------------
# bitwise parity across representative pipelines
# --------------------------------------------------------------------------


class TestBitwiseParity:
    def test_image_chain(self):
        df = image_df()
        pm = PipelineModel([
            ImageTransformer().resize(16, 16).flip(1).threshold(100.0, 255.0),
            ImageFeaturizer(scaleFactor=1 / 255., batchSize=8)
            .set_model(toy_cnn())])
        fused = fused_of(pm)
        assert_bitwise(pm.transform(df), fused.transform(df))
        stats = fused.fusion_stats()
        assert stats["n_fused_segments"] == 1
        assert stats["fallbacks"] == []
        seg = stats["segments"][0]
        assert seg["stages"] == ["ImageTransformer", "ImageFeaturizer"]

    def test_image_chain_with_null_and_dropna(self):
        df = image_df(null_at=7)
        pm = PipelineModel([
            ImageTransformer().resize(16, 16).flip(1),
            ImageFeaturizer(scaleFactor=1 / 255., batchSize=8, dropNa=True)
            .set_model(toy_cnn())])
        fused = fused_of(pm)
        ref, got = pm.transform(df), fused.transform(df)
        assert ref.count() == got.count() == 22  # the null row dropped
        assert_bitwise(ref, got)

    def test_resize_stage_heads_a_segment(self):
        df = image_df(n=11)
        pm = PipelineModel([
            ResizeImageTransformer(height=16, width=16, nChannels=3),
            ImageFeaturizer(scaleFactor=1 / 255., batchSize=8)
            .set_model(toy_cnn())])
        fused = fused_of(pm)
        assert_bitwise(pm.transform(df), fused.transform(df))
        assert fused.fusion_stats()["n_fused_segments"] == 1

    def test_featurize_gbdt_classifier(self):
        df = tabular_df()
        asm = FastVectorAssembler(inputCols=["a", "b"])
        model = LightGBMClassifier(labelCol="label", numIterations=8,
                                   numLeaves=7).fit(asm.transform(df))
        pm = PipelineModel([asm, model])
        fused = fused_of(pm)
        assert_bitwise(pm.transform(df), fused.transform(df))
        assert fused.fusion_stats()["fallbacks"] == []

    def test_featurize_gbdt_regressor(self):
        df = tabular_df(seed=6)
        asm = FastVectorAssembler(inputCols=["a", "b"])
        model = LightGBMRegressor(labelCol="label", numIterations=5) \
            .fit(asm.transform(df))
        pm = PipelineModel([asm, model])
        assert_bitwise(pm.transform(df), fused_of(pm).transform(df))

    def test_featurize_dnn(self):
        df = tabular_df(seed=7)
        asm = FastVectorAssembler(inputCols=["a", "b"])
        dnn = DNNModel(inputCol="features", outputCol="emb", batchSize=16)
        dnn.set_model(toy_mlp())
        pm = PipelineModel([asm, dnn])
        fused = fused_of(pm)
        assert_bitwise(pm.transform(df), fused.transform(df))
        seg = fused.fusion_stats()["segments"][0]
        assert seg["stages"] == ["FastVectorAssembler", "DNNModel"]

    def test_dnn_null_rows_propagate(self):
        rng = np.random.default_rng(9)
        rows = np.empty(20, dtype=object)
        for i in range(20):
            rows[i] = rng.normal(size=4).astype(np.float32)
        rows[3] = None
        df = DataFrame.from_dict({"x": rows}, num_partitions=2)
        dnn = DNNModel(inputCol="x", outputCol="emb", batchSize=8)
        dnn.set_model(toy_mlp())
        pm = PipelineModel([dnn])
        ref, got = pm.transform(df), fused_of(pm).transform(df)
        assert got.collect()["emb"][3] is None
        assert_bitwise(ref, got)

    def test_udf_device_mirror_fuses(self):
        rng = np.random.default_rng(11)
        rows = np.empty(30, dtype=object)
        for i in range(30):
            rows[i] = rng.normal(size=4).astype(np.float32)
        df = DataFrame.from_dict({"x": rows}, num_partitions=2)

        def host_double(col):
            out = np.empty(len(col), dtype=object)
            for i, v in enumerate(col):
                out[i] = v * np.float32(2.0)
            return out

        udf = UDFTransformer(inputCol="x", outputCol="x2",
                             vectorizedUdf=host_double,
                             deviceUdf=lambda x: x * np.float32(2.0))
        dnn = DNNModel(inputCol="x2", outputCol="emb", batchSize=8)
        dnn.set_model(toy_mlp())
        pm = PipelineModel([udf, dnn])
        fused = fused_of(pm)
        assert_bitwise(pm.transform(df), fused.transform(df))
        seg = fused.fusion_stats()["segments"][0]
        assert seg["stages"] == ["UDFTransformer", "DNNModel"]

    def test_transform_fused_kwarg(self):
        df = tabular_df(seed=8)
        asm = FastVectorAssembler(inputCols=["a", "b"])
        dnn = DNNModel(inputCol="features", outputCol="emb", batchSize=16)
        dnn.set_model(toy_mlp())
        pm = PipelineModel([asm, dnn])
        assert_bitwise(pm.transform(df), pm.transform(df, fused=True))
        assert pm.fuse() is pm.fuse()  # cached runner


# --------------------------------------------------------------------------
# planning: splits, demotion, terminal stages
# --------------------------------------------------------------------------


class TestPlanning:
    def test_host_stage_splits_segment(self):
        df = tabular_df(seed=12)
        asm = FastVectorAssembler(inputCols=["a", "b"])

        def host_sum(col):
            return np.asarray([float(v.sum()) for v in col], dtype=np.float64)

        udf = UDFTransformer(inputCol="features", outputCol="fsum",
                             vectorizedUdf=host_sum)  # no device mirror
        dnn = DNNModel(inputCol="features", outputCol="emb", batchSize=16)
        dnn.set_model(toy_mlp())
        pm = PipelineModel([asm, udf, dnn])
        fused = fused_of(pm)
        nodes = fused._plan_for(df.schema)
        kinds = [type(n).__name__ for n in nodes]
        # the host-only UDF splits; the lone assembler run is demoted to
        # host (no heavy stage to amortize a device round trip)
        assert kinds == ["HostStage", "HostStage", "Segment"]
        assert_bitwise(pm.transform(df), fused.transform(df))

    def test_light_only_segment_demoted(self):
        df = tabular_df(seed=13)
        asm = FastVectorAssembler(inputCols=["a", "b"])
        nodes = plan([asm], df.schema.copy())
        assert all(isinstance(n, HostStage) for n in nodes)

    def test_gbdt_is_terminal(self):
        df = tabular_df(seed=14)
        asm = FastVectorAssembler(inputCols=["a", "b"])
        model = LightGBMRegressor(labelCol="label", numIterations=3) \
            .fit(asm.transform(df))
        dnn = DNNModel(inputCol="features", outputCol="emb", batchSize=16)
        dnn.set_model(toy_mlp())
        nodes = plan([asm, model, dnn], df.schema.copy())
        segs = [n for n in nodes if isinstance(n, Segment)]
        # GBDT finalizes on host (f64 objective math) => ends its segment
        assert [s.describe()["stages"] for s in segs] == \
            [["FastVectorAssembler", "LightGBMRegressionModel"], ["DNNModel"]]

    def test_image_host_prefix_op_starts_new_segment(self):
        # a mid-chain resize cannot replay on device-resident input: the
        # planner must split rather than silently lose exactness
        df = image_df(n=9)
        t1 = ImageTransformer().resize(16, 16).flip(1)
        t2 = ImageTransformer().resize(8, 8)  # host-prep op, internal input
        feat = ImageFeaturizer(scaleFactor=1 / 255., batchSize=8) \
            .set_model(toy_cnn(size=8))
        pm = PipelineModel([t1, t2, feat])
        fused = fused_of(pm)
        nodes = fused._plan_for(df.schema)
        # t2's host-prep resize cannot consume t1's device output: t1 is cut
        # off (and, alone, demoted to host); t2 heads the fused segment
        assert [type(n).__name__ for n in nodes] == ["HostStage", "Segment"]
        assert nodes[1].describe()["stages"] == \
            ["ImageTransformer", "ImageFeaturizer"]
        assert_bitwise(pm.transform(df), fused.transform(df))


# --------------------------------------------------------------------------
# fallbacks: anything the bitwise contract cannot hold for -> host path
# --------------------------------------------------------------------------


class TestFallbacks:
    def test_f64_inputs_fall_back(self):
        df = tabular_df(seed=15, dtype=np.float64)
        asm = FastVectorAssembler(inputCols=["a", "b"])
        model = LightGBMRegressor(labelCol="label", numIterations=4) \
            .fit(asm.transform(df))
        pm = PipelineModel([asm, model])
        fused = fused_of(pm)
        assert_bitwise(pm.transform(df), fused.transform(df))
        assert any("dtype gate" in f for f in fused.fusion_stats()["fallbacks"])

    def test_sparse_rows_fall_back(self):
        rng = np.random.default_rng(16)
        n = 40
        dense = rng.normal(size=(n, 4)).astype(np.float64)
        y = (dense[:, 0] > 0).astype(np.float64)
        feats = np.empty(n, dtype=object)
        for i in range(n):
            feats[i] = {"indices": np.array([0, 2]),
                        "values": dense[i, [0, 2]], "size": 4}
        df_fit = DataFrame.from_dict(
            {"features": [dense[i] for i in range(n)], "label": y})
        model = LightGBMClassifier(labelCol="label", numIterations=4,
                                   numLeaves=5).fit(df_fit)
        df = DataFrame.from_dict({"features": feats}, num_partitions=2)
        pm = PipelineModel([model])
        fused = fused_of(pm)
        assert_bitwise(pm.transform(df), fused.transform(df))
        assert any("sparse" in f for f in fused.fusion_stats()["fallbacks"])

    def test_ragged_rows_fall_back(self):
        rng = np.random.default_rng(17)
        rows = np.empty(12, dtype=object)
        for i in range(12):
            rows[i] = rng.normal(size=4 if i % 2 else 5).astype(np.float32)
        df = DataFrame.from_dict({"x": rows})
        dnn = DNNModel(inputCol="x", outputCol="emb", batchSize=8)
        dnn.set_model(toy_mlp())
        pm = PipelineModel([dnn])
        fused = fused_of(pm)
        with pytest.raises(ValueError):
            pm.transform(df).collect()  # unfused raises on ragged rows too
        with pytest.raises(ValueError):
            fused.transform(df).collect()

    def test_shape_mismatch_falls_back_to_host(self):
        # featurizer fed 8x8 device batches but backbone wants 16x16: the
        # trace gate fires and the segment reruns on host (bitwise anyway)
        df = image_df(n=9)
        t1 = ImageTransformer().resize(8, 8).flip(1)
        feat = ImageFeaturizer(scaleFactor=1 / 255., batchSize=8) \
            .set_model(toy_cnn(size=16))
        pm = PipelineModel([t1, feat])
        fused = fused_of(pm)
        assert_bitwise(pm.transform(df), fused.transform(df))
        assert len(fused.fusion_stats()["fallbacks"]) > 0


# --------------------------------------------------------------------------
# compile cache + bucketing
# --------------------------------------------------------------------------


class TestCompileCache:
    def test_executables_reused_across_calls(self):
        df = tabular_df(seed=18)
        asm = FastVectorAssembler(inputCols=["a", "b"])
        dnn = DNNModel(inputCol="features", outputCol="emb", batchSize=16)
        dnn.set_model(toy_mlp())
        cache = CompileCache()
        fused = fused_of(PipelineModel([asm, dnn]), cache=cache)
        fused.transform(df)  # warmup: compiles
        warm = cache.stats()
        assert warm["misses"] >= 1
        for _ in range(3):
            fused.transform(df)
        stats = cache.stats()
        assert stats["misses"] == warm["misses"]  # no recompiles
        post = ((stats["hits"] - warm["hits"])
                / max((stats["hits"] - warm["hits"])
                      + (stats["misses"] - warm["misses"]), 1))
        assert post >= 0.9  # acceptance: hit rate after warmup
        assert stats["compile_time_s"] > 0

    def test_bucketed_shapes_bound_compiles(self):
        # ragged partition tails pad to power-of-two buckets: many partition
        # sizes, O(log batch) compiled shapes
        rng = np.random.default_rng(19)
        cache = CompileCache()
        dnn = DNNModel(inputCol="x", outputCol="emb", batchSize=16)
        dnn.set_model(toy_mlp())
        fused = fused_of(PipelineModel([dnn]), cache=cache)
        for n in (5, 9, 16, 23, 31, 37):
            rows = np.empty(n, dtype=object)
            for i in range(n):
                rows[i] = rng.normal(size=4).astype(np.float32)
            fused.transform(DataFrame.from_dict({"x": rows}))
        # buckets: 8, 16 (and full 16-batches) => at most 3 distinct shapes
        assert cache.entries <= 3

    def test_global_cache_shared(self):
        assert compile_cache() is compile_cache()


# --------------------------------------------------------------------------
# observability: profiler annotations + stats surfaces
# --------------------------------------------------------------------------


class TestObservability:
    def test_annotate_named_per_segment(self, monkeypatch):
        from mmlspark_tpu.core import fusion as fusion_mod

        seen = []
        import contextlib

        @contextlib.contextmanager
        def recording_annotate(name):
            seen.append(name)
            yield

        monkeypatch.setattr(fusion_mod.profiling, "annotate",
                            recording_annotate)
        df = tabular_df(seed=20)
        asm = FastVectorAssembler(inputCols=["a", "b"])
        dnn = DNNModel(inputCol="features", outputCol="emb", batchSize=16)
        dnn.set_model(toy_mlp())
        fused = fused_of(PipelineModel([asm, dnn]))
        fused.transform(df)
        assert any(s == "fused:FastVectorAssembler+DNNModel" for s in seen)

    def test_ingest_stats_surface(self):
        df = tabular_df(seed=21)
        asm = FastVectorAssembler(inputCols=["a", "b"])
        dnn = DNNModel(inputCol="features", outputCol="emb", batchSize=16)
        dnn.set_model(toy_mlp())
        fused = fused_of(PipelineModel([asm, dnn]))
        assert fused.last_ingest_stats is None
        fused.transform(df)
        summary = fused.last_ingest_stats.summary()
        assert summary["rows"] == df.count()
        assert summary["bytes"] > 0
        per_seg = fused.fusion_stats()["per_segment"]
        assert list(per_seg) == ["FastVectorAssembler+DNNModel"]

    def test_fused_model_not_registered_and_saves_plain(self, tmp_path):
        from mmlspark_tpu.core.pipeline import (PipelineStage,
                                                registered_stages)

        assert "FusedPipelineModel" not in registered_stages()
        dnn = DNNModel(inputCol="x", outputCol="emb", batchSize=8)
        dnn.set_model(toy_mlp())
        fused = fused_of(PipelineModel([dnn]))
        path = str(tmp_path / "fused_pm")
        fused.save(path)
        loaded = PipelineStage.load(path)
        assert type(loaded) is PipelineModel  # fusion is not persisted
        rng = np.random.default_rng(22)
        rows = np.empty(6, dtype=object)
        for i in range(6):
            rows[i] = rng.normal(size=4).astype(np.float32)
        df = DataFrame.from_dict({"x": rows})
        assert_bitwise(loaded.transform(df), fused.transform(df))


# --------------------------------------------------------------------------
# serving round trip
# --------------------------------------------------------------------------


class TestServingFused:
    def test_round_trip_and_stats(self):
        from mmlspark_tpu.serving.server import serve_pipeline

        dnn = DNNModel(inputCol="x", outputCol="reply", batchSize=8)
        dnn.set_model(toy_mlp())
        pm = PipelineModel([dnn])
        server = serve_pipeline(pm, input_col="x", reply_col="reply",
                                parse="json", port=0, fused=True)
        with server:
            body = json.dumps([0.5, -1.0, 2.0, 0.25]).encode("utf-8")
            req = urllib.request.Request(server.address, data=body,
                                         method="POST")
            with urllib.request.urlopen(req, timeout=30) as resp:
                got = np.asarray(json.loads(resp.read()), dtype=np.float32)
            # oracle: the unfused chain on the same parsed payload
            x = np.empty(1, dtype=object)
            x[0] = np.asarray([0.5, -1.0, 2.0, 0.25], dtype=np.float64)
            ref = pm.transform(DataFrame.from_dict({"x": x})) \
                .collect()["reply"][0]
            np.testing.assert_array_equal(ref, got)
            stats_url = server.address.rstrip("/") + "/_mmlspark/stats"
            with urllib.request.urlopen(stats_url, timeout=10) as resp:
                stats = json.loads(resp.read())
        assert "fusion" in stats
        assert stats["fusion"]["n_fused_segments"] == 1
        assert stats["fusion"]["compile_cache"]["hits"] >= 1
