"""IO + serving tests: binary/image readers, HTTP stack, real localhost serving."""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.io import (
    BinaryFileReader,
    HTTPRequestData,
    HTTPResponseData,
    HTTPTransformer,
    JSONInputParser,
    JSONOutputParser,
    PartitionConsolidator,
    SimpleHTTPTransformer,
    StringOutputParser,
    read_binary_files,
    read_images,
    send_with_retries,
)
from mmlspark_tpu.ops.image import encode_ppm
from mmlspark_tpu.serving import ServingServer, serve_pipeline


@pytest.fixture
def echo_server():
    """Real localhost HTTP server (reference test strategy: HTTPv2Suite spins
    real servers)."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        fail_first = {"count": 0}

        def log_message(self, *args):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
            if self.path == "/double":
                data = json.loads(body)
                reply = json.dumps({"result": [2 * v for v in data["values"]]})
            elif self.path == "/flaky":
                Handler.fail_first["count"] += 1
                if Handler.fail_first["count"] % 2 == 1:
                    self.send_response(503)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                reply = json.dumps({"ok": True})
            else:
                reply = body.decode("utf-8")
            payload = reply.encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


class TestBinaryReader:
    def test_read_tree(self, tmp_path):
        (tmp_path / "sub").mkdir()
        (tmp_path / "a.bin").write_bytes(b"aaa")
        (tmp_path / "sub" / "b.bin").write_bytes(b"bbbb")
        df = read_binary_files(str(tmp_path))
        assert df.count() == 2
        rows = {r["path"].split("/")[-1]: r["bytes"] for r in df.rows()}
        assert rows["a.bin"] == b"aaa" and rows["b.bin"] == b"bbbb"

    def test_non_recursive_and_pattern(self, tmp_path):
        (tmp_path / "sub").mkdir()
        (tmp_path / "a.txt").write_bytes(b"x")
        (tmp_path / "b.bin").write_bytes(b"y")
        (tmp_path / "sub" / "c.txt").write_bytes(b"z")
        df = (BinaryFileReader().option("recursive", False)
              .option("pattern", "*.txt").load(str(tmp_path)))
        assert df.count() == 1

    def test_zip_inspection(self, tmp_path):
        import zipfile
        zp = tmp_path / "arch.zip"
        with zipfile.ZipFile(zp, "w") as z:
            z.writestr("inner1.dat", b"123")
            z.writestr("inner2.dat", b"4567")
        df = read_binary_files(str(tmp_path))
        assert df.count() == 2
        assert all("arch.zip/" in r["path"] for r in df.rows())

    def test_sampling(self, tmp_path):
        for i in range(50):
            (tmp_path / f"f{i}.bin").write_bytes(b"x")
        df = read_binary_files(str(tmp_path), sample_ratio=0.3, inspect_zip=False)
        assert 3 <= df.count() <= 30


class TestImageReader:
    def test_read_and_decode(self, tmp_path):
        rng = np.random.default_rng(0)
        for i in range(3):
            img = rng.integers(0, 255, (10, 8, 3), dtype=np.uint8)
            (tmp_path / f"img{i}.ppm").write_bytes(encode_ppm(img))
        (tmp_path / "broken.ppm").write_bytes(b"not an image")
        df = read_images(str(tmp_path))
        assert df.count() == 3  # broken dropped
        img0 = df.column("image")[0]
        assert img0["height"] == 10 and img0["nChannels"] == 3


class TestHTTPClient:
    def test_send_with_retries_429(self):
        calls = []

        def fake_send(req, timeout=60.0):
            calls.append(1)
            if len(calls) < 2:
                return HTTPResponseData(429, "too many",
                                        headers={"Retry-After": "0.01"})
            return HTTPResponseData(200, "OK", b"done")

        import mmlspark_tpu.io.http as H
        orig = H.send_request
        H.send_request = fake_send
        try:
            slept = []
            resp = send_with_retries(HTTPRequestData("http://x"),
                                     sleep_fn=slept.append)
            assert resp.statusCode == 200
            assert slept == [0.01]  # honored Retry-After
        finally:
            H.send_request = orig

    def test_real_http_round_trip(self, echo_server):
        df = DataFrame.from_dict({"req": [
            HTTPRequestData(url=echo_server + "/echo", method="POST",
                            entity=b'{"a":1}').to_row()]})
        out = HTTPTransformer(inputCol="req", outputCol="resp").transform(df)
        resp = HTTPResponseData.from_row(out.column("resp")[0])
        assert resp.statusCode == 200
        assert json.loads(resp.entity) == {"a": 1}

    def test_retry_on_503(self, echo_server):
        req = HTTPRequestData(url=echo_server + "/flaky", method="POST",
                              entity=b"{}")
        resp = send_with_retries(req, retry_backoffs_ms=(10, 10, 10))
        assert resp.statusCode == 200


class TestSimpleHTTPTransformer:
    def test_json_round_trip(self, echo_server):
        df = DataFrame.from_dict({"values": [[1.0, 2.0], [3.0]]})
        t = SimpleHTTPTransformer(outputCol="out")
        t.set("inputParser", JSONInputParser(echo_server + "/double"))
        t.set("outputParser", JSONOutputParser())
        out = t.transform(df)
        results = out.column("out")
        assert results[0]["result"] == [2.0, 4.0]
        assert results[1]["result"] == [6.0]
        assert out.column("errors")[0] is None

    def test_error_column(self, echo_server):
        df = DataFrame.from_dict({"values": [[1.0]]})
        t = SimpleHTTPTransformer(outputCol="out", concurrency=1)
        t.set("inputParser", JSONInputParser(echo_server + "/missing_path_404"))
        # the echo server treats unknown paths as echo -> force a bad URL instead
        t.set("inputParser", JSONInputParser("http://127.0.0.1:9/nope"))
        t.set("handler", lambda r: HTTPResponseData(500, "boom"))
        out = t.transform(df)
        assert out.column("out")[0] is None
        assert "500" in out.column("errors")[0]

    def test_consolidator(self):
        df = DataFrame.from_dict({"x": np.arange(10.0)}, num_partitions=5)
        out = PartitionConsolidator(targetPartitions=1).transform(df)
        assert out.num_partitions == 1 and out.count() == 10


class TestServing:
    def test_serve_echo_pipeline(self):
        from mmlspark_tpu.serving.stages import parse_request

        def transform(df):
            parsed = parse_request(df, "data", parse="json")
            return parsed.with_column(
                "reply", lambda p: [
                    {"sum": float(np.sum(v))} if v is not None else None
                    for v in p["data"]])

        with ServingServer(transform, port=0, max_wait_ms=2.0) as server:
            req = urllib.request.Request(
                server.address, data=json.dumps({"data": [1, 2, 3]}).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = json.loads(resp.read())
            assert body == {"sum": 6.0}

    def test_serve_batches_concurrent_requests(self):
        from mmlspark_tpu.serving.stages import parse_request
        batch_sizes = []

        def transform(df):
            batch_sizes.append(df.count())
            parsed = parse_request(df, "data", parse="json")
            return parsed.with_column(
                "reply", lambda p: [float(np.sum(v)) for v in p["data"]])

        with ServingServer(transform, port=0, max_wait_ms=50.0,
                           max_batch_size=16) as server:
            results = []

            def call(i):
                req = urllib.request.Request(
                    server.address, data=json.dumps({"data": [i]}).encode(),
                    method="POST")
                with urllib.request.urlopen(req, timeout=10) as resp:
                    results.append(float(resp.read()))

            threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert sorted(results) == [float(i) for i in range(8)]
            assert max(batch_sizes) > 1  # dynamic batching kicked in

    def test_serve_fitted_model(self):
        from mmlspark_tpu.gbdt import LightGBMRegressor
        rng = np.random.default_rng(0)
        X = rng.normal(size=(200, 3))
        y = 2 * X[:, 0] + X[:, 1]
        df = DataFrame.from_dict({"features": [X[i] for i in range(200)],
                                  "label": y})
        model = LightGBMRegressor(numIterations=10, numLeaves=7,
                                  minDataInLeaf=5).fit(df)
        server = serve_pipeline(model, input_col="features",
                                reply_col="reply", port=0)
        with server:
            x0 = X[0].tolist()
            req = urllib.request.Request(
                server.address, data=json.dumps({"data": x0}).encode(),
                method="POST")
            with urllib.request.urlopen(req, timeout=15) as resp:
                pred = float(resp.read())
            expected = model.transform(df.limit(1)).column("prediction")[0]
            assert pred == pytest.approx(expected, abs=1e-5)

    def test_latency_stats_decomposition(self):
        """The serving loop records per-request queue/compute/overhead; the
        /_mmlspark/stats endpoint exposes them (verdict item: decompose the
        model-endpoint latency into framework vs compute shares)."""
        from mmlspark_tpu.serving import ServingServer
        from mmlspark_tpu.serving.stages import parse_request

        def echo(df):
            parsed = parse_request(df, "data", parse="json")
            return parsed.with_column(
                "reply", lambda p: [float(np.sum(v)) for v in p["data"]])

        with ServingServer(echo, port=0, max_wait_ms=0.0) as server:
            server.warmup(json.dumps({"data": [1, 2]}).encode())
            payload = json.dumps({"data": [1, 2, 3]}).encode()
            for _ in range(12):
                req = urllib.request.Request(server.address, data=payload,
                                             method="POST")
                with urllib.request.urlopen(req, timeout=15) as resp:
                    resp.read()
            # warmup batches bypass HTTP: they must not pollute the stats
            s = server.stats.summary()
            assert s["n"] == 12
            for key in ("queue_ms", "compute_ms", "overhead_ms", "total_ms"):
                assert s[key]["p50"] >= 0.0
            # components must account for the total (within rounding)
            assert s["total_ms"]["mean"] == pytest.approx(
                s["queue_ms"]["mean"] + s["compute_ms"]["mean"]
                + s["overhead_ms"]["mean"], abs=0.01)
            # the stats endpoint serves the same summary
            with urllib.request.urlopen(
                    f"http://{server.host}:{server.port}/_mmlspark/stats",
                    timeout=15) as resp:
                remote = json.loads(resp.read())
            assert remote["n"] >= 12

    def test_warmup_precompiles_without_serving_replies(self):
        """warmup() pushes synthetic batches through the transform (compiling
        batch sizes 1 and max) without leaking replies or ids."""
        from mmlspark_tpu.serving import ServingServer

        seen_sizes = []

        def transform(df):
            data = df.collect()
            seen_sizes.append(len(data["id"]))
            return df.with_column("reply", lambda p: [b"ok"] * len(p["id"]))

        server = ServingServer(transform, port=0, max_batch_size=16)
        server.warmup(b"x")
        assert seen_sizes == [1, 16]
        assert server.requests_served == 0
        assert server.stats.summary()["n"] == 0

    def test_server_error_isolation(self):
        def transform(df):
            raise RuntimeError("model exploded")

        with ServingServer(transform, port=0) as server:
            req = urllib.request.Request(server.address, data=b"{}",
                                         method="POST")
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 500


class TestReviewRegressions:
    def test_malformed_200_goes_to_error_col(self, echo_server):
        df = DataFrame.from_dict({"values": [[1.0]]})
        t = SimpleHTTPTransformer(outputCol="out")
        t.set("inputParser", JSONInputParser("http://unused/"))
        t.set("handler",
              lambda r: HTTPResponseData(200, "OK", b"<html>not json</html>"))
        out = t.transform(df)
        assert out.column("out")[0] is None
        assert "parse failed" in out.column("errors")[0]

    def test_retry_timeout_slow_success(self):
        import time as _time
        from mmlspark_tpu.downloader import FaultToleranceUtils

        def slow():
            _time.sleep(0.05)
            return "done"

        # generous timeout: succeeds first try, no spurious retries
        assert FaultToleranceUtils.retry_with_timeout(
            slow, retries=1, timeout_s=5.0) == "done"

    def test_retry_timeout_enforced(self):
        import time as _time
        from mmlspark_tpu.downloader import FaultToleranceUtils

        def too_slow():
            _time.sleep(0.5)
            return "late"

        with pytest.raises(TimeoutError):
            FaultToleranceUtils.retry_with_timeout(
                too_slow, retries=1, timeout_s=0.05, backoff_s=0.001)


class TestDistributedServing:
    """Multi-worker serving: routing front + cross-worker replyTo
    (HTTPSourceV2 driver routing service + sendReplyUDF parity)."""

    @staticmethod
    def _echo_worker(tag):
        from mmlspark_tpu.serving.stages import parse_request

        def transform(df):
            parsed = parse_request(df, "data", parse="json")
            return parsed.with_column(
                "reply", lambda p: [{"worker": tag, "sum": float(np.sum(v))}
                                    for v in p["data"]])
        return transform

    def _post(self, url, obj, timeout=15):
        req = urllib.request.Request(
            url, data=json.dumps(obj).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())

    def test_front_spreads_load_and_all_answered(self):
        from mmlspark_tpu.serving import (RoutingFront, ServingServer,
                                          register_worker)
        with ServingServer(self._echo_worker("a"), port=0,
                           max_wait_ms=2.0) as wa, \
                ServingServer(self._echo_worker("b"), port=0,
                              max_wait_ms=2.0) as wb, \
                RoutingFront(port=0) as front:
            register_worker(front.address, wa.address)
            register_worker(front.address, wb.address)
            seen = set()
            for i in range(8):
                status, body = self._post(front.address, {"data": [i, 1]})
                assert status == 200
                assert body["sum"] == i + 1
                seen.add(body["worker"])
            assert seen == {"a", "b"}  # round-robin reached both

    def test_front_evicts_dead_worker_and_retries(self):
        from mmlspark_tpu.serving import (RoutingFront, ServingServer,
                                          register_worker)
        with ServingServer(self._echo_worker("live"), port=0,
                           max_wait_ms=2.0) as live, \
                RoutingFront(port=0, max_failures=2) as front:
            register_worker(front.address, live.address)
            # register a dead address too
            register_worker(front.address, "http://127.0.0.1:9/")
            for i in range(6):
                status, body = self._post(front.address, {"data": [i]})
                assert status == 200 and body["worker"] == "live"
            assert front.workers == [live.address]  # dead one evicted

    def test_cross_worker_reply_to(self):
        """A request enters worker A; worker B answers it via the internal
        reply endpoint (the cross-machine replyTo hop)."""
        from mmlspark_tpu.serving import ServingServer, reply_to
        handed_off = []

        def transform_a(df):
            # hand the batch off instead of answering locally
            data = df.collect()
            for rid, body, origin in zip(data["id"], data["value"],
                                         data["origin"]):
                handed_off.append((int(rid), bytes(body), origin))
            return df.limit(0)  # answer no rows locally -> stay pending

        with ServingServer(transform_a, port=0, max_wait_ms=2.0,
                           slot_timeout_s=20.0) as wa:
            result = {}

            def client():
                status, body = self._post(wa.address, {"data": [5, 6]})
                result["status"], result["body"] = status, body

            t = threading.Thread(target=client)
            t.start()
            deadline = time.time() + 10
            while not handed_off and time.time() < deadline:
                time.sleep(0.01)
            assert handed_off, "request never reached the transform"
            rid, body, origin = handed_off[0]
            # "worker B": answer from outside A's loop via the origin address
            payload = json.loads(body.decode())
            reply_to(origin, rid, {"answered_by": "b",
                                   "sum": float(sum(payload["data"]))})
            t.join(timeout=10)
            assert result["status"] == 200
            assert result["body"] == {"answered_by": "b", "sum": 11.0}

    def test_slot_timeout_configurable(self):
        from mmlspark_tpu.serving import ServingServer

        def never_answers(df):
            return df.select([])

        with ServingServer(never_answers, port=0, max_wait_ms=1.0,
                           slot_timeout_s=0.3) as server:
            t0 = time.time()
            req = urllib.request.Request(
                server.address, data=b"{}", method="POST")
            try:
                urllib.request.urlopen(req, timeout=10)
                assert False, "expected 504"
            except urllib.error.HTTPError as e:
                assert e.code == 504
            assert time.time() - t0 < 5.0

    def test_missing_reply_col_fails_fast_not_hang(self):
        """A transform that outputs rows without the reply column is a config
        error: clients get an immediate 500, not a slot-timeout hang."""
        from mmlspark_tpu.serving import ServingServer

        def misconfigured(df):
            return df.with_column("wrong_col", lambda p: p["value"])

        with ServingServer(misconfigured, port=0, max_wait_ms=1.0,
                           slot_timeout_s=30.0) as server:
            t0 = time.time()
            req = urllib.request.Request(
                server.address, data=b"{}", method="POST")
            try:
                urllib.request.urlopen(req, timeout=10)
                assert False, "expected 500"
            except urllib.error.HTTPError as e:
                assert e.code == 500
                assert b"reply" in e.read()
            assert time.time() - t0 < 5.0  # did NOT wait out the 30s slot

    def test_internal_endpoints_require_token(self):
        """With a cluster token set, unauthenticated replyTo and register are
        rejected; authenticated ones work."""
        from mmlspark_tpu.serving import (RoutingFront, ServingServer,
                                          register_worker, reply_to)

        with ServingServer(self._echo_worker("a"), port=0, max_wait_ms=2.0,
                           token="s3cret") as wa, \
                RoutingFront(port=0, token="s3cret") as front:
            # unauthenticated register -> 403
            with pytest.raises(urllib.error.HTTPError) as ei:
                register_worker(front.address, wa.address)
            assert ei.value.code == 403
            # unauthenticated replyTo -> 403
            with pytest.raises(urllib.error.HTTPError) as ei:
                reply_to(wa.address, 12345, {"x": 1})
            assert ei.value.code == 403
            # authenticated register + serve work end-to-end
            register_worker(front.address, wa.address, token="s3cret")
            status, body = self._post(front.address, {"data": [2, 3]})
            assert status == 200 and body["sum"] == 5.0

    def test_front_does_not_replay_timed_out_post(self):
        """A POST that times out on a worker must NOT be replayed on another
        worker (double-processing hazard) — client gets 504."""
        from mmlspark_tpu.serving import RoutingFront, ServingServer, \
            register_worker
        processed = []

        def slow(df):
            data = df.collect()
            processed.extend(int(r) for r in data["id"])
            time.sleep(1.5)  # longer than the front's forward timeout
            return df.with_column("reply", lambda p: [b"late"] * len(p["id"]))

        with ServingServer(slow, port=0, max_wait_ms=1.0) as ws, \
                ServingServer(self._echo_worker("fast"), port=0,
                              max_wait_ms=1.0) as wf, \
                RoutingFront(port=0, forward_timeout_s=0.4) as front:
            register_worker(front.address, ws.address)  # round-robin hits slow first
            register_worker(front.address, wf.address)
            req = urllib.request.Request(
                front.address, data=json.dumps({"data": [1]}).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            try:
                urllib.request.urlopen(req, timeout=10)
                assert False, "expected 504"
            except urllib.error.HTTPError as e:
                assert e.code == 504
                assert b"not replayed" in e.read()
            time.sleep(2.0)  # let the slow worker finish
            assert len(processed) == 1  # exactly one worker saw the request

    def test_front_forwards_path_and_query(self):
        """Non-root paths forward verbatim: the worker's own 404 comes back."""
        from mmlspark_tpu.serving import RoutingFront, ServingServer, \
            register_worker
        with ServingServer(self._echo_worker("a"), port=0,
                           max_wait_ms=2.0) as wa, \
                RoutingFront(port=0) as front:
            register_worker(front.address, wa.address)
            req = urllib.request.Request(
                front.address.rstrip("/") + "/nonexistent?q=1",
                data=b"{}", method="POST")
            try:
                urllib.request.urlopen(req, timeout=10)
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404  # the WORKER's 404, not a model reply


class TestPortForwarding:
    """SSH reverse-forward parity (PortForwarding.scala) — the ssh transport
    itself is the system client; these tests pin the argv contract and the
    retry-across-ports supervision using a stub executable."""

    def test_ssh_command_contract(self):
        from mmlspark_tpu.serving import build_ssh_command

        cmd = build_ssh_command("worker", "gateway.example", 2222,
                                "0.0.0.0", 8900, "127.0.0.1", 8898,
                                key_file="/keys/id_ed25519")
        assert cmd[0] == "ssh" and "-N" in cmd
        assert "ExitOnForwardFailure=yes" in cmd  # taken port must fail fast
        assert "-R" in cmd
        assert cmd[cmd.index("-R") + 1] == "0.0.0.0:8900:127.0.0.1:8898"
        assert cmd[cmd.index("-p") + 1] == "2222"
        assert cmd[cmd.index("-i") + 1] == "/keys/id_ed25519"
        assert cmd[-1] == "worker@gateway.example"

    def test_retries_across_ports_until_one_binds(self, tmp_path, monkeypatch):
        """First two 'ports' fail (ssh exits), third stays up -> picked."""
        import subprocess

        from mmlspark_tpu.serving import PortForwarder

        calls = []

        def fake_spawn(self, remote_port):
            calls.append(remote_port)
            if len(calls) < 3:
                return subprocess.Popen(["false"])  # exits immediately
            return subprocess.Popen(["sleep", "30"])  # tunnel "holds"

        monkeypatch.setattr(PortForwarder, "_spawn", fake_spawn)
        fwd = PortForwarder("u", "gw", remote_port_start=9000,
                            local_port=1234, settle_s=0.2, max_retries=5)
        try:
            proc, port = fwd.start()
            assert port == 9002
            assert calls == [9000, 9001, 9002]
            assert fwd.remote_address == "http://gw:9002/"
            assert proc.poll() is None
        finally:
            fwd.stop()
        assert fwd._proc is None

    def test_all_ports_taken_raises(self, monkeypatch):
        import subprocess

        from mmlspark_tpu.serving import PortForwarder

        monkeypatch.setattr(
            PortForwarder, "_spawn",
            lambda self, port: subprocess.Popen(["false"]))
        fwd = PortForwarder("u", "gw", settle_s=0.05, max_retries=2)
        with pytest.raises(RuntimeError, match="could not establish"):
            fwd.start()


class TestRequestJournal:
    """Epoch/commit semantics (HTTPSourceV2.scala:575-640 parity): requests
    journal before processing, epochs commit when fully answered, recovery
    replays uncommitted requests."""

    def _post(self, url, obj, timeout=15):
        req = urllib.request.Request(
            url, data=json.dumps(obj).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())

    def test_answered_epochs_commit(self, tmp_path):
        from mmlspark_tpu.serving import RequestJournal, ServingServer
        from mmlspark_tpu.serving.stages import parse_request

        jp = str(tmp_path / "journal.jsonl")

        def transform(df):
            parsed = parse_request(df, "data", parse="json")
            return parsed.with_column(
                "reply", lambda p: [{"sum": float(np.sum(v))}
                                    for v in p["data"]])

        with ServingServer(transform, port=0, max_wait_ms=2.0,
                           journal_path=jp) as server:
            for i in range(4):
                status, body = self._post(server.address, {"data": [i, 1]})
                assert status == 200
            time.sleep(0.3)  # let the loop commit
        # every journaled epoch committed -> nothing to recover
        assert RequestJournal.recover(jp) == []
        text = open(jp).read()
        assert '"op": "entry"' in text and '"op": "commit"' in text

    def test_crash_recovery_replays_unanswered(self, tmp_path):
        from mmlspark_tpu.serving import RequestJournal

        jp = str(tmp_path / "j.jsonl")
        j = RequestJournal(jp)
        j.append(1, 100, b'{"data": [1]}', {"H": "v"})
        j.append(1, 101, b'{"data": [2]}')
        j.commit(1)
        j.append(2, 102, b'{"data": [3]}')  # crash before commit
        j.close()
        pending = RequestJournal.recover(jp)
        assert [(rid, body) for rid, body, _ in pending] == \
            [(102, b'{"data": [3]}')]

    def test_journal_written_even_when_transform_fails(self, tmp_path):
        from mmlspark_tpu.serving import RequestJournal, ServingServer

        jp = str(tmp_path / "j.jsonl")

        def explode(df):
            raise RuntimeError("boom")

        with ServingServer(explode, port=0, max_wait_ms=1.0,
                           journal_path=jp) as server:
            req = urllib.request.Request(server.address, data=b'{"x":1}',
                                         method="POST")
            try:
                urllib.request.urlopen(req, timeout=10)
                assert False
            except urllib.error.HTTPError as e:
                assert e.code == 500
            time.sleep(0.3)
        # the request was journaled BEFORE the failing transform ran, and the
        # epoch still commits (the client got its 500 — answered)
        text = open(jp).read()
        assert '"op": "entry"' in text
        assert RequestJournal.recover(jp) == []

    def test_compact_drops_committed(self, tmp_path):
        from mmlspark_tpu.serving import RequestJournal

        jp = str(tmp_path / "j.jsonl")
        j = RequestJournal(jp)
        for e in range(5):
            j.append(e, 200 + e, b"x")
            if e != 3:
                j.commit(e)
        j.compact()
        pending = RequestJournal.recover(jp)
        assert [rid for rid, _, _ in pending] == [203]
        # epoch numbers survive compaction: a LATE commit of the live epoch
        # must still match its entries
        j.commit(3)
        assert RequestJournal.recover(jp) == []
        j.close()

    def test_torn_final_line_is_skipped(self, tmp_path):
        """A crash mid-append leaves a truncated last line; recovery must
        skip it, not abort."""
        from mmlspark_tpu.serving import RequestJournal

        jp = str(tmp_path / "j.jsonl")
        j = RequestJournal(jp)
        j.append(1, 300, b"keep-me")
        j.close()
        with open(jp, "a") as fh:
            fh.write('{"op": "entry", "epoch": 2, "id": 301, "body_')  # torn
        pending = RequestJournal.recover(jp)
        assert [rid for rid, _, _ in pending] == [300]
