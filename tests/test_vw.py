"""VW-parity engine tests: hashing, featurizer, learner, stages."""

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.ops.hashing import MurmurWithPrefix, hash_string, murmur3_32
from mmlspark_tpu.vw import (
    LearnerConfig,
    SparseDataset,
    VowpalWabbitClassifier,
    VowpalWabbitFeaturizer,
    VowpalWabbitInteractions,
    VowpalWabbitRegressor,
    train_linear,
)
from mmlspark_tpu.vw.learner import predict_linear
from mmlspark_tpu.vw.stages import parse_vw_args


class TestMurmur:
    def test_known_vectors(self):
        assert murmur3_32(b"", 0) == 0
        assert murmur3_32(b"", 1) == 0x514E28B7
        assert murmur3_32(b"hello", 0) == 0x248BFA47
        assert murmur3_32(b"abc", 0) == 0xB3DD93FA

    def test_prefix_hashing(self):
        m = MurmurWithPrefix("col=")
        assert m.hash("value") == hash_string("col=value")


class TestFeaturizer:
    def test_numeric_and_string(self):
        df = DataFrame.from_dict({
            "age": [25.0, 0.0, 31.0],
            "city": ["nyc", "sf", None],
        })
        out = VowpalWabbitFeaturizer(inputCols=["age", "city"]).transform(df)
        f0 = out.column("features")[0]
        assert len(f0["indices"]) == 2   # age + city=nyc
        f1 = out.column("features")[1]
        assert len(f1["indices"]) == 1   # zero numeric dropped, city=sf kept
        f2 = out.column("features")[2]
        assert len(f2["indices"]) == 1   # None string dropped, age kept

    def test_same_string_same_index(self):
        df = DataFrame.from_dict({"city": ["nyc", "nyc"]})
        out = VowpalWabbitFeaturizer(inputCols=["city"]).transform(df)
        c = out.column("features")
        assert c[0]["indices"][0] == c[1]["indices"][0]

    def test_map_and_vector(self):
        df = DataFrame.from_dict({
            "m": [{"a": 1.0, "b": 2.0}],
            "v": [np.array([0.0, 3.0, 0.0, 4.0])],
        })
        out = VowpalWabbitFeaturizer(inputCols=["m", "v"], numBits=24).transform(df)
        f = out.column("features")[0]
        # VectorFeaturizer passthrough: raw positional indices 0..3 incl. zeros
        # (reference VectorFeaturizer.scala dense branch), + 2 hashed map features
        assert len(f["indices"]) == 6
        assert {0, 1, 2, 3}.issubset(set(f["indices"].tolist()))
        assert set(np.round(f["values"]).astype(int)) == {0, 1, 2, 3, 4}

    def test_reference_hash_scheme(self):
        """Indices follow the reference exactly: namespaceHash = murmur(outputCol,
        seed); string idx = murmur(colName + value, namespaceHash)
        (VowpalWabbitFeaturizer.scala:115, StringFeaturizer.scala)."""
        from mmlspark_tpu.ops.hashing import hash_string

        df = DataFrame.from_dict({"city": ["nyc"], "age": [3.0]})
        out = VowpalWabbitFeaturizer(inputCols=["city", "age"], outputCol="features",
                                     numBits=30).transform(df)
        f = out.column("features")[0]
        ns = hash_string("features", 0)
        mask = (1 << 30) - 1
        want = {hash_string("citynyc", ns) & mask, hash_string("age", ns) & mask}
        assert set(f["indices"].tolist()) == want

        # namespace (outputCol) changes the whole feature space
        out2 = VowpalWabbitFeaturizer(inputCols=["city", "age"], outputCol="other",
                                      numBits=30).transform(df)
        assert set(out2.column("other")[0]["indices"].tolist()) != want

        # prefixStringsWithColumnName=False drops the column prefix only
        out3 = VowpalWabbitFeaturizer(inputCols=["city"], outputCol="features",
                                      prefixStringsWithColumnName=False,
                                      numBits=30).transform(df)
        assert out3.column("features")[0]["indices"][0] == \
            (hash_string("nyc", ns) & mask)

    def test_interactions_fnv1_combine(self):
        """Interaction index = (i1 * 16777619) ^ i2 in 32-bit, masked
        (VowpalWabbitInteractions.scala:43-57)."""
        from mmlspark_tpu.ops.hashing import hash_string

        df = DataFrame.from_dict({"a": ["x"], "b": ["y"]})
        fa = VowpalWabbitFeaturizer(inputCols=["a"], outputCol="fa").transform(df)
        fb = VowpalWabbitFeaturizer(inputCols=["b"], outputCol="fb").transform(fa)
        out = VowpalWabbitInteractions(inputCols=["fa", "fb"], outputCol="fx",
                                       numBits=30).transform(fb)
        i1 = int(fb.collect()["fa"][0]["indices"][0])
        i2 = int(fb.collect()["fb"][0]["indices"][0])
        want = ((np.uint32(i1) * np.uint32(16777619)) ^ np.uint32(i2)) & np.uint32(
            (1 << 30) - 1)
        got = out.column("fx")[0]["indices"]
        assert got.tolist() == [int(want)]

    def test_string_split(self):
        df = DataFrame.from_dict({"text": ["hello world hello"]})
        out = VowpalWabbitFeaturizer(inputCols=["text"], stringSplit=True,
                                     sumCollisions=True).transform(df)
        f = out.column("features")[0]
        assert len(f["indices"]) == 2
        assert sorted(f["values"]) == [1.0, 2.0]  # repeated word summed

    def test_interactions(self):
        df = DataFrame.from_dict({"a": ["x"], "b": ["y"]})
        fa = VowpalWabbitFeaturizer(inputCols=["a"], outputCol="fa").transform(df)
        fb = VowpalWabbitFeaturizer(inputCols=["b"], outputCol="fb").transform(fa)
        out = VowpalWabbitInteractions(inputCols=["fa", "fb"],
                                       outputCol="fi").transform(fb)
        f = out.column("fi")[0]
        assert len(f["indices"]) == 1 and f["values"][0] == 1.0


def synth_sparse(n=400, d=50, seed=0, num_bits=12):
    rng = np.random.default_rng(seed)
    true_w = rng.normal(size=d)
    rows = []
    raws = np.zeros(n)
    for i in range(n):
        nnz = rng.integers(3, 10)
        idx = rng.choice(d, size=nnz, replace=False)
        val = rng.normal(size=nnz).astype(np.float32)
        rows.append({"indices": idx.astype(np.int64), "values": val})
        raws[i] = (true_w[idx] * val).sum()
    return rows, raws


class TestLearner:
    def test_squared_regression_converges(self):
        rows, raws = synth_sparse()
        cfg = LearnerConfig(num_bits=12, learning_rate=0.5, num_passes=10)
        ds = SparseDataset.from_rows(rows, raws, num_bits=12)
        w, stats = train_linear(cfg, ds)
        pred = predict_linear(w, ds)
        r2 = 1 - np.var(pred - raws) / np.var(raws)
        assert r2 > 0.95, r2
        assert stats[-1].average_loss < stats[0].average_loss

    def test_logistic_classification(self):
        rows, raws = synth_sparse(600)
        y = np.where(raws > 0, 1.0, -1.0)
        cfg = LearnerConfig(num_bits=12, loss_function="logistic",
                            learning_rate=0.5, num_passes=10)
        ds = SparseDataset.from_rows(rows, y, num_bits=12)
        w, _ = train_linear(cfg, ds)
        pred = predict_linear(w, ds)
        assert np.mean((pred > 0) == (y > 0)) > 0.9

    def test_ftrl(self):
        rows, raws = synth_sparse(500)
        y = np.where(raws > 0, 1.0, -1.0)
        cfg = LearnerConfig(num_bits=12, loss_function="logistic", ftrl=True,
                            ftrl_alpha=0.1, num_passes=5)
        ds = SparseDataset.from_rows(rows, y, num_bits=12)
        w, _ = train_linear(cfg, ds)
        pred = predict_linear(w, ds)
        assert np.mean((pred > 0) == (y > 0)) > 0.85

    def test_ftrl_l1_sparsifies(self):
        rows, raws = synth_sparse(300)
        cfg = LearnerConfig(num_bits=12, ftrl=True, l1=100.0, num_passes=3)
        ds = SparseDataset.from_rows(rows, raws, num_bits=12)
        w, _ = train_linear(cfg, ds)
        cfg0 = LearnerConfig(num_bits=12, ftrl=True, l1=0.0, num_passes=3)
        w0, _ = train_linear(cfg0, ds)
        assert (w != 0).sum() < (w0 != 0).sum()

    def test_distributed_matches_single(self, mesh8):
        rows, raws = synth_sparse(400)
        y = np.where(raws > 0, 1.0, -1.0)
        cfg = LearnerConfig(num_bits=12, loss_function="logistic",
                            learning_rate=0.5, num_passes=8)
        ds = SparseDataset.from_rows(rows, y, num_bits=12)
        w_single, _ = train_linear(cfg, ds)
        w_mesh, _ = train_linear(cfg, ds, mesh=mesh8)
        acc_s = np.mean((predict_linear(w_single, ds) > 0) == (y > 0))
        acc_m = np.mean((predict_linear(w_mesh, ds) > 0) == (y > 0))
        assert acc_m > 0.85, acc_m
        assert abs(acc_s - acc_m) < 0.08

    def test_quantile_loss(self):
        rng = np.random.default_rng(0)
        rows = [{"indices": np.array([0]), "values": np.array([1.0], dtype=np.float32)}
                for _ in range(2000)]
        y = rng.exponential(scale=2.0, size=2000)
        cfg = LearnerConfig(num_bits=4, loss_function="quantile", quantile_tau=0.9,
                            learning_rate=0.3, num_passes=30)
        ds = SparseDataset.from_rows(rows, y, num_bits=4)
        w, _ = train_linear(cfg, ds)
        q90 = np.quantile(y, 0.9)
        assert abs(w[0] - q90) < 0.6, (w[0], q90)


class TestArgsParsing:
    def test_parse(self):
        cfg = parse_vw_args("--loss_function logistic -l 0.3 -b 22 --passes 4 "
                            "--l1 0.01 --ftrl --ftrl_alpha 0.2")
        assert cfg.loss_function == "logistic"
        assert cfg.learning_rate == 0.3
        assert cfg.num_bits == 22
        assert cfg.num_passes == 4
        assert cfg.l1 == 0.01
        assert cfg.ftrl and cfg.ftrl_alpha == 0.2

    def test_unknown_args_ignored(self):
        cfg = parse_vw_args("--quiet --some_future_flag -l 0.1")
        assert cfg.learning_rate == 0.1


class TestStages:
    def make_df(self, n=400, seed=0):
        rng = np.random.default_rng(seed)
        age = rng.uniform(20, 60, n)
        income = rng.normal(50, 10, n)
        city = rng.choice(["nyc", "sf", "la"], n)
        logit = 0.1 * (age - 40) + 0.05 * (income - 50) + np.where(city == "sf", 1.5, 0)
        y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
        return DataFrame.from_dict(
            {"age": age, "income": income, "city": list(city), "label": y},
            num_partitions=2)

    def test_classifier_pipeline(self):
        df = self.make_df()
        feat = VowpalWabbitFeaturizer(inputCols=["age", "income", "city"],
                                      outputCol="features", numBits=18)
        fdf = feat.transform(df)
        clf = VowpalWabbitClassifier(featuresCol="features", labelCol="label",
                                     numPasses=10, numBits=18)
        model = clf.fit(fdf)
        out = model.transform(fdf)
        acc = np.mean(out.column("prediction") == fdf.column("label"))
        assert acc > 0.8, acc
        proba = out.column("probability")
        assert ((proba >= 0) & (proba <= 1)).all()

    def test_regressor_dense_vectors(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(300, 10))
        tw = rng.normal(size=10)
        y = X @ tw + 0.01 * rng.normal(size=300)
        df = DataFrame.from_dict({"features": [X[i] for i in range(300)], "label": y})
        model = VowpalWabbitRegressor(featuresCol="features", labelCol="label",
                                      numPasses=15, numBits=10).fit(df)
        pred = model.transform(df).column("prediction")
        r2 = 1 - np.var(pred - y) / np.var(y)
        assert r2 > 0.95, r2

    def test_performance_statistics(self):
        df = self.make_df(100)
        feat = VowpalWabbitFeaturizer(inputCols=["age", "city"], outputCol="features")
        model = VowpalWabbitClassifier(featuresCol="features", labelCol="label",
                                       numPasses=2).fit(feat.transform(df))
        stats = model.get_performance_statistics()
        assert stats.count() == 2  # one row per pass
        assert "averageLoss" in stats.columns

    def test_pass_through_args(self):
        df = self.make_df(200)
        feat = VowpalWabbitFeaturizer(inputCols=["age", "city"], outputCol="features")
        clf = VowpalWabbitClassifier(featuresCol="features", labelCol="label",
                                     passThroughArgs="--passes 3 -l 0.8 --ftrl")
        model = clf.fit(feat.transform(df))
        assert len(model._stats) == 3

    def test_initial_model_warm_start(self):
        df = self.make_df(300)
        feat = VowpalWabbitFeaturizer(inputCols=["age", "income", "city"],
                                      outputCol="features")
        fdf = feat.transform(df)
        m1 = VowpalWabbitClassifier(featuresCol="features", labelCol="label",
                                    numPasses=3).fit(fdf)
        clf2 = VowpalWabbitClassifier(featuresCol="features", labelCol="label",
                                      numPasses=1)
        clf2.set("initialModel", m1.get("weights"))
        m2 = clf2.fit(fdf)
        acc = np.mean(m2.transform(fdf).column("prediction") == fdf.column("label"))
        assert acc > 0.75

    def test_model_save_load(self, tmp_path):
        df = self.make_df(200)
        feat = VowpalWabbitFeaturizer(inputCols=["age", "city"], outputCol="features")
        fdf = feat.transform(df)
        model = VowpalWabbitClassifier(featuresCol="features",
                                       labelCol="label").fit(fdf)
        model.save(str(tmp_path / "m"))
        from mmlspark_tpu.core.pipeline import PipelineStage
        loaded = PipelineStage.load(str(tmp_path / "m"))
        np.testing.assert_allclose(
            loaded.transform(fdf).column("rawPrediction"),
            model.transform(fdf).column("rawPrediction"), atol=1e-6)


class TestReviewRegressions:
    def test_ftrl_warm_start_used(self):
        rows, raws = synth_sparse(300)
        cfg = LearnerConfig(num_bits=12, ftrl=True, ftrl_alpha=0.1, num_passes=3)
        ds = SparseDataset.from_rows(rows, raws, num_bits=12)
        w1, _ = train_linear(cfg, ds)
        # warm-starting from w1 with zero extra passes should preserve w1
        cfg0 = LearnerConfig(num_bits=12, ftrl=True, ftrl_alpha=0.1, num_passes=1)
        w2, _ = train_linear(cfg0, SparseDataset.from_rows(rows[:1], raws[:1],
                                                           num_bits=12),
                             initial_weights=w1)
        # one example barely moves the model; weights stay close to w1, not zero
        assert np.abs(w2).sum() > 0.5 * np.abs(w1).sum()

    def test_sum_collisions_false_keeps_first(self):
        df = DataFrame.from_dict({"text": ["hello hello"]})
        out = VowpalWabbitFeaturizer(inputCols=["text"], stringSplit=True,
                                     sumCollisions=False).transform(df)
        f = out.column("features")[0]
        assert list(f["values"]) == [1.0]

    def test_parse_args_trailing_flag_raises(self):
        import pytest as _pytest
        with _pytest.raises(ValueError, match="expects a value"):
            parse_vw_args("--loss_function hinge -l")

    def test_padded_distributed_loss_unbiased(self, mesh8):
        rows, raws = synth_sparse(401)  # not divisible by 8 -> 7 pad rows
        y = np.where(raws > 0, 1.0, -1.0)
        # lr=0 freezes weights: every real example's loss is exactly log(2),
        # so any deviation in the mesh average exposes pad-row contamination
        cfg = LearnerConfig(num_bits=12, loss_function="logistic",
                            learning_rate=0.0, adaptive=False, num_passes=1)
        ds = SparseDataset.from_rows(rows, y, num_bits=12)
        _, stats_mesh = train_linear(cfg, ds, mesh=mesh8)
        assert stats_mesh[0].average_loss == pytest.approx(np.log(2), abs=1e-5)

    def test_logistic_loss_no_overflow(self):
        rows = [{"indices": np.array([0]), "values": np.array([1000.0],
                                                              dtype=np.float32)}]
        cfg = LearnerConfig(num_bits=4, loss_function="logistic",
                            learning_rate=10.0, num_passes=2)
        ds = SparseDataset.from_rows(rows * 20, np.ones(20), num_bits=4)
        _, stats = train_linear(cfg, ds)
        assert np.isfinite(stats[-1].average_loss)


class TestParamParityAdditions:
    def test_additional_features_merge(self):
        """additionalFeatures columns merge into the training examples
        (vw/VowpalWabbitBase.scala additionalFeatures)."""
        from mmlspark_tpu.vw import VowpalWabbitClassifier

        rng = np.random.default_rng(0)
        n = 200
        # base features are noise; the SIGNAL lives in the additional column
        base = [{"indices": np.array([1]), "values":
                 np.array([rng.normal()], dtype=np.float32)} for _ in range(n)]
        y = rng.integers(0, 2, n).astype(np.float64)
        extra = [{"indices": np.array([7]),
                  "values": np.array([1.0 if y[i] else -1.0],
                                     dtype=np.float32)} for i in range(n)]
        df = DataFrame.from_dict({"features": np.array(base, dtype=object),
                                  "extra": np.array(extra, dtype=object),
                                  "label": y})
        plain = VowpalWabbitClassifier(numPasses=5).fit(df)
        acc_plain = np.mean(plain.transform(df).column("prediction") == y)
        boosted = VowpalWabbitClassifier(
            numPasses=5, additionalFeatures=["extra"]).fit(df)
        acc_boosted = np.mean(boosted.transform(df).column("prediction") == y)
        assert acc_boosted > 0.95 > acc_plain + 0.3

    def test_string_split_input_cols(self):
        from mmlspark_tpu.vw import VowpalWabbitFeaturizer

        df = DataFrame.from_dict({
            "a": np.array(["x y", "x y"], dtype=object),
            "b": np.array(["x y", "x y"], dtype=object)})
        out = VowpalWabbitFeaturizer(
            inputCols=["a", "b"], outputCol="f", numBits=18,
            stringSplitInputCols=["a"]).transform(df)
        row = out.column("f")[0]
        # col a tokenizes into 2 features; col b stays 1 whole-string feature
        assert len(row["indices"]) == 3


def test_readable_model_dump():
    """--readable_model parity: index:weight lines over the hashed space
    (binary VW blob interchange is a documented non-goal, docs/vw.md)."""
    from mmlspark_tpu.vw import VowpalWabbitClassifier

    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    df = DataFrame.from_dict(
        {"features": [X[i] for i in range(len(X))], "label": y})
    model = VowpalWabbitClassifier(numPasses=2, labelCol="label").fit(df)
    text = model.get_readable_model()
    lines = text.strip().splitlines()
    assert lines[0] == "bits:18"
    assert len(lines) > 1
    idx, wval = lines[1].split(":")
    w = np.asarray(model.get("weights"))
    assert abs(w[int(idx)] - float(wval)) < 1e-5


def test_readable_model_import_continue_training():
    """Round trip the text dump: export -> parse -> continue training, and
    compare against continuing from the in-memory weights directly
    (initialModel semantics, vw/VowpalWabbitBase.scala:120-122). The dump
    stores 6-decimal weights, so parity is tolerance-based."""
    from mmlspark_tpu.vw import VowpalWabbitClassifier, parse_readable_model

    rng = np.random.default_rng(1)
    X = rng.normal(size=(300, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    df = DataFrame.from_dict(
        {"features": [X[i] for i in range(len(X))], "label": y})
    m1 = VowpalWabbitClassifier(numPasses=2).fit(df)
    text = m1.get_readable_model()

    bits, weights = parse_readable_model(text)
    assert bits == 18
    w1 = np.asarray(m1.get("weights"), dtype=np.float64)
    np.testing.assert_allclose(weights, w1, atol=5e-7)

    cont_text = (VowpalWabbitClassifier(numPasses=2)
                 .set_initial_model_readable(text).fit(df))
    cont_mem = VowpalWabbitClassifier(numPasses=2,
                                      initialModel=w1).fit(df)
    p_text = np.asarray(cont_text.transform(df).column("rawPrediction"),
                        dtype=np.float64)
    p_mem = np.asarray(cont_mem.transform(df).column("rawPrediction"),
                       dtype=np.float64)
    np.testing.assert_allclose(p_text, p_mem, atol=1e-3)
    # continuation actually moved the weights
    assert np.abs(np.asarray(cont_text.get("weights")) - w1).max() > 0


class TestNativeLearner:
    """Native C++ sequential pass vs the jitted scan (same f32 update
    semantics, two-phase duplicate-index handling; reference architecture:
    VW's C++ core driven per example, vw/VowpalWabbitBase.scala:218-305)."""

    @pytest.mark.parametrize("loss", ["squared", "logistic", "hinge",
                                      "quantile"])
    def test_native_matches_scan(self, loss, monkeypatch):
        from mmlspark_tpu import native_loader as NL
        from mmlspark_tpu.vw.learner import (
            LearnerConfig,
            SparseDataset,
            train_linear,
        )

        if not NL.available():
            pytest.skip("native toolchain unavailable")
        monkeypatch.delenv("MMLSPARK_TPU_NATIVE_VW", raising=False)
        rows, raws = synth_sparse(300, num_bits=10)
        y = np.where(raws > 0, 1.0, -1.0) if loss != "quantile" \
            else np.abs(raws)
        ds = SparseDataset.from_rows(rows, y, num_bits=10)
        cfg = LearnerConfig(num_bits=10, loss_function=loss, num_passes=3,
                            learning_rate=0.4, l2=1e-4)
        w_nat, stats_nat = train_linear(cfg, ds)
        monkeypatch.setenv("MMLSPARK_TPU_NATIVE_VW", "0")
        w_scan, stats_scan = train_linear(cfg, ds)
        np.testing.assert_allclose(w_nat, np.asarray(w_scan), rtol=1e-3,
                                   atol=2e-4)
        assert abs(stats_nat[-1].average_loss
                   - stats_scan[-1].average_loss) < 1e-3

    def test_native_nonadaptive_decay(self, monkeypatch):
        from mmlspark_tpu import native_loader as NL
        from mmlspark_tpu.vw.learner import (
            LearnerConfig,
            SparseDataset,
            train_linear,
        )

        if not NL.available():
            pytest.skip("native toolchain unavailable")
        monkeypatch.delenv("MMLSPARK_TPU_NATIVE_VW", raising=False)
        rows, raws = synth_sparse(300, num_bits=10, seed=3)
        y = np.where(raws > 0, 1.0, -1.0)
        ds = SparseDataset.from_rows(rows, y, num_bits=10)
        cfg = LearnerConfig(num_bits=10, loss_function="logistic",
                            num_passes=2, adaptive=False, learning_rate=0.4,
                            initial_t=1.0)
        w_nat, _ = train_linear(cfg, ds)
        monkeypatch.setenv("MMLSPARK_TPU_NATIVE_VW", "0")
        w_scan, _ = train_linear(cfg, ds)
        np.testing.assert_allclose(w_nat, np.asarray(w_scan), rtol=1e-3,
                                   atol=2e-4)

    def test_native_warm_start_does_not_mutate_source(self, monkeypatch):
        # np.asarray of a jax array is a zero-copy READ-ONLY view on
        # CPU-addressable backends; the in-place native update must copy —
        # warm-starting model2 from model1's weights must not corrupt
        # model1 (r5 review finding)
        from mmlspark_tpu import native_loader as NL
        from mmlspark_tpu.vw.learner import (
            LearnerConfig,
            SparseDataset,
            train_linear,
        )

        if not NL.available():
            pytest.skip("native toolchain unavailable")
        monkeypatch.delenv("MMLSPARK_TPU_NATIVE_VW", raising=False)
        rows, raws = synth_sparse(200, num_bits=10, seed=9)
        y = np.where(raws > 0, 1.0, -1.0)
        ds = SparseDataset.from_rows(rows, y, num_bits=10)
        cfg = LearnerConfig(num_bits=10, loss_function="logistic",
                            num_passes=2)
        w1, _ = train_linear(cfg, ds)
        snap = np.array(np.asarray(w1))
        w2, _ = train_linear(cfg, ds, initial_weights=w1)
        np.testing.assert_array_equal(np.asarray(w1), snap)
        assert np.abs(np.asarray(w2) - snap).max() > 0

    def test_native_oob_indices_fall_back_to_scan(self, monkeypatch):
        # hand-built datasets may carry out-of-range indices; the C kernel
        # must never see them (XLA clamps, raw memory corrupts)
        import dataclasses

        from mmlspark_tpu import native_loader as NL
        from mmlspark_tpu.vw.learner import (
            LearnerConfig,
            SparseDataset,
            train_linear,
        )

        if not NL.available():
            pytest.skip("native toolchain unavailable")
        monkeypatch.delenv("MMLSPARK_TPU_NATIVE_VW", raising=False)
        rows, raws = synth_sparse(100, num_bits=10, seed=11)
        y = np.where(raws > 0, 1.0, -1.0)
        ds = SparseDataset.from_rows(rows, y, num_bits=10)
        bad = dataclasses.replace(
            ds, indices=ds.indices.copy()) if dataclasses.is_dataclass(ds) \
            else ds
        bad.indices[0, 0] = 1 << 12  # >= dim for num_bits=10
        cfg = LearnerConfig(num_bits=10, loss_function="logistic",
                            num_passes=1)
        w, _ = train_linear(cfg, bad)  # must not crash the process
        assert np.isfinite(np.asarray(w)).all()

    def test_native_continuation_and_weights(self, monkeypatch):
        from mmlspark_tpu import native_loader as NL
        from mmlspark_tpu.vw.learner import (
            LearnerConfig,
            SparseDataset,
            predict_linear,
            train_linear,
        )

        if not NL.available():
            pytest.skip("native toolchain unavailable")
        monkeypatch.delenv("MMLSPARK_TPU_NATIVE_VW", raising=False)
        rows, raws = synth_sparse(400, num_bits=10, seed=5)
        y = np.where(raws > 0, 1.0, -1.0)
        wts = np.where(y > 0, 2.0, 1.0)
        ds = SparseDataset.from_rows(rows, y, wts, num_bits=10)
        cfg = LearnerConfig(num_bits=10, loss_function="logistic",
                            num_passes=4)
        w1, _ = train_linear(cfg, ds)
        w2, _ = train_linear(cfg, ds, initial_weights=w1)  # warm start
        acc = np.mean((predict_linear(np.asarray(w2), ds) > 0) == (y > 0))
        assert acc > 0.9


def test_parse_readable_model_vw_header_format():
    """A real vw dump has informational headers and 'Num weight bits'."""
    from mmlspark_tpu.vw import parse_readable_model

    text = ("Version 8.7.0\nId \nMin label:-1\nMax label:1\n"
            "Num weight bits:10\nlda:0\n0 ngram:\n1 skip:\n"
            "options:\nCheckpoint state, not reproducible\n"
            "5:0.25\n1023:-1.5\n")
    bits, w = parse_readable_model(text)
    assert bits == 10 and len(w) == 1024
    assert w[5] == 0.25 and w[1023] == -1.5
