"""Tests for the NN module system, ResNet, DNNModel, and image stages (E2E slice)."""

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.schema import ImageSchema
from mmlspark_tpu.models import (
    DNNModel,
    Dense,
    FunctionModel,
    Sequential,
    build_resnet,
    relu,
    resnet,
)
from mmlspark_tpu.image import (
    ImageFeaturizer,
    ImageSetAugmenter,
    ImageTransformer,
    ResizeImageTransformer,
    UnrollImage,
)
from mmlspark_tpu.ops import image as imops


def tiny_mlp(din=4, dhid=8, dout=3, seed=0):
    import jax
    module = Sequential([
        ("dense1", Dense(dhid)),
        ("relu1", relu()),
        ("dense2", Dense(dout)),
    ], name="mlp")
    params, out_shape = module.init(jax.random.PRNGKey(seed), (din,))
    assert out_shape == (dout,)
    return FunctionModel(module, params, (din,), layer_names=["dense2", "relu1", "dense1"])


class TestModule:
    def test_init_apply_shapes(self):
        m = tiny_mlp()
        x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
        y = np.asarray(m.apply(x))
        assert y.shape == (5, 3)

    def test_taps(self):
        m = tiny_mlp()
        x = np.ones((2, 4), dtype=np.float32)
        hidden = np.asarray(m.apply(x, tap="relu1"))
        assert hidden.shape == (2, 8)
        assert (hidden >= 0).all()

    def test_output_node_resolution(self):
        m = tiny_mlp()
        assert m.resolve_output(None) is None
        assert m.resolve_output("OUTPUT_0") is None
        assert m.resolve_output("OUTPUT_2") == "relu1"
        assert m.resolve_output("relu1") == "relu1"
        with pytest.raises(KeyError):
            m.resolve_output("nope")

    def test_layer_paths(self):
        m = tiny_mlp()
        paths = m.module.layer_paths()
        assert "dense1" in paths and "relu1" in paths

    def test_fn_shape_probe_is_abstract(self):
        """Fn without out_shape_fn probes via jax.eval_shape: no concrete
        execution, so jax-only ops work and nothing runs on host numpy."""
        import jax
        import jax.numpy as jnp

        from mmlspark_tpu.models.module import Fn

        ran = []

        def jax_only(x):
            ran.append(True)
            # top_k has no numpy equivalent under np-array dispatch
            vals, _ = jax.lax.top_k(x, 3)
            return jnp.swapaxes(vals, -1, -2) if vals.ndim > 2 else vals

        params, out_shape = Fn(jax_only).init(jax.random.key(0), (10,))
        assert out_shape == (3,)
        assert params == {}
        # traced (abstractly) exactly once, never executed concretely
        assert len(ran) == 1


class TestResNet:
    def test_tiny_resnet_forward(self):
        # depth-18 at 32px, width 8: small enough for CPU CI
        import jax
        module = build_resnet(18, num_classes=10, image_size=32, width=8)
        params, out_shape = module.init(jax.random.PRNGKey(0), (32, 32, 3))
        assert out_shape == (10,)
        x = np.random.default_rng(0).normal(size=(2, 32, 32, 3)).astype(np.float32)
        y = np.asarray(module.apply(params, x))
        assert y.shape == (2, 10)
        assert np.isfinite(y).all()

    def test_resnet_tap_avgpool(self):
        m = resnet(18, num_classes=10, image_size=32, width=8)
        x = np.zeros((1, 32, 32, 3), dtype=np.float32)
        feats = np.asarray(m.apply(x, tap="avgpool"))
        assert feats.shape == (1, 8 * 8)  # width 8 * 2^3


class TestDNNModel:
    def test_transform_vectors(self):
        m = tiny_mlp()
        rng = np.random.default_rng(1)
        rows = [rng.normal(size=4).astype(np.float32) for _ in range(11)]
        df = DataFrame.from_dict({"feats": rows}, num_partitions=3)
        stage = DNNModel(inputCol="feats", outputCol="out", batchSize=4).set_model(m)
        out = stage.transform(df)
        col = out.column("out")
        assert len(col) == 11
        ref = np.asarray(m.apply(np.stack(rows)))
        got = np.stack(list(col))
        np.testing.assert_allclose(got, ref, atol=1e-4)

    def test_output_node(self):
        m = tiny_mlp()
        df = DataFrame.from_dict({"feats": [np.ones(4, dtype=np.float32)] * 3})
        stage = (DNNModel(inputCol="feats", outputCol="h", batchSize=2)
                 .set_model(m).set_output_node("relu1"))
        col = stage.transform(df).column("h")
        assert col[0].shape == (8,)

    def test_empty_partition(self):
        m = tiny_mlp()
        df = DataFrame([{"feats": np.empty(0, dtype=object)}])
        stage = DNNModel(inputCol="feats", outputCol="out").set_model(m)
        assert stage.transform(df).count() == 0

    def test_fetch_dict_multi_output_one_forward(self):
        """fetchDict: several output columns, each a different node, all from
        ONE forward (CNTKModel.scala:215-223)."""
        m = tiny_mlp()
        rng = np.random.default_rng(2)
        rows = [rng.normal(size=4).astype(np.float32) for _ in range(7)]
        df = DataFrame.from_dict({"feats": rows}, num_partitions=2)
        stage = (DNNModel(inputCol="feats", batchSize=4).set_model(m)
                 .set_fetch_dict({"logits": "OUTPUT_0", "hidden": "relu1"}))
        out = stage.transform(df)
        logits = np.stack(list(out.column("logits")))
        hidden = np.stack(list(out.column("hidden")))
        np.testing.assert_allclose(logits, np.asarray(m.apply(np.stack(rows))),
                                   atol=1e-4)
        np.testing.assert_allclose(
            hidden, np.asarray(m.apply(np.stack(rows), tap="relu1")),
            atol=1e-4)

    def test_feed_dict_multi_input_graph(self, tmp_path):
        """feedDict: a two-input ONNX graph fed from two columns
        (CNTKModel.scala:204-214)."""
        import mmlspark_tpu.onnx.proto as proto
        from mmlspark_tpu.onnx import import_onnx

        rng = np.random.default_rng(3)
        W = rng.normal(size=(4, 3)).astype(np.float32)
        nodes = [
            proto.make_node("MatMul", ["a", "W"], ["aw"], name="proj"),
            proto.make_node("Add", ["aw", "b"], ["out"], name="sum"),
        ]
        inits = [proto.make_tensor("W", W)]
        blob = proto.make_model(
            nodes, inits,
            [proto.make_value_info("a", [None, 4]),
             proto.make_value_info("b", [None, 3])],
            [proto.make_value_info("out", [None, 3])])
        p = tmp_path / "two_in.onnx"
        p.write_bytes(blob)
        fm = import_onnx(str(p))
        assert fm.argument_names() == ["a", "b"]
        assert fm.resolve_input("ARGUMENT_1") == "b"

        a_rows = [rng.normal(size=4).astype(np.float32) for _ in range(6)]
        b_rows = [rng.normal(size=3).astype(np.float32) for _ in range(6)]
        df = DataFrame.from_dict({"ca": a_rows, "cb": b_rows},
                                 num_partitions=2)
        stage = (DNNModel(outputCol="out", batchSize=4).set_model(fm)
                 .set_feed_dict({"ARGUMENT_0": "ca", "ARGUMENT_1": "cb"}))
        got = np.stack(list(stage.transform(df).column("out")))
        want = np.stack(a_rows) @ W + np.stack(b_rows)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_feed_dict_null_row_any_column(self):
        """A row is null if ANY fed column is null."""
        m = tiny_mlp()
        rows = [np.ones(4, dtype=np.float32), None, np.ones(4, dtype=np.float32)]
        df = DataFrame.from_dict({"feats": np.array(rows, dtype=object)})
        stage = DNNModel(inputCol="feats", outputCol="out",
                         batchSize=2).set_model(m)
        col = stage.transform(df).column("out")
        assert col[1] is None and col[0] is not None

    def test_resolve_input_errors(self):
        m = tiny_mlp()
        assert m.resolve_input("ARGUMENT_0")  # single-arg models: index 0 ok
        with pytest.raises(KeyError):
            m.resolve_input("ARGUMENT_3")
        with pytest.raises(KeyError):
            m.resolve_input("ARGUMENT_-1")   # negative must not wrap around
        with pytest.raises(KeyError):
            m.resolve_input("ARGUMENT_x")
        with pytest.raises(KeyError):
            m.resolve_input("nonexistent_input")

    def _two_input_token_model(self, tmp_path):
        """Embedding-style graph: int token ids Gather + float bias add."""
        import mmlspark_tpu.onnx.proto as proto
        from mmlspark_tpu.onnx import import_onnx

        rng = np.random.default_rng(5)
        table = rng.normal(size=(16, 3)).astype(np.float32)
        nodes = [
            proto.make_node("Gather", ["table", "ids"], ["emb"], name="embed",
                            axis=0),
            proto.make_node("ReduceMean", ["emb"], ["pooled"], name="pool",
                            axes=[1], keepdims=0),
            proto.make_node("Add", ["pooled", "bias"], ["out"], name="sum"),
        ]
        inits = [proto.make_tensor("table", table)]
        blob = proto.make_model(
            nodes, inits,
            [proto.make_value_info("ids", [None, 5],
                                   elem_type=proto.DT_INT32),
             proto.make_value_info("bias", [None, 3])],
            [proto.make_value_info("out", [None, 3])])
        p = tmp_path / "tok.onnx"
        p.write_bytes(blob)
        return import_onnx(str(p), input_shape=(5,)), table

    def test_feed_dict_integer_tokens_preserved(self, tmp_path):
        """Token-id columns must reach embedding Gathers as INTEGERS — the
        batcher preserves int dtypes instead of casting to f32."""
        fm, table = self._two_input_token_model(tmp_path)
        rng = np.random.default_rng(6)
        ids = [rng.integers(0, 16, size=5).astype(np.int32) for _ in range(5)]
        bias = [rng.normal(size=3).astype(np.float32) for _ in range(5)]
        df = DataFrame.from_dict({"ids": ids, "bias": bias})
        stage = (DNNModel(outputCol="out", batchSize=3).set_model(fm)
                 .set_feed_dict({"ids": "ids", "bias": "bias"}))
        got = np.stack(list(stage.transform(df).column("out")))
        want = table[np.stack(ids)].mean(axis=1) + np.stack(bias)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_single_entry_feed_dict_secondary_input_validates(self, tmp_path):
        """A single-entry feedDict naming a SECONDARY input must fail with
        the missing-inputs validation, not silently bind to the primary."""
        fm, _ = self._two_input_token_model(tmp_path)
        df = DataFrame.from_dict(
            {"bias": [np.zeros(3, dtype=np.float32)] * 2})
        stage = (DNNModel(outputCol="out", batchSize=2).set_model(fm)
                 .set_feed_dict("ARGUMENT_1", "bias"))
        with pytest.raises(KeyError, match="not fed"):
            stage.transform(df).column("out")

    def test_multi_input_graph_init_probe(self, tmp_path):
        fm, _ = self._two_input_token_model(tmp_path)
        import jax

        params, out_shape = fm.module.init(jax.random.key(0), (5,))
        assert out_shape == (3,)


class TestImageOps:
    def test_resize_identity(self):
        img = np.arange(48, dtype=np.uint8).reshape(4, 4, 3)
        assert np.array_equal(imops.resize(img, 4, 4), img)

    def test_resize_downscale(self):
        img = np.full((8, 8, 3), 100, dtype=np.uint8)
        out = imops.resize(img, 4, 4)
        assert out.shape == (4, 4, 3)
        assert np.all(out == 100)

    def test_resize_matches_jax(self):
        import jax
        rng = np.random.default_rng(0)
        img = rng.normal(size=(8, 6, 3)).astype(np.float32)
        ours = imops.resize(img, 16, 12)
        theirs = np.asarray(jax.image.resize(img, (16, 12, 3), method="linear"))
        np.testing.assert_allclose(ours, theirs, atol=1e-4)

    def test_flip(self):
        img = np.arange(12, dtype=np.uint8).reshape(2, 2, 3)
        assert np.array_equal(imops.flip(img, 1), img[:, ::-1])
        assert np.array_equal(imops.flip(img, 0), img[::-1])
        assert np.array_equal(imops.flip(img, -1), img[::-1, ::-1])

    def test_gray(self):
        img = np.full((2, 2, 3), 128, dtype=np.uint8)
        g = imops.color_format(img, "gray")
        assert g.shape == (2, 2, 1)
        assert np.all(np.abs(g.astype(int) - 128) <= 1)

    def test_box_blur_constant(self):
        img = np.full((5, 5), 7.0, dtype=np.float32)
        out = imops.box_blur(img, 3, 3)
        np.testing.assert_allclose(out, 7.0, atol=1e-4)

    def test_gaussian_blur_preserves_mean_of_constant(self):
        img = np.full((6, 6), 3.0, dtype=np.float32)
        np.testing.assert_allclose(imops.gaussian_blur(img, 1.0), 3.0, atol=1e-4)

    def test_threshold(self):
        img = np.array([[1.0, 5.0], [10.0, 0.0]], dtype=np.float32)
        out = imops.threshold(img, 4.0, 255.0, "binary")
        assert np.array_equal(out, [[0, 255], [255, 0]])

    def test_unroll_chw(self):
        img = np.arange(12, dtype=np.uint8).reshape(2, 2, 3)
        v = imops.unroll_chw(img)
        assert v.shape == (12,)
        # channel-major: first 4 entries are channel 0
        np.testing.assert_array_equal(v[:4], [0, 3, 6, 9])

    def test_ppm_roundtrip(self):
        rng = np.random.default_rng(0)
        img = rng.integers(0, 256, size=(5, 7, 3), dtype=np.uint8)
        data = imops.encode_ppm(img)
        dec = imops._decode_builtin(data)
        np.testing.assert_array_equal(dec, img)


def image_df(n=6, h=10, w=8, seed=0, num_partitions=2):
    rng = np.random.default_rng(seed)
    rows = [ImageSchema.make(rng.integers(0, 256, size=(h, w, 3), dtype=np.uint8),
                             origin=f"img{i}") for i in range(n)]
    return DataFrame.from_dict({"image": rows, "label": list(range(n))},
                               num_partitions=num_partitions)


class TestImageStages:
    def test_image_transformer_pipeline(self):
        df = image_df()
        t = (ImageTransformer(inputCol="image", outputCol="out")
             .resize(6, 6).flip(1).color_format("gray"))
        out = t.transform(df).column("out")
        assert out[0]["height"] == 6 and out[0]["nChannels"] == 1

    def test_resize_image_transformer(self):
        df = image_df()
        t = ResizeImageTransformer(inputCol="image", outputCol="image",
                                   height=4, width=4)
        out = t.transform(df).column("image")
        assert all(r["height"] == 4 and r["width"] == 4 for r in out)

    def test_unroll(self):
        df = image_df(h=4, w=4)
        out = UnrollImage(inputCol="image", outputCol="unrolled").transform(df)
        v = out.column("unrolled")[0]
        assert v.shape == (4 * 4 * 3,)

    def test_augmenter_doubles_rows(self):
        df = image_df(n=4)
        out = ImageSetAugmenter(inputCol="image", outputCol="image").transform(df)
        assert out.count() == 8

    def test_image_featurizer_end_to_end(self):
        m = resnet(18, num_classes=10, image_size=16, width=8)
        df = image_df(n=5, h=20, w=14)
        feat = (ImageFeaturizer(inputCol="image", outputCol="features", batchSize=4)
                .set_model(m).set_cut_output_layers(1))
        out = feat.transform(df)
        col = out.column("features")
        assert len(col) == 5
        assert col[0].shape == (64,)  # width 8 * 2^3
        assert np.isfinite(np.stack(list(col))).all()

    def test_image_featurizer_logits(self):
        m = resnet(18, num_classes=10, image_size=16, width=8)
        df = image_df(n=3)
        feat = (ImageFeaturizer(inputCol="image", outputCol="logits", batchSize=4)
                .set_model(m).set_cut_output_layers(0))
        col = feat.transform(df).column("logits")
        assert col[0].shape == (10,)

    def test_featurizer_from_bytes(self):
        m = resnet(18, num_classes=10, image_size=16, width=8)
        rng = np.random.default_rng(0)
        blobs = [imops.encode_ppm(rng.integers(0, 256, (9, 9, 3), dtype=np.uint8))
                 for _ in range(3)]
        df = DataFrame.from_dict({"data": blobs})
        feat = (ImageFeaturizer(inputCol="data", outputCol="features")
                .set_model(m))
        col = feat.transform(df).column("features")
        assert len(col) == 3 and col[0].shape == (64,)


class TestReviewRegressions:
    """Regression tests for code-review findings."""

    def test_batchnorm_ema_updated_by_train_step(self):
        import jax
        from mmlspark_tpu.models import training as T
        from mmlspark_tpu.models.module import BatchNorm, Dense, Sequential, relu

        module = Sequential([
            ("dense", Dense(8)),
            ("bn", BatchNorm()),
            ("relu", relu()),
            ("head", Dense(3)),
        ])
        opt = T.make_optimizer(0.01)
        state = T.init_train_state(module, (4,), opt, seed=0)
        step = T.make_train_step(module, opt)
        rng = np.random.default_rng(0)
        batch = {"x": (rng.normal(size=(16, 4)) * 5 + 2).astype(np.float32),
                 "y": (np.arange(16) % 3).astype(np.int32)}
        state, metrics = jax.jit(step)(state, batch)
        mean = np.asarray(state.params["bn"]["mean"])
        var = np.asarray(state.params["bn"]["var"])
        assert not np.allclose(mean, 0.0), "moving mean never updated"
        assert not np.allclose(var, 1.0), "moving var never updated"

    def test_weight_decay_skips_bn_stats(self):
        import jax
        from mmlspark_tpu.models import training as T
        from mmlspark_tpu.models.module import BatchNorm, Dense, Sequential

        module = Sequential([("dense", Dense(4)), ("bn", BatchNorm()), ("head", Dense(2))])
        opt = T.make_optimizer(0.1, weight_decay=0.5)
        state = T.init_train_state(module, (4,), opt, seed=0)
        step = T.make_train_step(module, opt)
        batch = {"x": np.ones((8, 4), dtype=np.float32),
                 "y": np.zeros(8, dtype=np.int32)}
        for _ in range(3):
            state, _ = jax.jit(step)(state, batch)
        # moving var must NOT be decayed toward zero by weight decay
        assert np.asarray(state.params["bn"]["var"]).min() > 0.1

    def test_dnn_model_set_model_invalidates_cache(self):
        m1 = tiny_mlp(dout=3)
        m2 = tiny_mlp(dout=5, seed=1)
        df = DataFrame.from_dict({"feats": [np.ones(4, dtype=np.float32)] * 2})
        stage = DNNModel(inputCol="feats", outputCol="out").set_model(m1)
        assert stage.transform(df).column("out")[0].shape == (3,)
        stage.set_model(m2)
        assert stage.transform(df).column("out")[0].shape == (5,)

    def test_dnn_model_null_rows_pass_through(self):
        m = tiny_mlp()
        col = np.empty(3, dtype=object)
        col[0] = np.ones(4, dtype=np.float32)
        col[1] = None
        col[2] = np.ones(4, dtype=np.float32)
        df = DataFrame([{"feats": col}])
        out = DNNModel(inputCol="feats", outputCol="out").set_model(m).transform(df)
        vals = out.column("out")
        assert vals[1] is None and vals[0] is not None and vals[2] is not None

    def test_featurizer_keep_na(self):
        m = resnet(18, num_classes=10, image_size=16, width=8)
        col = np.empty(2, dtype=object)
        col[0] = ImageSchema.make(np.zeros((8, 8, 3), dtype=np.uint8))
        col[1] = None
        df = DataFrame([{"image": col}])
        feat = (ImageFeaturizer(inputCol="image", outputCol="f", dropNa=False)
                .set_model(m))
        vals = feat.transform(df).column("f")
        assert len(vals) == 2 and vals[1] is None

    def test_residual_inner_taps(self):
        m = resnet(18, num_classes=10, image_size=16, width=8)
        paths = m.module.layer_paths()
        inner = [p for p in paths if "body/" in p]
        assert inner, "residual bodies should be addressable"
        x = np.zeros((1, 16, 16, 3), dtype=np.float32)
        act = np.asarray(m.apply(x, tap=inner[0]))
        assert act.ndim == 4

    def test_function_model_pickles(self):
        import pickle
        m = tiny_mlp()
        blob = pickle.dumps(m.module)
        m2 = pickle.loads(blob)
        x = np.ones((2, 4), dtype=np.float32)
        np.testing.assert_allclose(np.asarray(m2.apply(m.params, x)),
                                   np.asarray(m.apply(x)), atol=1e-5)


class TestMultiDevice:
    """Flagship-path multi-device tests (VERDICT round 1 weak #4): the DNN
    inference and train paths must produce single-device-identical results on
    the 8-virtual-device CPU mesh (SURVEY §4 single-host multi-device
    pattern)."""

    def test_dnn_model_sharded_inference_matches_single_device(self, mesh8):
        from mmlspark_tpu.parallel.mesh import MeshContext

        m = tiny_mlp(din=6, dhid=8, dout=3)
        rng = np.random.default_rng(0)
        n = 40
        df = DataFrame.from_dict(
            {"feats": [rng.normal(size=6) for _ in range(n)]}, num_partitions=2)

        single = DNNModel(inputCol="feats", outputCol="out", batchSize=16,
                          useMesh=False).set_model(m)
        out_single = np.stack(list(single.transform(df).column("out")))

        MeshContext.set(mesh8)
        try:
            # useMesh unset -> auto-on under the active multi-device mesh
            sharded = DNNModel(inputCol="feats", outputCol="out",
                               batchSize=16).set_model(m)
            out_sharded = np.stack(list(sharded.transform(df).column("out")))
        finally:
            MeshContext.reset()
        np.testing.assert_allclose(out_sharded, out_single, atol=1e-5)

    def test_train_step_dp_fsdp_tp_matches_single_device(self):
        """One DP/FSDP/TP train step on a 2x2x2 mesh == single-device step:
        loss, accuracy, and updated params all match (GSPMD-inserted
        collectives change nothing numerically)."""
        import jax
        from mmlspark_tpu.models import matmul_precision
        from mmlspark_tpu.models.resnet import build_resnet
        from mmlspark_tpu.models.training import (
            batch_sharding, compile_train_step, init_train_state,
            make_optimizer)
        from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh

        module = build_resnet(18, num_classes=4, image_size=16, width=8)
        optimizer = make_optimizer(learning_rate=0.1)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 16, 16, 3)).astype(np.float32)
        y = rng.integers(0, 4, size=8).astype(np.int32)
        batch = {"x": x, "y": y}

        # f32 matmuls: bf16 rounding varies with partitioning and would mask
        # real sharding bugs; equivalence must be tight in f32
        with matmul_precision("float32"):
            # single device
            state1 = init_train_state(module, (16, 16, 3), optimizer, seed=3)
            step1 = compile_train_step(module, optimizer)
            state1, metrics1 = step1(state1, dict(batch))

            # 2x2x2 DP/FSDP/TP mesh
            mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
            state2 = init_train_state(module, (16, 16, 3), optimizer, seed=3,
                                      mesh=mesh)
            bs = batch_sharding(mesh)
            sharded_batch = {k: jax.device_put(v, bs) for k, v in batch.items()}
            step2 = compile_train_step(module, optimizer, mesh=mesh)
            state2, metrics2 = step2(state2, sharded_batch)

        assert float(metrics2["loss"]) == pytest.approx(
            float(metrics1["loss"]), abs=1e-4)
        assert float(metrics2["accuracy"]) == pytest.approx(
            float(metrics1["accuracy"]), abs=1e-6)
        flat1 = jax.tree.leaves(state1.params)
        flat2 = jax.tree.leaves(state2.params)
        assert len(flat1) == len(flat2)
        for a, b in zip(flat1, flat2):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=5e-4, rtol=1e-3)

    def test_param_sharding_rules_actually_shard(self):
        """The TP/FSDP seams place conv/dense kernels on mesh axes (not all
        replicated) for the flagship ResNet."""
        import jax
        from mmlspark_tpu.models.resnet import build_resnet
        from mmlspark_tpu.models.training import param_sharding_rules
        from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh

        module = build_resnet(18, num_classes=4, image_size=16, width=8)
        params, _ = module.init(jax.random.PRNGKey(0), (16, 16, 3))
        mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
        shardings = param_sharding_rules(params, mesh)
        specs = [s.spec for s in jax.tree.leaves(shardings)]
        non_replicated = [s for s in specs
                         if any(ax is not None for ax in s)]
        assert len(non_replicated) >= 10, \
            f"expected sharded kernels, got {len(non_replicated)} non-replicated"


class TestTrainStateCheckpoint:
    pytest.importorskip("orbax.checkpoint")

    def test_save_restore_resume(self, tmp_path):
        """Params + opt state + step survive a round trip, and resuming from
        the checkpoint reproduces the uninterrupted trajectory exactly."""
        import jax
        from mmlspark_tpu.models.checkpoint import (load_train_state,
                                                    save_train_state)
        from mmlspark_tpu.models.resnet import build_resnet
        from mmlspark_tpu.models.training import (compile_train_step,
                                                  init_train_state,
                                                  make_optimizer)

        module = build_resnet(18, num_classes=4, image_size=16, width=8)
        opt = make_optimizer(learning_rate=0.1)
        rng = np.random.default_rng(0)
        batches = [{"x": rng.normal(size=(4, 16, 16, 3)).astype(np.float32),
                    "y": rng.integers(0, 4, size=4).astype(np.int32)}
                   for _ in range(4)]
        step = compile_train_step(module, opt)

        # uninterrupted: 4 steps
        s = init_train_state(module, (16, 16, 3), opt, seed=1)
        for b in batches:
            s, _ = step(s, dict(b))
        ref = jax.tree.leaves(s.params)

        # interrupted: 2 steps, checkpoint, restore, 2 more
        s2 = init_train_state(module, (16, 16, 3), opt, seed=1)
        for b in batches[:2]:
            s2, _ = step(s2, dict(b))
        ck = str(tmp_path / "ckpt")
        save_train_state(s2, ck)

        like = init_train_state(module, (16, 16, 3), opt, seed=99)
        s3 = load_train_state(ck, like=like)
        assert int(s3.step) == 2
        for b in batches[2:]:
            s3, _ = step(s3, dict(b))
        got = jax.tree.leaves(s3.params)
        for a, b_ in zip(ref, got):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=1e-6)

    def test_restore_onto_mesh_shardings(self, mesh8, tmp_path):
        """Restore with a mesh-sharded reference state places arrays back on
        the mesh (the multi-chip resume path)."""
        from mmlspark_tpu.models.checkpoint import (load_train_state,
                                                    save_train_state)
        from mmlspark_tpu.models.resnet import build_resnet
        from mmlspark_tpu.models.training import (init_train_state,
                                                  make_optimizer)
        import jax

        module = build_resnet(18, num_classes=4, image_size=16, width=8)
        opt = make_optimizer()
        s = init_train_state(module, (16, 16, 3), opt, seed=0)
        ck = str(tmp_path / "ckpt")
        save_train_state(s, ck)

        from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
        mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
        like = init_train_state(module, (16, 16, 3), opt, seed=5, mesh=mesh)
        restored = load_train_state(ck, like=like)
        leaf0 = jax.tree.leaves(like.params)[0]
        r0 = jax.tree.leaves(restored.params)[0]
        assert r0.sharding == leaf0.sharding
        # values must be the saved ones, not `like`'s
        a = np.asarray(jax.tree.leaves(s.params)[0])
        np.testing.assert_allclose(np.asarray(r0), a, atol=0)
