"""Tests for the unified ingest layer (parallel/ingest.py): PreprocessSpec,
TransferRing, IngestStats, the uint8 wire format through DNNModel /
ImageFeaturizer, and the satellite bugfix regressions that ride this PR."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.parallel.ingest import (
    IngestStats, PreprocessSpec, TransferRing,
)


def tiny_mlp(din=4, dhid=8, dout=3, seed=0):
    import jax

    from mmlspark_tpu.models import Dense, FunctionModel, Sequential, relu

    module = Sequential([
        ("dense1", Dense(dhid)),
        ("relu1", relu()),
        ("dense2", Dense(dout)),
    ], name="mlp")
    params, _ = module.init(jax.random.PRNGKey(seed), (din,))
    return FunctionModel(module, params, (din,),
                         layer_names=["dense2", "relu1", "dense1"])


class TestPreprocessSpec:
    def test_host_device_parity(self):
        spec = PreprocessSpec(scale=1.0 / 255, offset=-0.5)
        x = np.random.default_rng(0).integers(0, 256, (4, 6, 6, 3),
                                              dtype=np.uint8)
        host = spec.apply_host(x)
        dev = np.asarray(spec.apply_device(x))
        assert host.dtype == np.float32
        np.testing.assert_array_equal(host, dev)

    def test_transpose_matches_legacy_host_layout(self):
        # the legacy NCHW host path: astype(f32) * scale, then per-row
        # img.transpose(2, 0, 1)
        spec = PreprocessSpec(scale=2.0, transpose=(2, 0, 1))
        x = np.random.default_rng(1).integers(0, 256, (3, 5, 7, 2),
                                              dtype=np.uint8)
        legacy = np.stack([(r.astype(np.float32) * np.float32(2.0)
                            ).transpose(2, 0, 1) for r in x])
        np.testing.assert_array_equal(spec.apply_host(x), legacy)
        np.testing.assert_array_equal(np.asarray(spec.apply_device(x)), legacy)

    def test_identity_and_hashable(self):
        assert PreprocessSpec().is_identity
        assert not PreprocessSpec(scale=0.5).is_identity
        # jit-cache keys hash the spec
        assert hash(PreprocessSpec(scale=0.5)) == hash(PreprocessSpec(scale=0.5))
        assert PreprocessSpec(transpose=[2, 0, 1]) == \
            PreprocessSpec(transpose=(2, 0, 1))

    def test_identity_still_casts(self):
        x = np.arange(8, dtype=np.uint8).reshape(2, 4)
        assert PreprocessSpec().apply_host(x).dtype == np.float32


class TestTransferRing:
    def _run(self, n=7, depth=2, **kw):
        stats = IngestStats()
        ring = TransferRing((np.full((4, 3), i, dtype=np.float32)
                             for i in range(n)),
                            step=lambda x: x * 2.0,
                            fetch=lambda y: np.asarray(y),
                            depth=depth, stats=stats, **kw)
        return list(ring), stats

    def test_order_and_results(self):
        outs, stats = self._run(n=7, depth=3)
        assert len(outs) == 7
        for i, o in enumerate(outs):
            np.testing.assert_array_equal(o, np.full((4, 3), 2.0 * i))

    def test_depth_variants_agree(self):
        base, _ = self._run(n=5, depth=1)
        for depth in (2, 4, 16):
            outs, _ = self._run(n=5, depth=depth)
            for a, b in zip(base, outs):
                np.testing.assert_array_equal(a, b)

    def test_stats_populated(self):
        outs, stats = self._run(n=6, depth=2)
        s = stats.summary()
        assert s["n_batches"] == 6
        assert s["rows"] == 6 * 4
        assert s["bytes"] == 6 * 4 * 3 * 4  # f32 batches
        assert s["wall_s"] > 0
        for f in ("queue", "h2d", "dispatch", "compute", "readback"):
            assert s[f + "_s"] >= 0.0
            assert s[f + "_ms_per_batch"] >= 0.0
        assert s["overlap_ratio"] is None or s["overlap_ratio"] > 0

    def test_empty_iterator(self):
        outs, stats = self._run(n=0)
        assert outs == []
        assert stats.summary() == {"n_batches": 0}

    def test_put_runs_on_prefetch_thread(self):
        names = []

        def put(x):
            names.append(threading.current_thread().name)
            return x

        list(TransferRing(iter([1, 2, 3]), put=put, depth=2))
        assert names and all(n == "device-prefetch" for n in names)

    def test_producer_exception_propagates(self):
        def bad():
            yield 1
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="boom"):
            list(TransferRing(bad(), depth=2))

    def test_close_mid_stream_releases_producer(self):
        produced = []

        def slow():
            for i in range(100):
                produced.append(i)
                time.sleep(0.005)
                yield i

        ring = TransferRing(slow(), depth=2)
        it = iter(ring)
        next(it)
        ring.close()
        it.close()
        # the producer thread must terminate instead of spinning the full
        # 100-item iterator (or blocking on the bounded queue forever)
        ring._prefetch._thread.join(timeout=5)
        assert not ring._prefetch._thread.is_alive()
        assert len(produced) < 100

    def test_ring_with_jit_step(self):
        import jax

        f = jax.jit(lambda x: x.astype(np.float32) * (1.0 / 255))
        stats = IngestStats()
        batches = [np.random.default_rng(i).integers(0, 256, (8, 5),
                                                     dtype=np.uint8)
                   for i in range(4)]
        ring = TransferRing(iter(batches), put=jax.device_put, step=f,
                            fetch=lambda y: np.asarray(y), depth=2,
                            stats=stats)
        outs = list(ring)
        for b, o in zip(batches, outs):
            np.testing.assert_allclose(o, b.astype(np.float32) / 255,
                                       rtol=1e-6)
        assert stats.summary()["bytes"] == sum(b.nbytes for b in batches)


class TestDNNModelIngest:
    def _df(self, n=11, din=4, parts=2, dtype=np.float32, seed=1):
        rng = np.random.default_rng(seed)
        if np.issubdtype(dtype, np.integer):
            rows = [rng.integers(0, 256, size=din).astype(dtype)
                    for _ in range(n)]
        else:
            rows = [rng.normal(size=din).astype(dtype) for _ in range(n)]
        return DataFrame.from_dict({"feats": rows}, num_partitions=parts), rows

    def test_uint8_wire_with_spec_matches_host_preprocess(self):
        from mmlspark_tpu.models import DNNModel

        m = tiny_mlp()
        df, rows = self._df(dtype=np.uint8)
        spec = PreprocessSpec(scale=1.0 / 255)
        dev = (DNNModel(inputCol="feats", outputCol="out", batchSize=4)
               .set_model(m).set_preprocess(spec))
        got = np.stack(list(dev.transform(df).column("out")))
        # host oracle: preprocess on host, plain forward
        host_in = spec.apply_host(np.stack(rows))
        ref = np.asarray(m.apply(host_in))
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_ring_depth_param_parity(self):
        from mmlspark_tpu.models import DNNModel

        m = tiny_mlp()
        df, rows = self._df(n=13)
        base = None
        for depth in (1, 2, 5):
            stage = DNNModel(inputCol="feats", outputCol="out", batchSize=4,
                             ringDepth=depth).set_model(m)
            got = np.stack(list(stage.transform(df).column("out")))
            if base is None:
                base = got
            else:
                np.testing.assert_allclose(got, base, atol=1e-6)

    def test_donation_noop_on_cpu(self):
        """donateInputs=True on CPU: donation is a no-op there, results and
        buffers must be unaffected (the donated executable still runs)."""
        from mmlspark_tpu.models import DNNModel

        m = tiny_mlp()
        df, rows = self._df(n=9)
        plain = (DNNModel(inputCol="feats", outputCol="out", batchSize=4,
                          donateInputs=False).set_model(m))
        ref = np.stack(list(plain.transform(df).column("out")))
        donated = (DNNModel(inputCol="feats", outputCol="out", batchSize=4,
                            donateInputs=True).set_model(m))
        got = np.stack(list(donated.transform(df).column("out")))
        np.testing.assert_allclose(got, ref, atol=1e-6)

    def test_ingest_stats_surface(self):
        from mmlspark_tpu.models import DNNModel

        m = tiny_mlp()
        df, _ = self._df(n=10)
        stage = DNNModel(inputCol="feats", outputCol="out",
                         batchSize=4).set_model(m)
        assert stage.last_ingest_stats is None
        stage.transform(df)
        s = stage.last_ingest_stats.summary()
        assert s["n_batches"] >= 3  # 10 rows / batch 4, both partitions
        assert s["rows"] == 10
        assert s["bytes"] > 0
        for f in ("queue_s", "h2d_s", "compute_s", "readback_s"):
            assert s[f] >= 0.0

    def test_sharding_indivisible_batch_stays_uncommitted(self, mesh8):
        """A batch not divisible by the mesh's data axis must eval as an
        uncommitted host array (committing would conflict with replicated
        params inside jit) and still produce correct rows."""
        from mmlspark_tpu.models import DNNModel
        from mmlspark_tpu.parallel.mesh import MeshContext

        m = tiny_mlp(din=6)
        rng = np.random.default_rng(0)
        rows = [rng.normal(size=6).astype(np.float32) for _ in range(5)]
        df = DataFrame.from_dict({"feats": rows})
        single = DNNModel(inputCol="feats", outputCol="out", batchSize=3,
                          useMesh=False).set_model(m)
        ref = np.stack(list(single.transform(df).column("out")))
        MeshContext.set(mesh8)
        try:
            # batchSize=3: batches of 3 and 2, neither divisible by 8
            sharded = DNNModel(inputCol="feats", outputCol="out",
                               batchSize=3).set_model(m)
            got = np.stack(list(sharded.transform(df).column("out")))
        finally:
            MeshContext.reset()
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_preprocess_with_feed_fetch_dicts(self):
        """The spec composes with the feedDict/fetchDict surface as long as
        the model stays single-input (multi-output is fine: ONE forward)."""
        from mmlspark_tpu.models import DNNModel

        m = tiny_mlp()
        df, rows = self._df(n=4)
        stage = (DNNModel(batchSize=2).set_model(m)
                 .set_feed_dict({"ARGUMENT_0": "feats"})
                 .set_fetch_dict({"out": "OUTPUT_0", "h": "relu1"})
                 .set_preprocess(PreprocessSpec(scale=0.5)))
        out = stage.transform(df)
        ref = np.asarray(m.apply(np.stack(rows) * np.float32(0.5)))
        np.testing.assert_allclose(np.stack(list(out.column("out"))), ref,
                                   atol=1e-5)
        assert out.column("h")[0].shape == (8,)


class TestImageFeaturizerWire:
    def _image_df(self, n=5, h=20, w=14, seed=0):
        from mmlspark_tpu.core.schema import ImageSchema

        rng = np.random.default_rng(seed)
        col = np.empty(n, dtype=object)
        for i in range(n):
            col[i] = ImageSchema.make(
                rng.integers(0, 256, (h, w, 3), dtype=np.uint8))
        return DataFrame([{"image": col}])

    def test_uint8_wire_matches_float32_host_path(self):
        """Acceptance: uint8-wire output == legacy float32 host-preprocess
        output within atol=1e-5 on CPU."""
        from mmlspark_tpu.models import resnet

        from mmlspark_tpu.image import ImageFeaturizer

        m = resnet(18, num_classes=10, image_size=16, width=8)
        df = self._image_df()
        kw = dict(inputCol="image", outputCol="features", batchSize=4,
                  scaleFactor=1.0 / 255)
        wire = (ImageFeaturizer(**kw).set_model(m).set_cut_output_layers(1))
        legacy = (ImageFeaturizer(hostPreprocess=True, **kw)
                  .set_model(m).set_cut_output_layers(1))
        got = np.stack(list(wire.transform(df).column("features")))
        ref = np.stack(list(legacy.transform(df).column("features")))
        assert got.shape == ref.shape == (5, 64)
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_featurizer_exposes_ingest_stats(self):
        from mmlspark_tpu.models import resnet

        from mmlspark_tpu.image import ImageFeaturizer

        m = resnet(18, num_classes=10, image_size=16, width=8)
        feat = (ImageFeaturizer(inputCol="image", outputCol="f", batchSize=4)
                .set_model(m))
        assert feat.last_ingest_stats is None
        feat.transform(self._image_df(n=3))
        s = feat.last_ingest_stats.summary()
        assert s["n_batches"] >= 1 and s["rows"] == 3
        # wire bytes: 3 uint8 images of 16*16*3 padded to one bucket-of-4
        # batch -> 4 * 16*16*3 bytes (1/4 of the float32 wire)
        assert s["bytes"] == 4 * 16 * 16 * 3

    def test_wire_bytes_quarter_of_float32(self):
        """The uint8 wire ships exactly 1/4 the bytes of the legacy path."""
        from mmlspark_tpu.models import resnet

        from mmlspark_tpu.image import ImageFeaturizer

        m = resnet(18, num_classes=10, image_size=16, width=8)
        df = self._image_df(n=4)
        kw = dict(inputCol="image", outputCol="f", batchSize=4)
        wire = ImageFeaturizer(**kw).set_model(m)
        wire.transform(df)
        legacy = ImageFeaturizer(hostPreprocess=True, **kw).set_model(m)
        legacy.transform(df)
        b_wire = wire.last_ingest_stats.summary()["bytes"]
        b_legacy = legacy.last_ingest_stats.summary()["bytes"]
        assert b_wire * 4 == b_legacy


class TestGbdtRingScoring:
    def test_chunked_predict_rides_ring(self):
        """Chunked GEMM scoring through the transfer ring matches the
        single-dispatch path and records ingest stats."""
        from mmlspark_tpu.gbdt import LightGBMRegressor

        rng = np.random.default_rng(0)
        X = rng.normal(size=(300, 4))
        y = X[:, 0] * 2 + X[:, 1] - X[:, 2] * 0.5
        df = DataFrame.from_dict({"features": [X[i] for i in range(300)],
                                  "label": y})
        model = LightGBMRegressor(numIterations=8, numLeaves=7,
                                  minDataInLeaf=5).fit(df)
        ref = np.asarray(model.transform(df).column("prediction"),
                         dtype=np.float64)
        ens = model._ensemble()
        if ens.cat_host_fallback or ens._gemm is None:
            pytest.skip("host-fallback ensemble has no device chunk path")
        old_chunk = ens._gemm_row_chunk
        try:
            ens._gemm_row_chunk = 64  # force chunking (300 rows -> 5 chunks)
            got = np.asarray(model.transform(df).column("prediction"),
                             dtype=np.float64)
        finally:
            ens._gemm_row_chunk = old_chunk
        np.testing.assert_allclose(got, ref, atol=1e-6)
        s = ens.last_ingest_stats.summary()
        assert s["n_batches"] == 5
        assert s["rows"] == 300


class TestServingIngestSurface:
    def test_stats_endpoint_reports_ingest(self):
        """serve_pipeline over a DNNModel: /_mmlspark/stats carries the
        device-ingest decomposition next to the latency percentiles."""
        from mmlspark_tpu.models import DNNModel
        from mmlspark_tpu.serving import serve_pipeline

        m = tiny_mlp()
        stage = DNNModel(inputCol="features", outputCol="reply",
                         batchSize=4).set_model(m)
        server = serve_pipeline(stage, input_col="features", port=0)
        with server:
            payload = json.dumps({"data": [1.0, 2.0, 3.0, 4.0]}).encode()
            req = urllib.request.Request(server.address, data=payload,
                                         method="POST")
            with urllib.request.urlopen(req, timeout=15) as resp:
                resp.read()
            with urllib.request.urlopen(
                    f"http://{server.host}:{server.port}/_mmlspark/stats",
                    timeout=15) as resp:
                remote = json.loads(resp.read())
        assert "ingest" in remote
        assert remote["ingest"]["n_batches"] >= 1
        for f in ("queue_s", "h2d_s", "compute_s", "readback_s"):
            assert f in remote["ingest"]


class TestBatcherCloseRaceRegressions:
    """ADVICE.md round-5: close-vs-producer races in parallel/batching.py."""

    def test_dynamic_batcher_sentinel_never_leaks_as_data(self):
        from mmlspark_tpu.parallel.batching import DynamicBufferedBatcher

        # Force the race deterministically: fill the queue, then inject the
        # DONE mid-queue the way a racing producer put would leave it
        b = DynamicBufferedBatcher(iter([]), max_buffer=10)
        b._thread.join(timeout=5)
        while not b._q.empty():
            b._q.get_nowait()
        b._q.put(1)
        b._q.put(2)
        b._q.put(b._DONE)
        b._q.put(3)  # a racing put landing AFTER the sentinel
        got = [item for batch in b for item in batch]
        assert got == [1, 2]  # post-sentinel item abandoned, sentinel hidden

    def test_dynamic_batcher_close_unblocks_consumer(self):
        from mmlspark_tpu.parallel.batching import DynamicBufferedBatcher

        def slow():
            yield 1
            time.sleep(30)
            yield 2

        b = DynamicBufferedBatcher(slow(), max_buffer=2)
        consumed = []
        done = threading.Event()

        def consume():
            for batch in b:
                consumed.append(batch)
                b.close()  # external close mid-iteration
            done.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        assert done.wait(timeout=10), "consumer stranded after close()"
        assert all(b._DONE not in batch for batch in consumed)

    def test_device_prefetcher_close_unblocks_consumer(self):
        from mmlspark_tpu.parallel.batching import DevicePrefetcher

        def hang():
            yield 1
            time.sleep(30)
            yield 2

        p = DevicePrefetcher(hang(), depth=1)
        it = iter(p)
        assert next(it) == 1
        # close from another thread while the consumer is about to block
        closer = threading.Timer(0.2, p.close)
        closer.start()
        rest = list(it)  # must return promptly instead of hanging forever
        assert rest == []


class TestVwNativeFallbackRegression:
    def test_vw_train_pass_none_falls_back_to_scan(self, monkeypatch):
        """A vanished .so between the _native_pass_ok probe and the call must
        fall through to the jax scan engine (not TypeError under python -O)."""
        from mmlspark_tpu import native_loader
        from mmlspark_tpu.vw import learner as L

        monkeypatch.setattr(L, "_native_pass_ok", lambda cfg: True)
        monkeypatch.setattr(native_loader, "vw_train_pass",
                            lambda *a, **k: None)
        cfg = L.LearnerConfig(num_bits=8, num_passes=2, loss_function="squared")
        rng = np.random.default_rng(0)
        rows = [{"indices": np.array([i % 5]), "values": np.array([1.0]),
                 "size": 256} for i in range(20)]
        ds = L.SparseDataset.from_rows(rows, rng.normal(size=20), num_bits=8)
        w, stats = L.train_linear(cfg, ds)
        assert w.shape == (256,)
        assert np.isfinite(w).all()
        assert len(stats) == 2  # scan engine ran both passes
        assert not np.allclose(w, 0.0)  # it actually trained


class TestParseReadableModelRegression:
    def test_oob_index_raises(self):
        from mmlspark_tpu.vw import parse_readable_model

        text = "bits:4\n3:0.5\n200:1.0\n"
        with pytest.raises(ValueError, match="outside the 4-bit"):
            parse_readable_model(text)

    def test_missing_bits_header_warns(self):
        from mmlspark_tpu.vw import parse_readable_model

        with pytest.warns(UserWarning, match="no bits header"):
            bits, w = parse_readable_model("7:0.25\n")
        assert bits == 18 and w[7] == 0.25

    def test_clean_dump_no_warning(self):
        import warnings as W

        from mmlspark_tpu.vw import parse_readable_model

        with W.catch_warnings():
            W.simplefilter("error")
            bits, w = parse_readable_model("bits:10\n7:0.25\n")
        assert bits == 10 and w[7] == 0.25


class TestRenderCommentRegression:
    def test_quoted_hash_preserved(self):
        import importlib.util
        from pathlib import Path

        spec = importlib.util.spec_from_file_location(
            "render", Path(__file__).parent.parent / "tools/k8s/render.py")
        render = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(render)
        text = ('image: "repo/app#sha256"\n'
                "tag: v1.0   # trailing comment\n"
                "token: 'a#b'\n"
                "plain: a#b\n")
        vals = {}
        for line in text.splitlines():
            line = render._strip_comment(line)
            if not line:
                continue
            k, _, v = line.partition(":")
            vals[k] = render._coerce(v.strip())
        assert vals["image"] == "repo/app#sha256"
        assert vals["tag"] == "v1.0"
        assert vals["token"] == "a#b"
        assert vals["plain"] == "a#b"  # no preceding whitespace: not a comment
