"""E2E tier: run every example journey as a subprocess and assert success
(the reference's nbtest layer, DatabricksUtilities.scala — here the journeys
are plain scripts so the tier needs no cluster)."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

EXAMPLES_DIR = Path(__file__).resolve().parent.parent / "examples"
SCRIPTS = sorted(p.name for p in EXAMPLES_DIR.glob("*.py")
                 if not p.name.startswith("_"))


def test_every_example_is_covered():
    """Reflection guard, FuzzingTest-style: a new example script is
    automatically picked up (parametrization is generated from the dir)."""
    assert len(SCRIPTS) >= 10


@pytest.mark.parametrize("script", SCRIPTS)
def test_example_runs(script):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [str(EXAMPLES_DIR.parent), env.get("PYTHONPATH", "")])
    # the env var alone is not enough: the image's sitecustomize registers
    # the TPU plugin in every interpreter, so pin the platform the way
    # conftest.py does — post-import config.update — then run the script
    runner = (
        "import sys, runpy, jax; "
        "jax.config.update('jax_platforms', 'cpu'); "
        "runpy.run_path(sys.argv[1], run_name='__main__')"
    )
    proc = subprocess.run(
        [sys.executable, "-c", runner, str(EXAMPLES_DIR / script)],
        capture_output=True, text=True, timeout=900,
        cwd=str(EXAMPLES_DIR), env=env)
    assert proc.returncode == 0, \
        f"{script} failed:\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    assert "EXAMPLE OK" in proc.stdout, proc.stdout
