"""ONNX import/export + torch-checkpoint import.

Validation strategy (reference: the CNTK bridge is unit-tested directly against the
native engine, cntk/CNTKBindingSuite.scala):
  - proto round-trip: writer bytes parse back identically,
  - torch cross-validation: a torch CNN's weights hand-packed into ONNX by our writer,
    imported by our reader, must reproduce torch's forward within 1e-3,
  - native round-trip: export_onnx(resnet18) -> import_onnx reproduces the native model,
  - from_torch_resnet: transplanted torchvision-style ResNet matches torch bit-nearly.
"""

import numpy as np
import pytest

import mmlspark_tpu.onnx.proto as proto
from mmlspark_tpu.onnx import export_onnx, import_onnx

torch = pytest.importorskip("torch")


def _onnx_from_torch_seq(model, in_shape, path):
    """Hand-pack a small eval-mode torch CNN into ONNX bytes with our writer.

    Supports the layer types used in the fixtures below. This deliberately exercises
    the *reader* against torch's reference numerics without needing the onnx package.
    """
    import torch.nn as nn

    nodes, inits = [], []
    cur = "input"
    n = [0]

    def t(hint):
        n[0] += 1
        return f"{hint}_{n[0]}"

    def add_init(hint, arr):
        name = t(hint)
        inits.append(proto.make_tensor(name, np.ascontiguousarray(arr)))
        return name

    def emit(op, ins, hint, **attrs):
        out = t(hint)
        nodes.append(proto.make_node(op, ins, [out], name=out, **attrs))
        return out

    for layer in model:
        if isinstance(layer, nn.Conv2d):
            w = layer.weight.detach().numpy()
            ins = [cur, add_init("w", w)]
            if layer.bias is not None:
                ins.append(add_init("b", layer.bias.detach().numpy()))
            p = layer.padding if isinstance(layer.padding, tuple) else (layer.padding,) * 2
            cur = emit("Conv", ins, "conv",
                       strides=list(layer.stride),
                       kernel_shape=list(layer.kernel_size),
                       pads=[p[0], p[1], p[0], p[1]],
                       group=layer.groups)
        elif isinstance(layer, nn.BatchNorm2d):
            ins = [cur,
                   add_init("s", layer.weight.detach().numpy()),
                   add_init("bb", layer.bias.detach().numpy()),
                   add_init("m", layer.running_mean.numpy()),
                   add_init("v", layer.running_var.numpy())]
            cur = emit("BatchNormalization", ins, "bn", epsilon=float(layer.eps))
        elif isinstance(layer, nn.ReLU):
            cur = emit("Relu", [cur], "relu")
        elif isinstance(layer, nn.MaxPool2d):
            k = layer.kernel_size if isinstance(layer.kernel_size, tuple) \
                else (layer.kernel_size,) * 2
            s = layer.stride if isinstance(layer.stride, tuple) else (layer.stride,) * 2
            p = layer.padding if isinstance(layer.padding, tuple) else (layer.padding,) * 2
            cur = emit("MaxPool", [cur], "maxpool", kernel_shape=list(k),
                       strides=list(s), pads=[p[0], p[1], p[0], p[1]],
                       ceil_mode=int(layer.ceil_mode))
        elif isinstance(layer, nn.AvgPool2d):
            k = layer.kernel_size if isinstance(layer.kernel_size, tuple) \
                else (layer.kernel_size,) * 2
            cur = emit("AveragePool", [cur], "avgpool", kernel_shape=list(k),
                       strides=list(k),
                       count_include_pad=int(layer.count_include_pad))
        elif isinstance(layer, nn.AdaptiveAvgPool2d):
            cur = emit("GlobalAveragePool", [cur], "gap")
        elif isinstance(layer, nn.Flatten):
            cur = emit("Flatten", [cur], "flatten", axis=1)
        elif isinstance(layer, nn.Linear):
            ins = [cur, add_init("fw", layer.weight.detach().numpy())]
            if layer.bias is not None:
                ins.append(add_init("fb", layer.bias.detach().numpy()))
            cur = emit("Gemm", ins, "gemm", transB=1)
        elif isinstance(layer, nn.Sigmoid):
            cur = emit("Sigmoid", [cur], "sigmoid")
        elif isinstance(layer, nn.Dropout):
            cur = emit("Dropout", [cur], "dropout")
        elif isinstance(layer, nn.ConvTranspose2d):
            w = layer.weight.detach().numpy()  # [C_in, C_out/g, kH, kW]
            ins = [cur, add_init("wt", w)]
            if layer.bias is not None:
                ins.append(add_init("bt", layer.bias.detach().numpy()))
            p = layer.padding if isinstance(layer.padding, tuple) \
                else (layer.padding,) * 2
            op = layer.output_padding if isinstance(layer.output_padding, tuple) \
                else (layer.output_padding,) * 2
            cur = emit("ConvTranspose", ins, "convt",
                       strides=list(layer.stride),
                       kernel_shape=list(layer.kernel_size),
                       pads=[p[0], p[1], p[0], p[1]],
                       output_padding=list(op), group=layer.groups)
        elif isinstance(layer, nn.InstanceNorm2d):
            ins = [cur,
                   add_init("is", layer.weight.detach().numpy()
                            if layer.affine else
                            np.ones(layer.num_features, np.float32)),
                   add_init("ib", layer.bias.detach().numpy()
                            if layer.affine else
                            np.zeros(layer.num_features, np.float32))]
            cur = emit("InstanceNormalization", ins, "inorm",
                       epsilon=float(layer.eps))
        elif isinstance(layer, nn.LayerNorm):
            ins = [cur, add_init("lns", layer.weight.detach().numpy()),
                   add_init("lnb", layer.bias.detach().numpy())]
            cur = emit("LayerNormalization", ins, "lnorm",
                       axis=-len(layer.normalized_shape),
                       epsilon=float(layer.eps))
        elif isinstance(layer, nn.GELU):
            cur = emit("Gelu", [cur], "gelu",
                       approximate=layer.approximate)
        elif isinstance(layer, nn.ELU):
            cur = emit("Elu", [cur], "elu", alpha=float(layer.alpha))
        elif isinstance(layer, nn.Softplus):
            cur = emit("Softplus", [cur], "softplus")
        else:
            raise NotImplementedError(type(layer))

    blob = proto.make_model(
        nodes, inits,
        [proto.make_value_info("input", [None] + list(in_shape))],
        [proto.make_value_info(cur, [None, -1])])
    with open(path, "wb") as fh:
        fh.write(blob)
    return path


class TestProtoRoundTrip:
    def test_tensor_roundtrip(self):
        for arr in [np.arange(12, dtype=np.float32).reshape(3, 4),
                    np.array([1, -5, 2**40], dtype=np.int64),
                    np.random.default_rng(0).normal(size=(2, 3, 4, 5)).astype(np.float32)]:
            blob = proto.make_tensor("t", arr).tobytes()
            back = proto.Tensor(blob)
            assert back.name == "t"
            np.testing.assert_array_equal(back.to_numpy(), arr)

    def test_node_attrs_roundtrip(self):
        blob = proto.make_node("Conv", ["x", "w"], ["y"], name="c1",
                               strides=[2, 2], pads=[3, 3, 3, 3],
                               epsilon=1e-5, mode="constant").tobytes()
        node = proto.Node(blob)
        assert node.op_type == "Conv"
        assert node.inputs == ["x", "w"] and node.outputs == ["y"]
        assert node.attrs["strides"] == [2, 2]
        assert node.attrs["pads"] == [3, 3, 3, 3]
        assert abs(node.attrs["epsilon"] - 1e-5) < 1e-12
        assert node.attrs["mode"] == b"constant"

    def test_model_roundtrip(self):
        w = np.random.default_rng(1).normal(size=(4, 3)).astype(np.float32)
        blob = proto.make_model(
            [proto.make_node("Gemm", ["input", "w"], ["out"], name="g", transB=1)],
            [proto.make_tensor("w", w)],
            [proto.make_value_info("input", [None, 3])],
            [proto.make_value_info("out", [None, 4])])
        m = proto.Model(blob)
        assert m.graph.nodes[0].op_type == "Gemm"
        assert m.graph.inputs[0].dims == [None, 3]
        np.testing.assert_array_equal(m.graph.initializers[0].to_numpy(), w)
        assert m.opset == 13


class TestTorchCrossValidation:
    """Imported graphs must reproduce torch's reference forward pass."""

    def _check(self, model, in_shape, tmp_path, atol=1e-3):
        import torch

        model.eval()
        path = _onnx_from_torch_seq(model, in_shape, str(tmp_path / "m.onnx"))
        fm = import_onnx(path)
        x = np.random.default_rng(7).normal(size=(4,) + tuple(in_shape)).astype(np.float32)
        with torch.no_grad():
            want = model(torch.from_numpy(x)).numpy()
        got = np.asarray(fm.apply(x))
        np.testing.assert_allclose(got, want.reshape(got.shape), atol=atol, rtol=1e-3)
        return fm

    def test_conv_bn_relu_pool_linear(self, tmp_path):
        import torch.nn as nn

        torch.manual_seed(0)
        model = nn.Sequential(
            nn.Conv2d(3, 8, 3, stride=2, padding=1),
            nn.BatchNorm2d(8), nn.ReLU(),
            nn.MaxPool2d(3, stride=2, padding=1),
            nn.Conv2d(8, 16, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2d(1), nn.Flatten(), nn.Linear(16, 5))
        # make BN stats non-trivial
        model[1].running_mean.normal_(0, 0.5)
        model[1].running_var.uniform_(0.5, 2.0)
        self._check(model, (3, 17, 17), tmp_path)  # odd dims: exercises pad math

    def test_grouped_conv_sigmoid(self, tmp_path):
        import torch.nn as nn

        torch.manual_seed(1)
        model = nn.Sequential(
            nn.Conv2d(4, 8, 3, padding=1), nn.ReLU(),
            nn.Conv2d(8, 8, 3, padding=1, groups=8),  # depthwise
            nn.Sigmoid(),
            nn.AdaptiveAvgPool2d(1), nn.Flatten(), nn.Linear(8, 3))
        self._check(model, (4, 12, 12), tmp_path)

    def test_avgpool_dropout(self, tmp_path):
        import torch.nn as nn

        torch.manual_seed(2)
        model = nn.Sequential(
            nn.Conv2d(2, 4, 5, padding=2), nn.ReLU(), nn.Dropout(0.5),
            nn.AvgPool2d(2),
            nn.Flatten(), nn.Linear(4 * 8 * 8, 6))
        self._check(model, (2, 16, 16), tmp_path)

    def test_taps_and_layer_names(self, tmp_path):
        import torch.nn as nn

        torch.manual_seed(3)
        model = nn.Sequential(
            nn.Conv2d(3, 4, 3, padding=1), nn.ReLU(),
            nn.AdaptiveAvgPool2d(1), nn.Flatten(), nn.Linear(4, 2))
        model.eval()
        path = _onnx_from_torch_seq(model, (3, 8, 8), str(tmp_path / "m.onnx"))
        fm = import_onnx(path)
        assert fm.layer_names, "importer should auto-derive layer_names"
        x = np.random.default_rng(0).normal(size=(2, 3, 8, 8)).astype(np.float32)
        emb = fm.apply(x, tap=fm.resolve_output("OUTPUT_1"))
        assert emb.shape[0] == 2 and emb.ndim >= 2
        paths = fm.module.layer_paths()
        assert all("_" in p for p in paths)  # node names addressable


class TestFromTorchResnet:
    @pytest.mark.parametrize("depth", [18, 50])
    def test_transplant_matches_torch(self, depth):
        """Build the torch reference ResNet locally (torchvision architecture,
        random init) and require near-bit parity after transplant."""
        torchvision = pytest.importorskip  # noqa: F841 — torchvision absent; build manually
        tmodel = _torch_resnet(depth, num_classes=10)
        tmodel.eval()
        fm = _import_from(tmodel, depth, num_classes=10, image_size=64)
        x = np.random.default_rng(5).normal(size=(2, 64, 64, 3)).astype(np.float32) * 0.3
        import torch as th

        with th.no_grad():
            want = tmodel(th.from_numpy(np.transpose(x, (0, 3, 1, 2)))).numpy()
        got = np.asarray(fm.apply(x))
        # our convs run bf16 on the MXU; tolerance covers bf16 rounding
        np.testing.assert_allclose(got, want, atol=5e-2, rtol=5e-2)

    def test_embedding_tap(self):
        tmodel = _torch_resnet(18, num_classes=7)
        tmodel.eval()
        fm = _import_from(tmodel, 18, num_classes=7, image_size=32)
        x = np.random.default_rng(6).normal(size=(2, 32, 32, 3)).astype(np.float32)
        emb = fm.apply(x, tap=fm.resolve_output("avgpool"))
        assert emb.shape == (2, 512)

    def test_shape_mismatch_raises(self):
        from mmlspark_tpu.models import from_torch_resnet

        tmodel = _torch_resnet(18, num_classes=7)
        sd = {k: v for k, v in tmodel.state_dict().items()}
        with pytest.raises((ValueError, KeyError)):
            from_torch_resnet(sd, depth=50, num_classes=7)


def _torch_resnet(depth, num_classes):
    """Minimal torchvision-compatible ResNet (same state_dict keys/shapes)."""
    import torch.nn as nn

    class BasicBlock(nn.Module):
        expansion = 1

        def __init__(self, cin, cout, stride=1, down=None):
            super().__init__()
            self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
            self.bn1 = nn.BatchNorm2d(cout)
            self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
            self.bn2 = nn.BatchNorm2d(cout)
            self.downsample = down
            self.relu = nn.ReLU(inplace=True)

        def forward(self, x):
            idn = x if self.downsample is None else self.downsample(x)
            out = self.relu(self.bn1(self.conv1(x)))
            out = self.bn2(self.conv2(out))
            return self.relu(out + idn)

    class Bottleneck(nn.Module):
        expansion = 4

        def __init__(self, cin, mid, stride=1, down=None):
            super().__init__()
            self.conv1 = nn.Conv2d(cin, mid, 1, bias=False)
            self.bn1 = nn.BatchNorm2d(mid)
            self.conv2 = nn.Conv2d(mid, mid, 3, stride, 1, bias=False)
            self.bn2 = nn.BatchNorm2d(mid)
            self.conv3 = nn.Conv2d(mid, mid * 4, 1, bias=False)
            self.bn3 = nn.BatchNorm2d(mid * 4)
            self.downsample = down
            self.relu = nn.ReLU(inplace=True)

        def forward(self, x):
            idn = x if self.downsample is None else self.downsample(x)
            out = self.relu(self.bn1(self.conv1(x)))
            out = self.relu(self.bn2(self.conv2(out)))
            out = self.bn3(self.conv3(out))
            return self.relu(out + idn)

    cfg = {18: (BasicBlock, (2, 2, 2, 2)), 34: (BasicBlock, (3, 4, 6, 3)),
           50: (Bottleneck, (3, 4, 6, 3))}
    block, blocks = cfg[depth]

    class ResNet(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
            self.bn1 = nn.BatchNorm2d(64)
            self.relu = nn.ReLU(inplace=True)
            self.maxpool = nn.MaxPool2d(3, 2, 1)
            cin = 64
            for i, n in enumerate(blocks):
                ch = 64 * 2 ** i
                layers = []
                for j in range(n):
                    stride = 2 if (i > 0 and j == 0) else 1
                    down = None
                    if stride != 1 or cin != ch * block.expansion:
                        down = nn.Sequential(
                            nn.Conv2d(cin, ch * block.expansion, 1, stride, bias=False),
                            nn.BatchNorm2d(ch * block.expansion))
                    layers.append(block(cin, ch, stride, down))
                    cin = ch * block.expansion
                setattr(self, f"layer{i + 1}", nn.Sequential(*layers))
            self.avgpool = nn.AdaptiveAvgPool2d(1)
            self.fc = nn.Linear(cin, num_classes)

        def forward(self, x):
            x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
            for i in range(4):
                x = getattr(self, f"layer{i + 1}")(x)
            x = self.avgpool(x).flatten(1)
            return self.fc(x)

    import torch as th

    th.manual_seed(depth)
    model = ResNet()
    # non-trivial BN stats so eval-mode normalization is actually tested
    for m in model.modules():
        if isinstance(m, nn.BatchNorm2d):
            m.running_mean.normal_(0, 0.2)
            m.running_var.uniform_(0.5, 1.5)
    return model


def _import_from(tmodel, depth, num_classes, image_size):
    from mmlspark_tpu.models import from_torch_resnet

    return from_torch_resnet(tmodel.state_dict(), depth=depth,
                             num_classes=num_classes, image_size=image_size)


class TestIntegration:
    def test_image_featurizer_on_imported_onnx(self, tmp_path):
        """Real transfer-learning path: ONNX backbone -> ImageFeaturizer embeddings
        (reference flow ImageFeaturizer.scala:133-178 with a downloaded model)."""
        import torch.nn as nn

        from mmlspark_tpu.core.dataframe import DataFrame
        from mmlspark_tpu.image.featurizer import ImageFeaturizer

        torch.manual_seed(9)
        backbone = nn.Sequential(
            nn.Conv2d(3, 6, 3, stride=2, padding=1), nn.ReLU(),
            nn.AdaptiveAvgPool2d(1), nn.Flatten(), nn.Linear(6, 4))
        backbone.eval()
        path = _onnx_from_torch_seq(backbone, (3, 10, 10), str(tmp_path / "b.onnx"))
        fm = import_onnx(path)
        assert fm.data_format == "NCHW"

        rng = np.random.default_rng(0)
        imgs = [rng.integers(0, 255, size=(10, 10, 3)).astype(np.uint8)
                for _ in range(6)]
        df = DataFrame.from_dict({"image": np.array(imgs, dtype=object)},
                                 num_partitions=2)
        feat = (ImageFeaturizer(inputCol="image", outputCol="features")
                .set_model(fm).set_cut_output_layers(1))
        out = feat.transform(df).collect()
        vecs = out["features"]
        assert len(vecs) == 6
        assert all(v.shape == (6,) for v in vecs)  # pooled 6-dim embedding (pre-fc)

        # cut 0 = full head
        out0 = (ImageFeaturizer(inputCol="image", outputCol="features")
                .set_model(fm).set_cut_output_layers(0)).transform(df).collect()
        assert all(v.shape == (4,) for v in out0["features"])

    def test_downloader_onnx_payload(self, tmp_path):
        import torch.nn as nn

        from mmlspark_tpu.downloader import ModelDownloader, ModelSchema

        torch.manual_seed(4)
        model = nn.Sequential(nn.Conv2d(3, 4, 3, padding=1), nn.ReLU(),
                              nn.AdaptiveAvgPool2d(1), nn.Flatten(), nn.Linear(4, 2))
        model.eval()
        repo = tmp_path / "repo"
        repo.mkdir()
        payload = repo / "tiny_cnn.onnx"
        _onnx_from_torch_seq(model, (3, 8, 8), str(payload))
        schema = ModelSchema(name="tiny_cnn", uri=str(payload), modelType="onnx")
        (repo / "tiny_cnn.meta").write_text(schema.to_json())

        dl = ModelDownloader(str(tmp_path / "cache"), repo=str(repo))
        local = dl.download_by_name("tiny_cnn")
        fm = ModelDownloader.load_function_model(local)
        x = np.random.default_rng(2).normal(size=(3, 3, 8, 8)).astype(np.float32)
        with torch.no_grad():
            want = model(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(np.asarray(fm.apply(x)), want, atol=1e-3, rtol=1e-3)

    def test_downloader_pth_payload(self, tmp_path):
        from mmlspark_tpu.downloader import ModelDownloader, ModelSchema

        tmodel = _torch_resnet(18, num_classes=5)
        tmodel.eval()
        pth = tmp_path / "r18.pth"
        torch.save(tmodel.state_dict(), str(pth))
        schema = ModelSchema(name="r18", uri=str(pth), modelType="torch-resnet18")
        fm = ModelDownloader.load_function_model(schema)
        assert fm.name == "resnet18"
        x = np.random.default_rng(3).normal(size=(1, 224, 224, 3)).astype(np.float32) * 0.1
        import torch as th

        with th.no_grad():
            want = tmodel(th.from_numpy(np.transpose(x, (0, 3, 1, 2)))).numpy()
        np.testing.assert_allclose(np.asarray(fm.apply(x)), want, atol=5e-2, rtol=5e-2)


class TestNativeRoundTrip:
    def test_export_import_resnet18(self, tmp_path):
        from mmlspark_tpu.models.resnet import resnet

        fm = resnet(18, num_classes=10, image_size=32, seed=3)
        blob = export_onnx(fm.module, fm.params, fm.input_shape,
                           path=str(tmp_path / "r18.onnx"), name="resnet18")
        assert len(blob) > 1000
        fm2 = import_onnx(str(tmp_path / "r18.onnx"), compute_dtype="bfloat16")
        x = np.random.default_rng(0).normal(size=(2, 32, 32, 3)).astype(np.float32)
        want = np.asarray(fm.apply(x))
        # imported graph takes NCHW
        got = np.asarray(fm2.apply(np.transpose(x, (0, 3, 1, 2))))
        np.testing.assert_allclose(got, want, atol=5e-2, rtol=5e-2)

    def test_export_explicit_padding(self, tmp_path):
        """torch-padded models (explicit pad tuples) must export their pads."""
        import jax

        from mmlspark_tpu.models.resnet import build_resnet

        mod = build_resnet(18, num_classes=4, image_size=32, width=8,
                           torch_padding=True)
        params, _ = mod.init(jax.random.PRNGKey(1), (32, 32, 3))
        blob = export_onnx(mod, params, (32, 32, 3))
        fm = import_onnx(blob, compute_dtype="bfloat16")
        x = np.random.default_rng(4).normal(size=(2, 32, 32, 3)).astype(np.float32)
        want = np.asarray(mod.apply(params, x))
        got = np.asarray(fm.apply(np.transpose(x, (0, 3, 1, 2))))
        np.testing.assert_allclose(got, want, atol=5e-2, rtol=5e-2)

    def test_export_mlp(self, tmp_path):
        import jax

        from mmlspark_tpu.models.module import Dense, Sequential, flatten, relu

        mod = Sequential([("d1", Dense(16)), ("act", relu()), ("d2", Dense(4))])
        params, out_shape = mod.init(jax.random.PRNGKey(0), (8,))
        assert out_shape == (4,)
        blob = export_onnx(mod, params, (8,), path=str(tmp_path / "mlp.onnx"))
        fm = import_onnx(blob)
        x = np.random.default_rng(1).normal(size=(5, 8)).astype(np.float32)
        want = np.asarray(mod.apply(params, x))
        got = np.asarray(fm.apply(x))
        np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-2)


class TestTransformerGraphImport:
    """Transformer-family ONNX graphs import and match torch numerics —
    attention is MatMul/Transpose/Mul/Softmax/Add, all runtime ops of
    GraphModule, so sequence models ride the same import path as CNNs."""

    def _attention_onnx(self, Wq, Wk, Wv, Wo, scale, path):
        nodes, inits = [], []

        def init(name, arr):
            inits.append(proto.make_tensor(name,
                                           np.ascontiguousarray(arr)))
            return name

        init("Wq", Wq), init("Wk", Wk), init("Wv", Wv), init("Wo", Wo)
        init("scale", np.asarray(scale, dtype=np.float32))
        for proj, w in (("q", "Wq"), ("k", "Wk"), ("v", "Wv")):
            nodes.append(proto.make_node("MatMul", ["input", w], [proj],
                                         name=f"proj_{proj}"))
        nodes.append(proto.make_node("Transpose", ["k"], ["kT"],
                                     name="kT", perm=[0, 2, 1]))
        nodes.append(proto.make_node("MatMul", ["q", "kT"], ["s_raw"],
                                     name="scores"))
        nodes.append(proto.make_node("Mul", ["s_raw", "scale"], ["s"],
                                     name="scale_scores"))
        nodes.append(proto.make_node("Softmax", ["s"], ["p"],
                                     name="attn_softmax", axis=-1))
        nodes.append(proto.make_node("MatMul", ["p", "v"], ["ctx"],
                                     name="context"))
        nodes.append(proto.make_node("MatMul", ["ctx", "Wo"], ["out"],
                                     name="out_proj"))
        blob = proto.make_model(
            nodes, inits,
            [proto.make_value_info("input", [None, 6, 8])],
            [proto.make_value_info("out", [None, 6, 8])])
        with open(path, "wb") as fh:
            fh.write(blob)
        return path

    def test_self_attention_matches_torch(self, tmp_path):
        import torch

        rng = np.random.default_rng(0)
        D = 8
        Wq, Wk, Wv, Wo = ((rng.normal(size=(D, D)) / np.sqrt(D))
                          .astype(np.float32) for _ in range(4))
        scale = 1.0 / np.sqrt(D)
        path = self._attention_onnx(Wq, Wk, Wv, Wo, scale,
                                    str(tmp_path / "attn.onnx"))
        fm = import_onnx(path, compute_dtype="float32")

        x = rng.normal(size=(3, 6, D)).astype(np.float32)
        with torch.no_grad():
            tx = torch.from_numpy(x)
            q, k, v = tx @ torch.from_numpy(Wq), tx @ torch.from_numpy(Wk), \
                tx @ torch.from_numpy(Wv)
            p = torch.softmax(q @ k.transpose(1, 2) * scale, dim=-1)
            want = ((p @ v) @ torch.from_numpy(Wo)).numpy()
        got = np.asarray(fm.apply(x))
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)

    def test_layernorm_gelu_mlp(self, tmp_path):
        import torch.nn as nn

        torch.manual_seed(5)
        model = nn.Sequential(
            nn.Flatten(), nn.Linear(12, 16), nn.LayerNorm(16), nn.GELU(),
            nn.Linear(16, 8), nn.LayerNorm(8), nn.ELU(), nn.Linear(8, 3))
        model.eval()
        path = _onnx_from_torch_seq(model, (12,), str(tmp_path / "ln.onnx"))
        fm = import_onnx(path)
        x = np.random.default_rng(0).normal(size=(5, 12)).astype(np.float32)
        with torch.no_grad():
            want = model(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(np.asarray(fm.apply(x)), want,
                                   atol=1e-4, rtol=1e-3)

    def test_conv_transpose_instance_norm(self, tmp_path):
        import torch.nn as nn

        torch.manual_seed(6)
        model = nn.Sequential(
            nn.Conv2d(3, 8, 3, stride=2, padding=1),
            nn.InstanceNorm2d(8, affine=True), nn.ReLU(),
            nn.ConvTranspose2d(8, 4, 3, stride=2, padding=1,
                               output_padding=1),
            nn.Softplus(),
            nn.ConvTranspose2d(4, 4, 4, stride=2, padding=1, groups=2))
        model.eval()
        with torch.no_grad():  # non-trivial affine stats
            model[1].weight.normal_(1.0, 0.2)
            model[1].bias.normal_(0, 0.2)
        path = _onnx_from_torch_seq(model, (3, 13, 13),
                                    str(tmp_path / "ct.onnx"))
        fm = import_onnx(path)
        x = np.random.default_rng(1).normal(size=(2, 3, 13, 13)) \
            .astype(np.float32)
        with torch.no_grad():
            want = model(torch.from_numpy(x)).numpy()
        got = np.asarray(fm.apply(x))
        np.testing.assert_allclose(got.reshape(want.shape), want,
                                   atol=1e-4, rtol=1e-3)

    def test_data_ops_roundtrip(self, tmp_path):
        """Reduce/Arg/Expand/Where/compare ops vs numpy reference."""
        rng = np.random.default_rng(2)
        x = rng.normal(size=(3, 4, 5)).astype(np.float32)
        nodes = [
            proto.make_node("ReduceSum", ["input", "axes1"], ["rsum"],
                            name="rsum", keepdims=1),
            proto.make_node("ReduceMax", ["input"], ["rmax"], name="rmax",
                            axes=[2], keepdims=0),
            proto.make_node("ArgMax", ["rmax"], ["amax"], name="amax",
                            axis=1, keepdims=0),
            proto.make_node("GreaterOrEqual", ["input", "rsum"], ["ge"],
                            name="ge"),
            proto.make_node("Where", ["ge", "input", "zero"], ["w"],
                            name="w"),
            proto.make_node("Expand", ["w", "eshape"], ["out"], name="out"),
        ]
        inits = [proto.make_tensor("axes1", np.asarray([1], dtype=np.int64)),
                 proto.make_tensor("zero", np.asarray(0.0, dtype=np.float32)),
                 proto.make_tensor("eshape",
                                   np.asarray([2, 3, 4, 5], dtype=np.int64))]
        blob = proto.make_model(
            nodes, inits, [proto.make_value_info("input", [None, 4, 5])],
            [proto.make_value_info("out", [2, 3, 4, 5])])
        p = tmp_path / "ops.onnx"
        p.write_bytes(blob)
        fm = import_onnx(str(p), input_shape=(4, 5))
        got = np.asarray(fm.apply(x))
        rsum = x.sum(axis=1, keepdims=True)
        want = np.broadcast_to(np.where(x >= rsum, x, 0.0), (2, 3, 4, 5))
        np.testing.assert_allclose(got, want, atol=1e-5)
        # and the intermediate int outputs are tappable
        amax = np.asarray(fm.apply(x, tap="amax"))
        np.testing.assert_array_equal(amax, x.max(axis=2).argmax(axis=1))

    def _pack_rnn(self, op, torch_rnn, in_dim, hidden, path, extra_attrs=None):
        """Hand-pack a torch LSTM/GRU into the corresponding ONNX node.

        torch gate orders: LSTM (i,f,g,o) -> ONNX (i,o,f,c);
        GRU (r,z,n) -> ONNX (z,r,h) with linear_before_reset=1.
        """
        ngates = 4 if op == "LSTM" else 3

        def reorder(m):
            gates = np.split(m, ngates, axis=0)
            if op == "LSTM":
                i, f, g, o = gates
                return np.concatenate([i, o, f, g], axis=0)
            r, z, nn_ = gates
            return np.concatenate([z, r, nn_], axis=0)

        dirs = 2 if torch_rnn.bidirectional else 1
        W, R, B = [], [], []
        for d in range(dirs):
            sfx = f"_l0{'_reverse' if d else ''}"
            W.append(reorder(getattr(torch_rnn, "weight_ih" + sfx)
                             .detach().numpy()))
            R.append(reorder(getattr(torch_rnn, "weight_hh" + sfx)
                             .detach().numpy()))
            B.append(np.concatenate(
                [reorder(getattr(torch_rnn, "bias_ih" + sfx).detach().numpy()),
                 reorder(getattr(torch_rnn, "bias_hh" + sfx)
                         .detach().numpy())]))
        attrs = dict(hidden_size=hidden,
                     direction="bidirectional" if dirs == 2 else "forward")
        if extra_attrs:
            attrs.update(extra_attrs)
        nodes = [proto.make_node(op, ["input", "W", "R", "B"], ["Y"],
                                 name="rnn", **attrs)]
        inits = [proto.make_tensor("W", np.stack(W).astype(np.float32)),
                 proto.make_tensor("R", np.stack(R).astype(np.float32)),
                 proto.make_tensor("B", np.stack(B).astype(np.float32))]
        blob = proto.make_model(
            nodes, inits, [proto.make_value_info("input", [None, 2, in_dim])],
            [proto.make_value_info("Y", [None, dirs, 2, hidden])])
        path.write_bytes(blob)
        return str(path)

    def test_bilstm_matches_torch(self, tmp_path):
        """A torch BiLSTM imported through the ONNX LSTM op — the BiLSTM
        entity-extraction notebook's import path (reference runs it through
        CNTKModel, DeepLearning - BiLSTM notebook)."""
        import torch.nn as nn

        torch.manual_seed(7)
        T, B, I, H = 6, 2, 5, 7
        rnn = nn.LSTM(I, H, bidirectional=True)
        rnn.eval()
        path = self._pack_rnn("LSTM", rnn, I, H, tmp_path / "bilstm.onnx")
        # torch input [T, B, I] == ONNX layout 0; per-example shape (B, I)
        fm = import_onnx(path, input_shape=(B, I))
        x = np.random.default_rng(3).normal(size=(T, B, I)).astype(np.float32)
        with torch.no_grad():
            want, _ = rnn(torch.from_numpy(x))   # [T, B, 2H]
        got = np.asarray(fm.apply(x))            # [T, 2, B, H]
        np.testing.assert_allclose(got[:, 0], want[:, :, :H].numpy(),
                                   atol=1e-4, rtol=1e-3)
        np.testing.assert_allclose(got[:, 1], want[:, :, H:].numpy(),
                                   atol=1e-4, rtol=1e-3)

    def test_gru_matches_torch(self, tmp_path):
        import torch.nn as nn

        torch.manual_seed(8)
        T, B, I, H = 5, 3, 4, 6
        rnn = nn.GRU(I, H)
        rnn.eval()
        path = self._pack_rnn("GRU", rnn, I, H, tmp_path / "gru.onnx",
                              extra_attrs={"linear_before_reset": 1})
        fm = import_onnx(path, input_shape=(B, I))
        x = np.random.default_rng(4).normal(size=(T, B, I)).astype(np.float32)
        with torch.no_grad():
            want, _ = rnn(torch.from_numpy(x))   # [T, B, H]
        got = np.asarray(fm.apply(x))            # [T, 1, B, H]
        np.testing.assert_allclose(got[:, 0], want.numpy(),
                                   atol=1e-4, rtol=1e-3)

    def test_attention_tap_addressing(self, tmp_path):
        """Named nodes in the imported graph are tappable (OUTPUT_i /
        layer addressing works for sequence graphs too)."""
        rng = np.random.default_rng(1)
        D = 8
        ws = [(rng.normal(size=(D, D)) / np.sqrt(D)).astype(np.float32)
              for _ in range(4)]
        path = self._attention_onnx(*ws, 1.0 / np.sqrt(D),
                                    str(tmp_path / "attn2.onnx"))
        fm = import_onnx(path, compute_dtype="float32",
                         layer_names=["out_proj", "attn_softmax"])
        x = rng.normal(size=(2, 6, D)).astype(np.float32)
        p = np.asarray(fm.apply(x, tap="attn_softmax"))
        assert p.shape == (2, 6, 6)
        np.testing.assert_allclose(p.sum(-1), 1.0, atol=1e-5)  # rows sum to 1
