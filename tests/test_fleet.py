"""Fleet control plane suite (serving/fleet, docs/fleet.md).

Covers the three parts end to end: the persistent compile cache's
round-trip / AOT warm / corruption degradation, the capacity planner's
SLO-meeting sweep and uncalibrated hold, the autoscale controller's
quorum + journal + one-step rollback, and the serving wiring
(``/_mmlspark/capacity``, the stats section, front aggregation, and
``fleet=False`` parity). The chaos-lane fault-injection cases live in
tests/test_faults.py (TestCompileCacheChaos).
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from mmlspark_tpu.core.device_stage import CompileCache  # noqa: E402
from mmlspark_tpu.serving.fleet import (  # noqa: E402
    CapacityPlanner,
    FleetController,
    FleetSpec,
    PersistentCompileCache,
    PlannerConfig,
    content_key,
    forecast_rps,
    make_fleet,
    plan_capacity,
)
from mmlspark_tpu.serving.fleet import cache as fleet_cache  # noqa: E402


def _compiled(mult=2.0, n=4):
    """A tiny AOT-compiled executable (what fusion's builder returns)."""
    x = jnp.ones((n,), jnp.float32)
    return jax.jit(lambda v: v * mult).lower(x).compile()


KEY = ("seg0", (("col", (4,), "float32"),))
X = jnp.arange(4, dtype=jnp.float32)


class TestPersistentCacheRoundTrip:
    def test_cold_store_then_fresh_process_load(self, tmp_path):
        """Process A compiles + stores; 'process B' (a fresh in-process
        cache over the same directory) answers with ZERO compiles and a
        bitwise-identical result."""
        t1 = PersistentCompileCache(str(tmp_path))
        c1 = CompileCache()
        c1.attach_persistent(t1)
        fn1 = c1.get(KEY, _compiled, label="seg0", shape="b4")
        ref = np.asarray(fn1(X))
        s1 = c1.stats()
        assert s1["misses"] == 1 and s1["compile_time_s"] > 0
        assert t1.stats()["stores"] == 1
        assert t1.entry_count() == 1

        t2 = PersistentCompileCache(str(tmp_path))
        c2 = CompileCache()
        c2.attach_persistent(t2)
        built = []

        def builder():
            built.append(1)
            return _compiled()

        fn2 = c2.get(KEY, builder, label="seg0", shape="b4")
        assert not built, "tier hit must not invoke the builder"
        s2 = c2.stats()
        # counter-verified zero compiles: the memory tier saw neither a
        # miss nor a compile; the persistent tier accounts the hit
        assert s2["misses"] == 0 and s2["compile_time_s"] == 0.0
        assert t2.stats()["hits"] == 1
        assert np.array_equal(np.asarray(fn2(X)), ref)

    def test_warm_preloads_for_zero_compile_first_request(self, tmp_path):
        t1 = PersistentCompileCache(str(tmp_path))
        c1 = CompileCache()
        c1.attach_persistent(t1)
        ref = np.asarray(c1.get(KEY, _compiled, label="seg0",
                                shape="b4")(X))

        c2 = CompileCache()
        t2 = PersistentCompileCache(str(tmp_path))
        c2.attach_persistent(t2)
        out = t2.warm(c2)
        assert out["warmed"] == 1 and out["errors"] == 0
        fn = c2.get(KEY, lambda: pytest.fail("must be resident"),
                    label="seg0", shape="b4")
        s = c2.stats()
        assert s["hits"] == 1 and s["misses"] == 0
        assert s["compile_time_s"] == 0.0
        assert np.array_equal(np.asarray(fn(X)), ref)

    def test_costs_only_fallback_warms_model_and_knobs(self, tmp_path):
        t1 = PersistentCompileCache(
            str(tmp_path), knobs_provider=lambda: {"inflight": 3})
        # a plain lambda is not an AOT executable -> serialize fails ->
        # the entry persists kind="costs" with the harvested record
        assert t1.store(KEY, lambda v: v, cost={"compute_ms": 1.5},
                        label="seg0", shape="b4")
        assert t1.stats()["costs_only"] == 1

        t2 = PersistentCompileCache(str(tmp_path))
        assert t2.load(KEY, label="seg0", shape="b4") is None
        assert t2.harvested_costs() == {
            "seg0": {"b4": {"compute_ms": 1.5}}}
        assert t2.loaded_knobs == {"inflight": 3}
        # warm over cost-only entries touches the side channels only
        c = CompileCache()
        out = t2.warm(c)
        assert out["costs_only"] == 1 and out["warmed"] == 0
        assert c.stats()["entries"] == 0

    def test_store_skips_existing_entry(self, tmp_path):
        t = PersistentCompileCache(str(tmp_path))
        fn = _compiled()
        assert t.store(KEY, fn, label="seg0", shape="b4")
        assert not t.store(KEY, fn, label="seg0", shape="b4")
        assert t.stats()["store_skips"] == 1

    def test_readonly_tier_never_writes(self, tmp_path):
        t = PersistentCompileCache(str(tmp_path), write=False)
        assert not t.store(KEY, _compiled(), label="seg0", shape="b4")
        assert t.entry_count() == 0

    def test_unwritable_path_degrades_to_readonly(self, tmp_path):
        blocker = tmp_path / "file"
        blocker.write_text("x")
        t = PersistentCompileCache(str(blocker / "sub"))
        assert t.write is False  # mkdir failed, constructor survived

    def test_content_key_binds_environment(self):
        fp = fleet_cache.env_fingerprint()
        other = dict(fp, jax="0.0.0-other")
        assert content_key(KEY, fp) != content_key(KEY, other)
        assert content_key(KEY, fp) == content_key(KEY, dict(fp))


class TestPersistentCacheCorruption:
    """Truncated / corrupted / foreign-version entries degrade to an
    accounted recompile — counters move, nothing raises."""

    def _entry_path(self, tmp_path):
        t = PersistentCompileCache(str(tmp_path))
        c = CompileCache()
        c.attach_persistent(t)
        c.get(KEY, _compiled, label="seg0", shape="b4")
        (name,) = [n for n in os.listdir(tmp_path)
                   if n.endswith(fleet_cache.SUFFIX)]
        return os.path.join(str(tmp_path), name)

    def _assert_degrades(self, tmp_path):
        t = PersistentCompileCache(str(tmp_path))
        assert t.load(KEY, label="seg0", shape="b4") is None
        assert t.stats()["load_errors"] == 1
        # warm over the same broken entry: counted, start still succeeds
        c = CompileCache()
        out = t.warm(c)
        assert out["errors"] == 1
        # and the serving path recompiles through the in-process cache
        c2 = CompileCache()
        c2.attach_persistent(PersistentCompileCache(str(tmp_path),
                                                    write=False))
        fn = c2.get(KEY, _compiled, label="seg0", shape="b4")
        assert np.array_equal(np.asarray(fn(X)), np.asarray(X) * 2.0)
        assert c2.stats()["misses"] == 1  # honest accounting: it compiled

    def test_truncated_entry(self, tmp_path):
        path = self._entry_path(tmp_path)
        blob = open(path, "rb").read()
        with open(path, "wb") as fh:
            fh.write(blob[: len(blob) - 7])
        self._assert_degrades(tmp_path)

    def test_bad_magic(self, tmp_path):
        path = self._entry_path(tmp_path)
        blob = open(path, "rb").read()
        with open(path, "wb") as fh:
            fh.write(b"NOTMML" + blob[6:])
        self._assert_degrades(tmp_path)

    def test_garbage_payload(self, tmp_path):
        path = self._entry_path(tmp_path)
        blob = open(path, "rb").read()
        with open(path, "wb") as fh:
            fh.write(blob[:200] + os.urandom(max(0, len(blob) - 200)))
        self._assert_degrades(tmp_path)

    def test_foreign_version_entry_rejected(self, tmp_path):
        """An entry written by a different jax/backend never loads: the
        digest differs (never looked up) AND a hand-copied file fails the
        header fingerprint check."""
        foreign_fp = dict(fleet_cache.env_fingerprint(), jax="9.9.9")
        t = PersistentCompileCache(str(tmp_path))
        header = {"kind": "exec", "env": foreign_fp,
                  "key_repr": repr(KEY), "label": "seg0", "shape": "b4",
                  "cost": None, "knobs": None,
                  "payload_sha256": fleet_cache.hashlib.sha256(
                      b"zz").hexdigest()}
        # drop it under the LOCAL digest — simulating a hand-copied file
        t._write_entry(t._file_for(content_key(KEY, t._fp)), header, b"zz")
        assert t.load(KEY, label="seg0", shape="b4") is None
        assert t.stats()["load_errors"] == 1


class TestForecast:
    def test_empty_is_zero(self):
        f = forecast_rps([])
        assert f["forecast_rps"] == 0.0 and f["seconds"] == 0

    def test_constant_rate_converges(self):
        now = 10_000
        buckets = [(now - 40 + i, 50, 0) for i in range(40)]
        f = forecast_rps(buckets, now=now)
        assert abs(f["level_rps"] - 50.0) < 1.0
        assert abs(f["forecast_rps"] - 50.0) < 5.0

    def test_rising_trend_projects_up(self):
        now = 10_000
        buckets = [(now - 30 + i, 10 + 4 * i, 0) for i in range(30)]
        f = forecast_rps(buckets, now=now)
        assert f["trend_rps_s"] > 0
        assert f["forecast_rps"] > f["level_rps"]

    def test_idle_gap_pulls_forecast_down(self):
        now = 10_000
        busy = [(now - 60 + i, 100, 0) for i in range(30)]
        # the 30 most recent seconds have NO buckets -> zero traffic
        f = forecast_rps(busy, now=now)
        assert f["level_rps"] < 30.0

    def test_current_partial_second_excluded(self):
        now = 10_000
        buckets = [(now - 2, 10, 0), (now - 1, 10, 0), (now, 9_999, 0)]
        f = forecast_rps(buckets, now=now)
        assert f["level_rps"] < 20.0

    def test_slo_tracker_bucket_form(self):
        from mmlspark_tpu.obs.perf import SLOTracker

        t = [100.0]
        trk = SLOTracker(clock=lambda: t[0])
        for _ in range(30):
            trk.record(0.001)
            t[0] += 1.0
        snap = trk.arrival_buckets()
        assert snap["now"] == t[0]
        f = forecast_rps(snap["buckets"], now=snap["now"])
        assert abs(f["level_rps"] - 1.0) < 0.5


def _predict_ms(bucket):
    """Synthetic calibrated cost model: 4ms fixed + 0.05ms/row."""
    return 4.0 + 0.05 * bucket


class TestPlanner:
    def test_sweep_meets_slo(self):
        """Across a simulated arrival sweep, every feasible plan's own
        numbers satisfy the objective when recomputed independently."""
        cfg = PlannerConfig(objective_ms=100.0, max_replicas=256)
        for demand in (0, 5, 50, 200, 1_000, 5_000, 20_000):
            p = plan_capacity(demand, _predict_ms, cfg)
            assert p.meets_slo is True, (demand, p)
            # independent re-check of the emitted config
            service = _predict_ms(p.bucket)
            mu = p.bucket * 1000.0 / service
            rho = (demand * cfg.headroom) / (p.replicas * mu) \
                if demand else 0.0
            assert rho <= cfg.utilization_cap + 1e-9
            wait = cfg.window_alpha * service
            lat = wait + service * (1.0 + rho / (1.0 - rho))
            assert lat <= cfg.objective_ms + 1e-6
            assert p.capacity_rps >= demand * cfg.headroom or demand == 0

    def test_more_demand_never_fewer_replicas(self):
        cfg = PlannerConfig(objective_ms=100.0, max_replicas=256)
        last = 0
        for demand in (10, 100, 1_000, 10_000, 50_000):
            p = plan_capacity(demand, _predict_ms, cfg)
            assert p.replicas >= last
            last = p.replicas

    def test_saturation_reports_infeasible(self):
        cfg = PlannerConfig(objective_ms=100.0, max_replicas=2)
        p = plan_capacity(1_000_000, _predict_ms, cfg)
        assert p.replicas == 2
        assert p.meets_slo is False

    def test_uncalibrated_holds_steady(self):
        p = plan_capacity(500.0, lambda b: None, live_replicas=7)
        assert p.meets_slo is None
        assert p.replicas == 7
        assert p.reason == "uncalibrated"
        # and a raising model reads the same as an uncalibrated one
        def boom(b):
            raise RuntimeError("no data")
        assert plan_capacity(500.0, boom).meets_slo is None

    def test_mega_k_engages_on_dispatch_rate(self):
        cfg = PlannerConfig(objective_ms=100.0, max_replicas=4,
                            bucket_candidates=(8,),
                            dispatch_floor_hz=50.0)
        lazy = plan_capacity(10.0, _predict_ms, cfg)
        assert lazy.mega_k == 1
        busy = plan_capacity(3_000.0, _predict_ms, cfg)
        assert busy.mega_k > 1

    def test_inflight_deepens_with_utilization(self):
        cfg = PlannerConfig(objective_ms=100.0, max_replicas=256)
        assert plan_capacity(1.0, _predict_ms, cfg).inflight == 1
        hot = plan_capacity(20_000.0, _predict_ms, cfg)
        assert hot.inflight >= 2

    def test_journal_and_summary(self):
        pl = CapacityPlanner(_predict_ms)
        pl.plan(100.0)
        pl.plan(200.0, live_replicas=3)
        assert pl.plans_total == 2
        j = pl.journal()
        assert len(j) == 2 and j[-1]["demand_rps"] == 200.0
        s = pl.summary()
        assert s["plans_total"] == 2
        assert s["latest"]["plan"]["reason"] == "planned"

    def test_config_validation(self):
        with pytest.raises(ValueError):
            PlannerConfig(utilization_cap=1.5)
        with pytest.raises(ValueError):
            PlannerConfig(headroom=0.5)
        with pytest.raises(ValueError):
            PlannerConfig(objective_ms=0)


class _FakeBrownout:
    def __init__(self):
        self.step = 0


def _controller(demand_rps=200_000.0, live=None, spec=None, brownout=None):
    """A controller over a fake clock with scripted hooks; returns
    (controller, clock list, applied log, live dict)."""
    clock = [1_000.0]
    live = live if live is not None else {
        "replicas": 1, "inflight": 1, "mega_k": 1}
    applied = []
    now_s = [50_000]

    def buckets():
        # steady synthetic arrivals at demand_rps for the past 60s
        return {"now": now_s[0],
                "buckets": [(now_s[0] - 60 + i, demand_rps, 0)
                            for i in range(60)]}

    hooks = {
        "live_config": lambda: dict(live),
        "set_inflight": lambda n: applied.append(("inflight", n)),
        "set_mega_k": lambda k: applied.append(("mega_k", k)),
        "arrival_buckets": buckets,
    }
    ctl = FleetController(
        CapacityPlanner(_predict_ms,
                        PlannerConfig(objective_ms=100.0,
                                      max_replicas=256)),
        spec=spec or FleetSpec(tick_s=0.0, plan_every_s=1.0,
                               consecutive_out=2, consecutive_in=3,
                               hold_s=0.0, watch_batches=5,
                               regress_pct=0.15, cooldown_s=30.0),
        brownout=brownout, hooks=hooks,
        clock=lambda: clock[0])
    return ctl, clock, applied, live


class TestController:
    def test_scale_out_needs_quorum_then_applies(self):
        ctl, clock, applied, _live = _controller()
        assert ctl.tick(0.01) is None  # plan 1: agreement only
        assert ctl._recommended is not None
        assert not applied
        clock[0] += 1.1
        assert ctl.tick(0.01) == "scale_out"  # plan 2: quorum reached
        assert any(k == "inflight" for k, _v in applied)
        assert ctl.decisions["scale_out"] == 1
        assert ctl.state == "scale_out"
        actions = [e["action"] for e in ctl.journal]
        assert "apply" in actions

    def test_regression_rolls_back_and_cools_down(self):
        ctl, clock, applied, _live = _controller()
        ctl.tick(0.01)
        clock[0] += 1.1
        assert ctl.tick(0.01) == "scale_out"
        applied.clear()
        # the watch window sees a >15% e2e regression
        clock[0] += 1.1
        for _ in range(6):
            out = ctl.tick(0.05)
            if out == "rollback":
                break
        assert ctl.decisions["rollback"] == 1
        assert ctl.state == "cooldown"
        # the snapshotted pre-apply knobs were restored through the hooks
        assert ("inflight", 1) in applied
        assert [e for e in ctl.journal if e["action"] == "rollback"]
        # cooldown vetoes further planning until it expires
        clock[0] += 1.1
        assert ctl.tick(0.01) is None
        clock[0] += 60.0
        assert ctl.tick(0.01) is None  # agreement restarts from zero

    def test_clean_watch_returns_to_steady(self):
        ctl, clock, _applied, _live = _controller()
        ctl.tick(0.01)
        clock[0] += 1.1
        ctl.tick(0.01)
        clock[0] += 1.1
        for _ in range(6):
            ctl.tick(0.0101)  # same latency: no regression
        assert ctl.state == "steady"
        assert ctl.decisions["rollback"] == 0
        assert [e for e in ctl.journal if e["action"] == "watch_clear"]

    def test_brownout_freezes_scaling(self):
        brown = _FakeBrownout()
        brown.step = 1
        ctl, clock, applied, _live = _controller(brownout=brown)
        for _ in range(4):
            ctl.tick(0.01)
            clock[0] += 1.1
        assert ctl.state == "degraded"
        assert ctl.decisions["held_degraded"] >= 1
        assert not applied
        # brownout clears -> planning resumes and can apply
        brown.step = 0
        ctl.tick(0.01)
        clock[0] += 1.1
        ctl.tick(0.01)
        clock[0] += 1.1
        ctl.tick(0.01)
        assert applied

    def test_uncalibrated_never_applies(self):
        ctl, clock, applied, _live = _controller()
        ctl.planner._predict_ms = lambda b: None
        for _ in range(5):
            ctl.tick(0.01)
            clock[0] += 1.1
        assert not applied
        assert ctl.summary()["recommended"]["reason"] == "uncalibrated"

    def test_manual_rollback_without_apply_is_false(self):
        ctl, _clock, _applied, _live = _controller()
        assert ctl.rollback() is False

    def test_summary_shape(self):
        ctl, _clock, _applied, _live = _controller()
        ctl.tick(0.01)
        s = ctl.summary()
        assert set(s) >= {"state", "forecast", "recommended", "live",
                          "decisions", "spec", "planner", "journal"}
        json.dumps(s)  # the /_mmlspark/capacity payload must serialize

    def test_make_fleet_coercions(self):
        assert make_fleet(None, predict_ms=_predict_ms) is None
        assert make_fleet(False, predict_ms=_predict_ms) is None
        ctl = make_fleet(True, predict_ms=_predict_ms)
        assert isinstance(ctl, FleetController)
        ctl2 = make_fleet({"plan_every_s": 2.5, "cache_path": "/x",
                           "cache_write": False,
                           "planner": {"objective_ms": 50.0}},
                          predict_ms=_predict_ms)
        assert ctl2.spec.plan_every_s == 2.5
        assert ctl2.planner.cfg.objective_ms == 50.0
        assert make_fleet(ctl, predict_ms=_predict_ms) is ctl
        with pytest.raises(ValueError):
            make_fleet(3, predict_ms=_predict_ms)


def _echo_transform(df):
    return df.with_column("reply", lambda p: p["value"])


def _serve_requests(server, bodies):
    replies = []
    with server:
        for b in bodies:
            req = urllib.request.Request(server.address, data=b,
                                         method="POST")
            with urllib.request.urlopen(req, timeout=15) as resp:
                replies.append(resp.read())
    return replies


class TestServingIntegration:
    def test_capacity_endpoint_and_stats_section(self):
        from mmlspark_tpu.serving.server import ServingServer

        srv = ServingServer(_echo_transform, port=0, fleet=True,
                            max_wait_ms=1.0)
        with srv:
            base = f"http://127.0.0.1:{srv.port}"
            req = urllib.request.Request(srv.address, data=b'{"x":1}',
                                         method="POST")
            urllib.request.urlopen(req, timeout=15).read()
            cap = json.loads(urllib.request.urlopen(
                base + "/_mmlspark/capacity", timeout=15).read())
            stats = json.loads(urllib.request.urlopen(
                base + "/_mmlspark/stats", timeout=15).read())
            metrics = urllib.request.urlopen(
                base + "/_mmlspark/metrics", timeout=15).read().decode()
        assert cap["state"] in ("steady", "scale_out", "scale_in")
        assert cap["recommended"] is not None
        assert "fleet" in stats
        assert "mmlspark_capacity_recommended_replicas" in metrics
        assert "mmlspark_capacity_decisions_total" in metrics

    def test_capacity_404_when_disabled(self):
        from mmlspark_tpu.serving.server import ServingServer

        srv = ServingServer(_echo_transform, port=0, max_wait_ms=1.0)
        with srv:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/_mmlspark/capacity",
                    timeout=15)
            assert e.value.code == 404

    def test_fleet_false_is_bitwise_identical(self):
        """fleet=False (the default) serves byte-identical replies and an
        identical stats surface to a server built without the knob."""
        from mmlspark_tpu.serving.server import ServingServer

        bodies = [json.dumps({"i": i}).encode() for i in range(4)]
        off = ServingServer(_echo_transform, port=0, max_wait_ms=1.0,
                            fleet=False)
        plain = ServingServer(_echo_transform, port=0, max_wait_ms=1.0)
        r_off = _serve_requests(off, bodies)
        r_plain = _serve_requests(plain, bodies)
        assert r_off == r_plain
        assert off._fleet is None

    def test_front_aggregates_worker_capacity(self):
        from mmlspark_tpu.serving.routing import (RoutingFront,
                                                  register_worker)
        from mmlspark_tpu.serving.server import ServingServer

        w1 = ServingServer(_echo_transform, port=0, fleet=True,
                           max_wait_ms=1.0).start()
        w2 = ServingServer(_echo_transform, port=0,
                           max_wait_ms=1.0).start()
        front = RoutingFront(port=0).start()
        try:
            for w in (w1, w2):
                register_worker(f"http://127.0.0.1:{front.port}",
                                w.address)
            cap = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{front.port}/_mmlspark/capacity",
                timeout=15).read())
        finally:
            front.stop()
            w1.stop()
            w2.stop()
        assert cap["workers"] == 2
        assert cap["responding"] == 1  # only the fleet-enabled worker
        per = list(cap["per_worker"].values())
        assert any("state" in v for v in per)
        assert any(v.get("disabled") for v in per)


class TestCompileCacheTierGlue:
    """CompileCache <-> persistent tier protocol surface (the glue the
    fused serving path rides via attach_persistent_cache)."""

    def test_attach_and_warm_round_trip(self, tmp_path):
        tier = PersistentCompileCache(str(tmp_path))
        c = CompileCache()
        c.attach_persistent(tier)
        assert c.persistent is tier
        c.get(KEY, _compiled, label="seg0", shape="b4")
        assert c.stats()["persistent"]["stores"] == 1

        c2 = CompileCache()
        t2 = PersistentCompileCache(str(tmp_path))
        c2.attach_persistent(t2)
        assert t2.warm(c2)["warmed"] == 1
