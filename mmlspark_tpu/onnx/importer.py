"""ONNX → GraphModule importer: pretrained-model ingestion for the TPU framework.

Reference parity: ModelDownloader fetches a serialized pretrained CNN and CNTKModel
loads it natively with name-addressable nodes (downloader/ModelDownloader.scala:27-120,
CNTK/SerializableFunction.scala:23-143). Here any ONNX checkpoint (the lingua franca
torch/tf/sklearn all export to) becomes a FunctionModel whose GraphModule jits on TPU.

Import pipeline:
  1. parse ModelProto (onnx/proto.py — no external deps),
  2. constant-fold every node whose inputs are all initializers (this collapses the
     Shape→Gather→Unsqueeze→Concat→Reshape idioms exporters emit for dynamic batch),
  3. topologically sort the remaining compute nodes, name anonymous ones,
  4. wrap as GraphModule + FunctionModel with auto-derived layer_names so
     ImageFeaturizer.cutOutputLayers works out of the box.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..models.graph_module import GraphModule, GraphNode
from ..models.module import FunctionModel
from . import proto

# ops we can evaluate on host numpy during constant folding
_FOLDABLE = {
    "Shape", "Gather", "Unsqueeze", "Squeeze", "Concat", "Cast", "Slice",
    "Add", "Sub", "Mul", "Div", "Constant", "Identity", "Reshape", "Transpose",
    "ConstantOfShape", "Range", "Equal", "Where",
}


def _fold_node(node: proto.Node, inputs: List[Optional[np.ndarray]]):
    op = node.op_type
    a = inputs
    if op == "Constant":
        t = node.attrs.get("value")
        if isinstance(t, proto.Tensor):
            return t.to_numpy()
        for key, dtype in (("value_float", np.float32), ("value_int", np.int64)):
            if key in node.attrs:
                return np.asarray(node.attrs[key], dtype=dtype)
        if "value_floats" in node.attrs:
            return np.asarray(node.attrs["value_floats"], dtype=np.float32)
        if "value_ints" in node.attrs:
            return np.asarray(node.attrs["value_ints"], dtype=np.int64)
        raise ValueError(f"Constant node {node.name!r} with no value")
    if op == "Identity":
        return a[0]
    if op == "Shape":
        return np.asarray(a[0].shape, dtype=np.int64)
    if op == "Gather":
        return np.take(a[0], np.asarray(a[1]), axis=int(node.attrs.get("axis", 0)))
    if op == "Unsqueeze":
        axes = node.attrs.get("axes") or np.asarray(a[1]).tolist()
        out = a[0]
        for ax in sorted(int(x) for x in axes):
            out = np.expand_dims(out, ax)
        return out
    if op == "Squeeze":
        axes = node.attrs.get("axes") or (np.asarray(a[1]).tolist() if len(a) > 1 else None)
        return np.squeeze(a[0], axis=tuple(int(x) for x in axes) if axes else None)
    if op == "Concat":
        return np.concatenate(a, axis=int(node.attrs.get("axis", 0)))
    if op == "Cast":
        to = {1: np.float32, 6: np.int32, 7: np.int64, 9: np.bool_,
              10: np.float16, 11: np.float64}[int(node.attrs.get("to", 1))]
        return a[0].astype(to)
    if op == "Reshape":
        shape = [int(s) for s in np.asarray(a[1]).tolist()]
        shape = [a[0].shape[i] if s == 0 else s for i, s in enumerate(shape)]
        return a[0].reshape(shape)
    if op == "Transpose":
        return np.transpose(a[0], axes=node.attrs.get("perm"))
    if op == "Slice":
        starts = np.asarray(a[1]).tolist()
        ends = np.asarray(a[2]).tolist()
        axes = np.asarray(a[3]).tolist() if len(a) > 3 and a[3] is not None \
            else list(range(len(starts)))
        steps = np.asarray(a[4]).tolist() if len(a) > 4 and a[4] is not None \
            else [1] * len(starts)
        idx: List = [slice(None)] * a[0].ndim
        for s, e, ax, st in zip(starts, ends, axes, steps):
            idx[int(ax)] = slice(int(s), int(e), int(st))
        return a[0][tuple(idx)]
    if op == "ConstantOfShape":
        t = node.attrs.get("value")
        fill = t.to_numpy().reshape(()) if isinstance(t, proto.Tensor) else np.float32(0)
        return np.full([int(s) for s in np.asarray(a[0]).tolist()], fill)
    if op == "Range":
        return np.arange(np.asarray(a[0]).item(), np.asarray(a[1]).item(),
                         np.asarray(a[2]).item())
    if op == "Equal":
        return np.equal(a[0], a[1])
    if op == "Where":
        return np.where(a[0], a[1], a[2])
    if op in ("Add", "Sub", "Mul", "Div"):
        fn = {"Add": np.add, "Sub": np.subtract,
              "Mul": np.multiply, "Div": np.divide}[op]
        return fn(a[0], a[1])
    raise AssertionError(op)


def import_onnx(path_or_bytes, input_shape: Optional[Sequence[int]] = None,
                compute_dtype: str = "float32",
                layer_names: Optional[List[str]] = None,
                name: Optional[str] = None) -> FunctionModel:
    """Load an ONNX model file into a FunctionModel (GraphModule + weights).

    input_shape: per-example shape WITHOUT the batch dim (e.g. (3, 224, 224) NCHW).
      Defaults to the graph input's declared static dims (dynamic batch dim dropped).
    layer_names: ordered tap paths (head → backbone) for ImageFeaturizer's
      cutOutputLayers; auto-derived from the tail of the graph when omitted.
    """
    model = proto.load_model(path_or_bytes)
    g = model.graph

    consts: Dict[str, np.ndarray] = {t.name: t.to_numpy() for t in g.initializers}
    init_names = set(consts)
    # old exporters list initializers as inputs too; real inputs are the rest.
    # The first is the primary (ARGUMENT_0); any others become secondary
    # inputs fed by dict (DNNModel feedDict parity for multi-input models).
    real_inputs = [vi for vi in g.inputs if vi.name not in init_names]
    if not real_inputs:
        raise ValueError("ONNX graph has no non-initializer input")
    graph_input = real_inputs[0]
    extra_input_shapes: Dict[str, tuple] = {}
    extra_input_dtypes: Dict[str, np.dtype] = {}
    for vi in real_inputs[1:]:
        # shapes/dtypes are introspection metadata (init()'s shape probe);
        # dynamic dims stay None — actual shapes arrive with the fed arrays
        tail = (vi.dims or [])[1:]
        extra_input_shapes[vi.name] = tuple(
            int(d) if d is not None else None for d in tail)
        extra_input_dtypes[vi.name] = np.dtype(
            proto._DT_TO_NP.get(vi.elem_type, np.float32))
    if input_shape is None:
        dims = graph_input.dims or []
        if len(dims) < 1:
            raise ValueError(
                f"graph input {graph_input.name!r} has no declared shape; "
                "pass input_shape=")
        tail = dims[1:]  # drop batch dim
        if any(d is None for d in tail):
            raise ValueError(
                f"graph input {graph_input.name!r} has dynamic non-batch dims {dims}; "
                "pass input_shape=")
        input_shape = tuple(int(d) for d in tail)
    input_shape = tuple(input_shape)

    # --- constant folding pass (also fixes any exporter node ordering) ------
    nodes = list(g.nodes)
    compute: List[proto.Node] = []
    pending = nodes
    # iterate to fixpoint: a fold can enable another fold; exporters emit topo order,
    # so one ordered pass folds everything reachable — loop twice for safety
    for _ in range(2):
        remaining: List[proto.Node] = []
        for node in pending:
            known = all((not i) or i in consts for i in node.inputs)
            if known and node.op_type in _FOLDABLE:
                try:
                    val = _fold_node(
                        node, [consts[i] if i else None for i in node.inputs])
                    consts[node.outputs[0]] = np.asarray(val)
                    continue
                except Exception:
                    pass  # fall through: execute at runtime
            remaining.append(node)
        if remaining == pending:
            break
        pending = remaining
    compute = pending

    # --- name + wire the runtime nodes --------------------------------------
    graph_nodes: List[GraphNode] = []
    used_names: Dict[str, int] = {}
    for i, n in enumerate(compute):
        base = n.name or f"{n.op_type.lower()}_{i}"
        if base in used_names:
            used_names[base] += 1
            base = f"{base}__{used_names[base]}"
        else:
            used_names[base] = 0
        graph_nodes.append(GraphNode(
            name=base, op_type=n.op_type, inputs=list(n.inputs),
            outputs=list(n.outputs), attrs=dict(n.attrs)))

    if not g.outputs:
        raise ValueError("ONNX graph declares no outputs")
    output_name = g.outputs[0].name

    # params = only initializers actually consumed by runtime nodes
    needed = {i for n in graph_nodes for i in n.inputs if i in consts}
    params = {k: consts[k] for k in needed}

    module = GraphModule(
        graph_nodes, params, input_name=graph_input.name, output_name=output_name,
        input_shape=input_shape, name=name or (g.name or "onnx_model"),
        compute_dtype=compute_dtype, extra_input_shapes=extra_input_shapes,
        extra_input_dtypes=extra_input_dtypes,
        input_dtype=proto._DT_TO_NP.get(graph_input.elem_type, np.float32))

    if layer_names is None:
        # taps from the head backwards: last nodes producing "cut-worthy" outputs
        interesting = [gn.name for gn in graph_nodes
                       if gn.op_type in ("Gemm", "MatMul", "GlobalAveragePool",
                                         "Flatten", "AveragePool", "Softmax")]
        layer_names = list(reversed(interesting[-4:]))

    return FunctionModel(
        module=module, params=params, input_shape=input_shape,
        layer_names=layer_names, name=module.name,
        data_format="NCHW" if len(input_shape) == 3 else "NHWC")
