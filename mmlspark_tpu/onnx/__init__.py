"""ONNX interchange: import external pretrained checkpoints, export native models.

Replaces the reference's CNTK-format model loading (CNTK/SerializableFunction.scala)
with the open interchange format; no onnx/protobuf pip deps (see proto.py).
"""

from .importer import import_onnx
from .export import export_onnx
from . import proto

__all__ = ["import_onnx", "export_onnx", "proto"]
