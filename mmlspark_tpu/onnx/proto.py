"""Minimal protobuf wire-format codec + ONNX message schema (no onnx/protobuf deps).

The reference loads external pretrained models through a native deserializer
(CNTK/SerializableFunction.scala:23-42 ``Function.load(bytes)``); our equivalent is an
ONNX ModelProto parser feeding the importer in onnx/importer.py. ONNX files are plain
protobuf, and we only need a deterministic subset of the schema, so a hand-rolled
wire-format codec keeps the framework dependency-free (the `onnx` pip package is not
part of the environment).

Wire format: https://protobuf.dev/programming-guides/encoding/
  tag = (field_number << 3) | wire_type
  wire types: 0=varint, 1=fixed64, 2=length-delimited, 5=fixed32

Schema field numbers follow onnx/onnx.proto3 (IR v7, opset 13+ era — stable since 2017
for every field we touch).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# Wire-format primitives
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long (corrupt protobuf)")


def _write_varint(value: int) -> bytes:
    if value < 0:  # protobuf encodes negative ints as 10-byte two's complement
        value += 1 << 64
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag(value: int) -> int:  # not used by ONNX (no sint fields) but cheap to keep
    return (value << 1) ^ (value >> 63)


def iter_fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """Yield (field_number, wire_type, raw_value) for each field in a message."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire} (field {field})")
        yield field, wire, val


def parse_fields(buf: bytes) -> Dict[int, List[Any]]:
    """Group fields by number (repeated fields accumulate in order)."""
    out: Dict[int, List[Any]] = {}
    for field, _wire, val in iter_fields(buf):
        out.setdefault(field, []).append(val)
    return out


class Writer:
    """Append-only protobuf message writer."""

    def __init__(self) -> None:
        self._parts: List[bytes] = []

    def varint(self, field: int, value: int) -> "Writer":
        self._parts.append(_write_varint(field << 3 | 0))
        self._parts.append(_write_varint(int(value)))
        return self

    def bytes_(self, field: int, value: bytes) -> "Writer":
        self._parts.append(_write_varint(field << 3 | 2))
        self._parts.append(_write_varint(len(value)))
        self._parts.append(value)
        return self

    def string(self, field: int, value: str) -> "Writer":
        return self.bytes_(field, value.encode("utf-8"))

    def message(self, field: int, sub: "Writer") -> "Writer":
        return self.bytes_(field, sub.tobytes())

    def float32(self, field: int, value: float) -> "Writer":
        self._parts.append(_write_varint(field << 3 | 5))
        self._parts.append(struct.pack("<f", value))
        return self

    def packed_varints(self, field: int, values) -> "Writer":
        body = b"".join(_write_varint(int(v)) for v in values)
        return self.bytes_(field, body)

    def tobytes(self) -> bytes:
        return b"".join(self._parts)


def _as_int(v: Any) -> int:
    return v if isinstance(v, int) else _read_varint(v, 0)[0]


def _as_str(v: bytes) -> str:
    return v.decode("utf-8")


def _packed_ints(vals: List[Any]) -> List[int]:
    """A repeated varint field arrives either packed (bytes) or unpacked (ints)."""
    out: List[int] = []
    for v in vals:
        if isinstance(v, int):
            out.append(v)
        else:
            pos = 0
            while pos < len(v):
                x, pos = _read_varint(v, pos)
                out.append(x)
    return out


def _signed64(x: int) -> int:
    return x - (1 << 64) if x >= (1 << 63) else x


# ---------------------------------------------------------------------------
# ONNX schema: typed views over parsed messages
# ---------------------------------------------------------------------------

# TensorProto.DataType
DT_FLOAT, DT_UINT8, DT_INT8, DT_INT32, DT_INT64, DT_BOOL, DT_FLOAT16, DT_DOUBLE = (
    1, 2, 3, 6, 7, 9, 10, 11)
DT_BFLOAT16 = 16

_DT_TO_NP = {
    DT_FLOAT: np.float32,
    DT_UINT8: np.uint8,
    DT_INT8: np.int8,
    DT_INT32: np.int32,
    DT_INT64: np.int64,
    DT_BOOL: np.bool_,
    DT_FLOAT16: np.float16,
    DT_DOUBLE: np.float64,
}
_NP_TO_DT = {np.dtype(v): k for k, v in _DT_TO_NP.items()}


class Attribute:
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8, strings=9, type=20."""

    def __init__(self, buf: bytes):
        f = parse_fields(buf)
        self.name = _as_str(f[1][0]) if 1 in f else ""
        self.f = struct.unpack("<f", f[2][0])[0] if 2 in f else None
        self.i = _signed64(_as_int(f[3][0])) if 3 in f else None
        self.s = f[4][0] if 4 in f else None
        self.t = Tensor(f[5][0]) if 5 in f else None
        # repeated float: packed (one long buffer) or unpacked (4-byte chunks) — both
        # concatenate cleanly as little-endian f32
        self.floats = [x for v in f.get(7, [])
                       for x in np.frombuffer(v, dtype="<f4").tolist()]
        self.ints = [_signed64(x) for x in _packed_ints(f.get(8, []))]
        self.strings = list(f.get(9, []))

    def value(self) -> Any:
        for v in (self.t, self.s, self.f, self.i):
            if v is not None:
                return v
        if self.ints:
            return self.ints
        if self.floats:
            return self.floats
        if self.strings:
            return self.strings
        # scalar zero attributes (f=0.0 / i=0) are omitted on the wire; default to 0
        return 0


class Tensor:
    """TensorProto: dims=1, data_type=2, float_data=4, int32_data=5, int64_data=7,
    name=8, raw_data=9, double_data=10."""

    def __init__(self, buf: bytes):
        f = parse_fields(buf)
        self.dims = [_as_int(x) for x in _packed_ints(f.get(1, []))]
        self.data_type = _as_int(f[2][0]) if 2 in f else DT_FLOAT
        self.name = _as_str(f[8][0]) if 8 in f else ""
        self._f = f

    def to_numpy(self) -> np.ndarray:
        np_dtype = _DT_TO_NP.get(self.data_type)
        if np_dtype is None:
            raise ValueError(f"unsupported tensor data_type {self.data_type} "
                             f"for initializer {self.name!r}")
        f = self._f
        if 9 in f:  # raw_data: little-endian bytes
            arr = np.frombuffer(f[9][0], dtype=np.dtype(np_dtype).newbyteorder("<"))
        elif 4 in f and self.data_type == DT_FLOAT:
            arr = np.concatenate([np.frombuffer(v, dtype="<f4") for v in f[4]])
        elif 10 in f and self.data_type == DT_DOUBLE:
            arr = np.concatenate([np.frombuffer(v, dtype="<f8") for v in f[10]])
        elif 7 in f and self.data_type == DT_INT64:
            arr = np.array([_signed64(x) for x in _packed_ints(f[7])], dtype=np.int64)
        elif 5 in f:  # int32_data carries int32/int8/uint8/bool/float16 payloads
            ints = _packed_ints(f[5])
            if self.data_type == DT_FLOAT16:
                # fp16 in int32_data is the raw uint16 bit pattern, not a value
                arr = np.array(ints, dtype=np.int32).astype(np.uint16).view(np.float16)
            else:
                arr = np.array(ints, dtype=np.int32).astype(np_dtype)
        else:
            arr = np.zeros(0, dtype=np_dtype)
        return arr.reshape(self.dims).astype(np_dtype, copy=False)


class ValueInfo:
    """ValueInfoProto -> (name, elem_type, dims); dynamic dims become None."""

    def __init__(self, buf: bytes):
        f = parse_fields(buf)
        self.name = _as_str(f[1][0]) if 1 in f else ""
        self.elem_type: Optional[int] = None
        self.dims: Optional[List[Optional[int]]] = None
        if 2 in f:  # TypeProto
            tp = parse_fields(f[2][0])
            if 1 in tp:  # tensor_type
                tt = parse_fields(tp[1][0])
                if 1 in tt:
                    self.elem_type = _as_int(tt[1][0])
                if 2 in tt:  # TensorShapeProto
                    shape = parse_fields(tt[2][0])
                    dims: List[Optional[int]] = []
                    for dbuf in shape.get(1, []):
                        d = parse_fields(dbuf)
                        dims.append(_as_int(d[1][0]) if 1 in d else None)
                    self.dims = dims


class Node:
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5, domain=7."""

    def __init__(self, buf: bytes):
        f = parse_fields(buf)
        self.inputs = [_as_str(v) for v in f.get(1, [])]
        self.outputs = [_as_str(v) for v in f.get(2, [])]
        self.name = _as_str(f[3][0]) if 3 in f else ""
        self.op_type = _as_str(f[4][0]) if 4 in f else ""
        self.domain = _as_str(f[7][0]) if 7 in f else ""
        self.attrs: Dict[str, Any] = {}
        for abuf in f.get(5, []):
            a = Attribute(abuf)
            self.attrs[a.name] = a.value()

    def __repr__(self) -> str:
        return f"Node({self.op_type}:{self.name} {self.inputs}->{self.outputs})"


class Graph:
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""

    def __init__(self, buf: bytes):
        f = parse_fields(buf)
        self.name = _as_str(f[2][0]) if 2 in f else ""
        self.nodes = [Node(v) for v in f.get(1, [])]
        self.initializers = [Tensor(v) for v in f.get(5, [])]
        self.inputs = [ValueInfo(v) for v in f.get(11, [])]
        self.outputs = [ValueInfo(v) for v in f.get(12, [])]


class Model:
    """ModelProto: ir_version=1, producer=2, opset_import=8 (version=2), graph=7."""

    def __init__(self, buf: bytes):
        f = parse_fields(buf)
        self.ir_version = _as_int(f[1][0]) if 1 in f else 0
        self.producer = _as_str(f[2][0]) if 2 in f else ""
        if 7 not in f:
            raise ValueError("ModelProto has no graph — not an ONNX model file?")
        self.graph = Graph(f[7][0])
        self.opset = 0
        for ob in f.get(8, []):
            o = parse_fields(ob)
            if _as_str(o.get(1, [b""])[0]) == "":  # default (ai.onnx) domain
                self.opset = max(self.opset, _as_int(o[2][0]) if 2 in o else 0)


def load_model(path_or_bytes) -> Model:
    if isinstance(path_or_bytes, (bytes, bytearray)):
        return Model(bytes(path_or_bytes))
    with open(path_or_bytes, "rb") as fh:
        return Model(fh.read())


# ---------------------------------------------------------------------------
# ONNX writers (export path + test-fixture construction)
# ---------------------------------------------------------------------------


def make_tensor(name: str, arr: np.ndarray) -> Writer:
    arr = np.ascontiguousarray(arr)
    dt = _NP_TO_DT.get(arr.dtype)
    if dt is None:
        raise ValueError(f"unsupported numpy dtype {arr.dtype} for ONNX export")
    w = Writer()
    w.packed_varints(1, arr.shape)
    w.varint(2, dt)
    w.string(8, name)
    w.bytes_(9, arr.astype(arr.dtype.newbyteorder("<"), copy=False).tobytes())
    return w


def _attr(name: str, value: Any) -> Writer:
    w = Writer().string(1, name)
    if isinstance(value, float):
        w.float32(2, value).varint(20, 1)  # FLOAT
    elif isinstance(value, bool) or isinstance(value, int):
        w.varint(3, int(value)).varint(20, 2)  # INT
    elif isinstance(value, (bytes, str)):
        w.bytes_(4, value.encode() if isinstance(value, str) else value).varint(20, 3)
    elif isinstance(value, Writer):  # pre-built TensorProto
        w.message(5, value).varint(20, 4)
    elif isinstance(value, (list, tuple)) and value and isinstance(value[0], float):
        for v in value:
            w.float32(7, v)
        w.varint(20, 6)  # FLOATS
    elif isinstance(value, (list, tuple)):
        w.packed_varints(8, [int(v) for v in value]).varint(20, 7)  # INTS
    else:
        raise ValueError(f"unsupported attribute value {value!r}")
    return w


def make_node(op_type: str, inputs: List[str], outputs: List[str],
              name: str = "", **attrs: Any) -> Writer:
    w = Writer()
    for i in inputs:
        w.string(1, i)
    for o in outputs:
        w.string(2, o)
    if name:
        w.string(3, name)
    w.string(4, op_type)
    for k, v in attrs.items():
        w.message(5, _attr(k, v))
    return w


def make_value_info(name: str, dims: List[Optional[int]],
                    elem_type: int = DT_FLOAT) -> Writer:
    shape = Writer()
    for d in dims:
        dim = Writer()
        if d is not None:
            dim.varint(1, d)
        else:
            dim.string(2, "N")
        shape.message(1, dim)
    tensor_type = Writer().varint(1, elem_type).message(2, shape)
    type_proto = Writer().message(1, tensor_type)
    return Writer().string(1, name).message(2, type_proto)


def make_model(nodes: List[Writer], initializers: List[Writer],
               inputs: List[Writer], outputs: List[Writer],
               graph_name: str = "graph", opset: int = 13) -> bytes:
    g = Writer()
    for n in nodes:
        g.message(1, n)
    g.string(2, graph_name)
    for t in initializers:
        g.message(5, t)
    for vi in inputs:
        g.message(11, vi)
    for vi in outputs:
        g.message(12, vi)
    m = Writer()
    m.varint(1, 7)  # ir_version
    m.string(2, "mmlspark_tpu")
    m.message(7, g)
    m.message(8, Writer().string(1, "").varint(2, opset))
    return m.tobytes()
