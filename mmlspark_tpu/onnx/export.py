"""Export native module trees (models/module.py) to ONNX.

The reference's model zoo interchanges serialized graphs between toolkits
(downloader/Schema.scala:24-100 stores CNTK model URIs); the TPU framework's
interchange format is ONNX in both directions — import_onnx ingests external
checkpoints, export_onnx lets models trained here run anywhere else.

Layout: our modules compute NHWC; ONNX convention is NCHW. Export keeps ONNX-standard
NCHW activations by transposing conv kernels HWIO→OIHW (a transposed-weights conv on
transposed activations is the identical computation), so any stock ONNX runtime
executes the file unmodified.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models import module as M
from . import proto


def _pad_attrs(padding) -> Dict:
    """ONNX attrs for a Conv2D/MaxPool padding spec ("SAME"/"VALID"/explicit pairs)."""
    if padding == "SAME":
        return {"auto_pad": "SAME_UPPER"}
    if padding == "VALID":
        return {}
    (t, b), (l, r) = padding  # ONNX pads order: x1_begin, x2_begin, x1_end, x2_end
    return {"pads": [int(t), int(l), int(b), int(r)]}


class _Exporter:
    def __init__(self) -> None:
        self.nodes: List[proto.Writer] = []
        self.initializers: List[proto.Writer] = []
        self._n = 0

    def tname(self, hint: str) -> str:
        self._n += 1
        return f"{hint}_{self._n}"

    def const(self, hint: str, arr: np.ndarray) -> str:
        name = self.tname(hint)
        self.initializers.append(proto.make_tensor(name, np.asarray(arr)))
        return name

    def emit(self, op: str, inputs: List[str], hint: str, **attrs) -> str:
        out = self.tname(hint)
        self.nodes.append(proto.make_node(op, inputs, [out], name=out, **attrs))
        return out

    # -- module dispatch -----------------------------------------------------
    def walk(self, mod: M.Module, params: Dict, x: str, rank: int) -> Tuple[str, int]:
        """Emit nodes for `mod`; returns (output tensor name, activation rank)."""
        if isinstance(mod, M.Sequential):
            for lname, layer in mod.layers:
                x, rank = self.walk(layer, params.get(lname, {}), x, rank)
            return x, rank
        if isinstance(mod, M.Residual):
            y, yr = self.walk(mod.body, params["body"], x, rank)
            s, _ = (self.walk(mod.shortcut, params["shortcut"], x, rank)
                    if mod.shortcut is not None else (x, rank))
            added = self.emit("Add", [y, s], "res_add")
            return self.emit("Relu", [added], "res_relu"), yr
        if isinstance(mod, M.Conv2D):
            # HWIO -> OIHW
            k = self.const("conv_w", np.transpose(params["kernel"], (3, 2, 0, 1)))
            inputs = [x, k]
            if mod.use_bias:
                inputs.append(self.const("conv_b", params["bias"]))
            attrs: Dict = {"strides": list(mod.strides),
                           "kernel_shape": list(mod.kernel)}
            attrs.update(_pad_attrs(mod.padding))
            return self.emit("Conv", inputs, "conv", **attrs), 4
        if isinstance(mod, M.BatchNorm):
            ins = [x, self.const("bn_scale", params["scale"]),
                   self.const("bn_bias", params["bias"]),
                   self.const("bn_mean", params["mean"]),
                   self.const("bn_var", params["var"])]
            return self.emit("BatchNormalization", ins, "bn", epsilon=float(mod.eps)), rank
        if isinstance(mod, M.Dense):
            ins = [x, self.const("dense_w", params["kernel"])]
            if mod.use_bias:
                ins.append(self.const("dense_b", params["bias"]))
            return self.emit("Gemm", ins, "gemm"), 2
        if isinstance(mod, M.MaxPool):
            attrs = {"kernel_shape": list(mod.window), "strides": list(mod.strides)}
            attrs.update(_pad_attrs(mod.padding))
            return self.emit("MaxPool", [x], "maxpool", **attrs), 4
        if isinstance(mod, M.GlobalAvgPool):
            pooled = self.emit("GlobalAveragePool", [x], "gap")
            return self.emit("Flatten", [pooled], "gap_flat", axis=1), 2
        if isinstance(mod, M.Fn):
            if mod.fn is M._relu_fn:
                return self.emit("Relu", [x], "relu"), rank
            if mod.fn is M._flatten_fn:
                if rank == 4:
                    # NHWC element order: transpose NCHW act back before flattening
                    x = self.emit("Transpose", [x], "to_nhwc", perm=[0, 2, 3, 1])
                return self.emit("Flatten", [x], "flatten", axis=1), 2
            raise NotImplementedError(f"cannot export Fn wrapping {mod.fn}")
        raise NotImplementedError(f"cannot export module type {type(mod).__name__}")


def export_onnx(module: M.Module, params: Dict,
                input_shape: Tuple[int, ...], path: Optional[str] = None,
                name: str = "model") -> bytes:
    """Serialize (module, params) to ONNX bytes (and optionally a file).

    input_shape: per-example shape in the module's own convention (NHWC for images);
    the emitted graph takes standard ONNX NCHW input.
    """
    ex = _Exporter()
    if len(input_shape) == 3:
        h, w, c = input_shape
        onnx_in_shape: List[Optional[int]] = [None, c, h, w]
        rank = 4
    else:
        onnx_in_shape = [None] + [int(d) for d in input_shape]
        rank = len(onnx_in_shape)
    in_name = "input"
    out, _rank = ex.walk(module, params, in_name, rank)

    # probe output shape by running the native module
    probe = np.zeros((1,) + tuple(input_shape), dtype=np.float32)
    out_arr = np.asarray(module.apply(params, probe))
    out_dims: List[Optional[int]] = [None] + list(out_arr.shape[1:])

    blob = proto.make_model(
        ex.nodes, ex.initializers,
        [proto.make_value_info(in_name, onnx_in_shape)],
        [proto.make_value_info(out, out_dims)],
        graph_name=name)
    if path is not None:
        with open(path, "wb") as fh:
            fh.write(blob)
    return blob
