"""D001 — device purity: jitted bodies must not call host-only APIs.

Functions that become XLA programs — passed to ``jax.jit``, decorated with
``@jax.jit`` / ``@functools.partial(jax.jit, ...)``, or registered as the
``fn`` of a ``DeviceFn`` — execute as traced computations. A host-only call
inside one either breaks tracing or (worse) silently runs at trace time and
bakes a constant into the compiled program; ``.item()``-style reads force a
device sync inside what profiling assumes is a fused segment.

Flagged inside a jittable body:

  - ``time.*`` / stdlib ``random.*`` / ``np.random.*`` calls
  - I/O: ``open()``, ``print()``, ``input()``, ``os.*``
  - tracer escapes: ``.item()``, ``.tolist()``
  - in-place mutation of a parameter: ``arg[...] = ...`` (jax arrays are
    immutable; on a traced numpy input this mutates the host buffer)

The pass resolves jittable functions **within one module**: the argument of
a jit/DeviceFn call site must be a plain name bound by a ``def`` in the same
file (the repo's universal idiom — closures jitted right where they are
defined). ``prepare``/``finalize`` of DeviceFn are host shims and exempt —
but a ``device_finalize=`` argument is a TRANSPILED host shim (a finalizer
ported into the fused jit for cross-segment stitching, core/fusion.py) and
is held to a STRICTER bar: besides every jittable-body rule, any bare
``np.*`` / ``numpy.*`` call is a finding, because inside the fused trace it
silently constant-folds the finalizer's math at trace time.

D001 also covers **ring staging callbacks**: the batch source and ``put``
arguments of ``TransferRing(...)`` / ``DevicePrefetcher(...)``. Those run
on the ring's producer thread between socket and device — a host
allocation there (``np.empty`` / ``np.zeros`` / ``np.stack``) reintroduces
the per-batch copy the slot-staging path exists to eliminate, silently and
off the transform thread where profilers point. Resolution is module-local
(plain names, ``self.X`` methods, lambdas wrapping module-local calls,
simple ``x = f(...)`` rebinds) plus a bounded transitive closure over
module-local callees. The accounted fallback path (slot contention →
allocate-and-count) carries justified inline suppressions.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .astutil import call_keyword, dotted_name
from .framework import AnalysisPass, Finding, SourceFile

_HOST_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.", "os.")
_HOST_BUILTINS = {"open", "print", "input"}
_TRACER_ESCAPES = {"item", "tolist"}
# DeviceFn(key, in_cols, out_cols, fn, ...) — fn is the 4th positional
_DEVICEFN_FN_POS = 3


def _is_jit_expr(node: ast.expr) -> bool:
    """True for ``jax.jit`` / ``jit`` and ``[functools.]partial(jax.jit,...)``."""
    name = dotted_name(node)
    if name in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in ("functools.partial", "partial") and node.args:
            return _is_jit_expr(node.args[0])
    return False


def _jitted_names(tree: ast.AST) -> "Tuple[Dict[str, int], Set[str]]":
    """(jitted, pallas): {function name: reporting line} for every
    module-local name that is jitted, registered as a DeviceFn body (dense
    ``fn`` or the CSR-capable ``sparse_fn``), or passed as a Pallas kernel
    (``pl.pallas_call`` bodies trace on-core — the same host-call rules
    apply). ``pallas`` names the kernel subset: ``out_ref[...] = ...``
    Ref stores are how a Pallas kernel WRITES its output, so the
    parameter-mutation rule is waived for them (host calls are not)."""
    jitted: Dict[str, int] = {}
    pallas: Set[str] = set()

    def mark(arg: ast.expr, kernel: bool = False) -> None:
        if isinstance(arg, ast.Name):
            jitted.setdefault(arg.id, arg.lineno)
            if kernel:
                pallas.add(arg.id)
        elif isinstance(arg, ast.Call):
            # functools.partial(kernel, ...) — the pallas_call grid idiom
            fname = dotted_name(arg.func)
            if fname in ("functools.partial", "partial") and arg.args:
                mark(arg.args[0], kernel=kernel)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            if _is_jit_expr(node.func) and node.args:
                mark(node.args[0])
            callee = dotted_name(node.func) or ""
            tail = callee.rsplit(".", 1)[-1]
            if tail == "pallas_call" and node.args:
                mark(node.args[0], kernel=True)
            if tail == "DeviceFn":
                kw = call_keyword(node, "fn")
                if kw is not None:
                    mark(kw)
                elif len(node.args) > _DEVICEFN_FN_POS:
                    mark(node.args[_DEVICEFN_FN_POS])
                sfn = call_keyword(node, "sparse_fn")
                if sfn is not None:
                    mark(sfn)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    jitted.setdefault(node.name, node.lineno)
    return jitted, pallas


def _transpiled_names(tree: ast.AST) -> Dict[str, int]:
    """{function name: reporting line} for every module-local name passed
    as a ``device_finalize=`` keyword — a host finalizer TRANSPILED into
    the fused jit (the cross-segment stitch shim). Matched on ANY call,
    not just a literal ``DeviceFn(...)``: stages route the shim through
    builder helpers (``self._score_device_fn(..., device_finalize=f)``)."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        kw = call_keyword(node, "device_finalize")
        if isinstance(kw, ast.Name):
            out.setdefault(kw.id, kw.lineno)
    return out


def _host_call_reason(node: ast.Call) -> Optional[str]:
    name = dotted_name(node.func)
    if name is not None:
        if name in _HOST_BUILTINS:
            return f"host I/O call '{name}()'"
        for p in _HOST_PREFIXES:
            if name.startswith(p):
                return f"host-only call '{name}'"
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in _TRACER_ESCAPES:
        return (f"'.{node.func.attr}()' forces a host sync on a tracer")
    return None


#: host allocators that negate zero-copy staging when run on a ring thread
_STAGING_ALLOCS = {"np.empty", "np.zeros", "np.stack",
                   "numpy.empty", "numpy.zeros", "numpy.stack"}
_RING_CLASSES = {"TransferRing", "DevicePrefetcher"}
#: module-local call-graph hops followed from a registered callback
_STAGING_CLOSURE_DEPTH = 3


def _local_defs(tree: ast.AST) -> Dict[str, ast.AST]:
    """Every ``def`` in the module (any nesting), by name. Later defs win —
    matching the runtime's last-binding-wins for module-level names and
    good enough for the repo's no-shadowing idiom."""
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def _callee_name(expr: ast.expr) -> Optional[str]:
    """Module-local function name a callback expression resolves to:
    ``fn`` or ``self.fn`` (methods live in the same file)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id in ("self", "cls"):
        return expr.attr
    return None


def _staging_callbacks(tree: ast.AST, defs: Dict[str, ast.AST]
                       ) -> "Tuple[Dict[str, int], List[ast.Lambda]]":
    """(callbacks, lambdas): {def name: registration line} for functions
    registered as ring staging callbacks — plus a bounded closure over
    their module-local callees (the allocation usually hides one call down,
    the batch generator behind a fill-ahead wrapper) — and the lambda
    callbacks, whose bodies are checked in place."""
    # simple rebind map: `src = self._batches(...)` / `a, b = f(...)`
    # lets a Name argument resolve through one local assignment
    assigned: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            fn = _callee_name(node.value.func)
            if fn is None or fn not in defs:
                continue
            for t in node.targets:
                for n2 in ast.walk(t):
                    if isinstance(n2, ast.Name) \
                            and isinstance(n2.ctx, ast.Store):
                        assigned.setdefault(n2.id, fn)

    seeds: Dict[str, int] = {}
    lambdas: List[ast.Lambda] = []

    def mark(arg: Optional[ast.expr], line: int) -> None:
        if arg is None:
            return
        if isinstance(arg, ast.Lambda):
            lambdas.append(arg)
            # a lambda wrapping a module-local call stages through it
            for inner in ast.walk(arg.body):
                if isinstance(inner, ast.Call):
                    fn = _callee_name(inner.func)
                    if fn in defs:
                        seeds.setdefault(fn, line)
            return
        fn = _callee_name(arg if not isinstance(arg, ast.Call)
                          else arg.func)
        if fn is not None and fn not in defs:
            fn = assigned.get(fn) if isinstance(arg, ast.Name) else None
        if fn in defs:
            seeds.setdefault(fn, line)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func) or ""
        if callee.rsplit(".", 1)[-1] not in _RING_CLASSES:
            continue
        if node.args:
            mark(node.args[0], node.lineno)       # the batch source
        put = call_keyword(node, "put")
        if put is None and len(node.args) > 1:
            put = node.args[1]                    # TransferRing(it, put, ..)
        mark(put, node.lineno)

    # bounded module-local closure: callbacks delegating to helpers
    frontier = list(seeds)
    for _ in range(_STAGING_CLOSURE_DEPTH):
        nxt: List[str] = []
        for name in frontier:
            body = defs.get(name)
            if body is None:
                continue
            for inner in ast.walk(body):
                if isinstance(inner, ast.Call):
                    fn = _callee_name(inner.func)
                    if fn in defs and fn not in seeds:
                        seeds[fn] = seeds[name]
                        nxt.append(fn)
        if not nxt:
            break
        frontier = nxt
    return seeds, lambdas


class DevicePurityPass(AnalysisPass):
    pass_ids = ("D001",)
    name = "device-purity"
    description = ("host-only APIs (time/random/IO/.item()) inside "
                   "functions that are jitted or registered as DeviceFn "
                   "bodies; host allocations inside ring staging "
                   "callbacks")

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("mmlspark_tpu/") and \
            not rel.startswith("mmlspark_tpu/testing/")

    def check(self, sf: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        if sf.tree is None:
            return findings
        findings.extend(self._check_staging(sf))
        jitted, pallas = _jitted_names(sf.tree)
        transpiled = _transpiled_names(sf.tree)
        for name, line in transpiled.items():
            jitted.setdefault(name, line)
        if not jitted:
            return findings
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name not in jitted:
                continue
            params: Set[str] = {a.arg for a in (
                node.args.posonlyargs + node.args.args
                + node.args.kwonlyargs)}
            # a name rebound inside the body (`devd = dict(devd)`) is a
            # local copy — mutating it is not mutating the traced input
            for inner in ast.walk(node):
                if isinstance(inner, ast.Assign):
                    for t in inner.targets:
                        for n2 in ast.walk(t):
                            if isinstance(n2, ast.Name) \
                                    and isinstance(n2.ctx, ast.Store):
                                params.discard(n2.id)
            for inner in ast.walk(node):
                if isinstance(inner, ast.Call):
                    reason = _host_call_reason(inner)
                    if reason is None and node.name in transpiled:
                        # transpiled finalizers run INSIDE the fused trace:
                        # bare numpy there constant-folds at trace time
                        cname = dotted_name(inner.func)
                        if cname is not None and (
                                cname.startswith("np.")
                                or cname.startswith("numpy.")):
                            reason = (f"host numpy call '{cname}' — "
                                      f"transpiled finalizers must use "
                                      f"jnp only")
                    if reason:
                        findings.append(Finding(
                            sf.rel, inner.lineno, "D001",
                            f"{reason} inside jittable '{node.name}' — "
                            f"device functions must be trace-pure"))
                elif isinstance(inner, (ast.Assign, ast.AugAssign)):
                    if node.name in pallas:
                        continue  # Ref stores ARE the kernel's output path
                    targets = inner.targets if isinstance(
                        inner, ast.Assign) else [inner.target]
                    for t in targets:
                        if (isinstance(t, ast.Subscript)
                                and isinstance(t.value, ast.Name)
                                and t.value.id in params):
                            findings.append(Finding(
                                sf.rel, t.lineno, "D001",
                                f"in-place mutation of parameter "
                                f"'{t.value.id}' inside jittable "
                                f"'{node.name}' — use .at[].set()"))
        return findings

    def _check_staging(self, sf: SourceFile) -> Iterable[Finding]:
        """Host allocations inside ring staging callbacks (and the
        module-local helpers they delegate to): each one is a per-batch
        copy on the producer thread the slot-staging path was built to
        remove."""
        findings: List[Finding] = []
        defs = _local_defs(sf.tree)
        callbacks, lambdas = _staging_callbacks(sf.tree, defs)
        if not callbacks and not lambdas:
            return findings

        def scan(body: ast.AST, where: str) -> None:
            for inner in ast.walk(body):
                if not isinstance(inner, ast.Call):
                    continue
                name = dotted_name(inner.func)
                if name in _STAGING_ALLOCS:
                    findings.append(Finding(
                        sf.rel, inner.lineno, "D001",
                        f"host allocation '{name}()' inside ring staging "
                        f"callback '{where}' — staging must fill "
                        f"pre-allocated slots, not allocate per batch"))

        for name in callbacks:
            body = defs.get(name)
            if body is not None:
                scan(body, name)
        for lam in lambdas:
            scan(lam.body, f"<lambda:{lam.lineno}>")
        return findings
