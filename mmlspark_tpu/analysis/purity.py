"""D001 — device purity: jitted bodies must not call host-only APIs.

Functions that become XLA programs — passed to ``jax.jit``, decorated with
``@jax.jit`` / ``@functools.partial(jax.jit, ...)``, or registered as the
``fn`` of a ``DeviceFn`` — execute as traced computations. A host-only call
inside one either breaks tracing or (worse) silently runs at trace time and
bakes a constant into the compiled program; ``.item()``-style reads force a
device sync inside what profiling assumes is a fused segment.

Flagged inside a jittable body:

  - ``time.*`` / stdlib ``random.*`` / ``np.random.*`` calls
  - I/O: ``open()``, ``print()``, ``input()``, ``os.*``
  - tracer escapes: ``.item()``, ``.tolist()``
  - in-place mutation of a parameter: ``arg[...] = ...`` (jax arrays are
    immutable; on a traced numpy input this mutates the host buffer)

The pass resolves jittable functions **within one module**: the argument of
a jit/DeviceFn call site must be a plain name bound by a ``def`` in the same
file (the repo's universal idiom — closures jitted right where they are
defined). ``prepare``/``finalize`` of DeviceFn are host shims and exempt.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from .astutil import call_keyword, dotted_name
from .framework import AnalysisPass, Finding, SourceFile

_HOST_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.", "os.")
_HOST_BUILTINS = {"open", "print", "input"}
_TRACER_ESCAPES = {"item", "tolist"}
# DeviceFn(key, in_cols, out_cols, fn, ...) — fn is the 4th positional
_DEVICEFN_FN_POS = 3


def _is_jit_expr(node: ast.expr) -> bool:
    """True for ``jax.jit`` / ``jit`` and ``[functools.]partial(jax.jit,...)``."""
    name = dotted_name(node)
    if name in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in ("functools.partial", "partial") and node.args:
            return _is_jit_expr(node.args[0])
    return False


def _jitted_names(tree: ast.AST) -> Dict[str, int]:
    """{function name: reporting line} for every module-local name that is
    jitted or registered as a DeviceFn body."""
    jitted: Dict[str, int] = {}

    def mark(arg: ast.expr) -> None:
        if isinstance(arg, ast.Name):
            jitted.setdefault(arg.id, arg.lineno)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            if _is_jit_expr(node.func) and node.args:
                mark(node.args[0])
            callee = dotted_name(node.func) or ""
            if callee.rsplit(".", 1)[-1] == "DeviceFn":
                kw = call_keyword(node, "fn")
                if kw is not None:
                    mark(kw)
                elif len(node.args) > _DEVICEFN_FN_POS:
                    mark(node.args[_DEVICEFN_FN_POS])
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    jitted.setdefault(node.name, node.lineno)
    return jitted


def _host_call_reason(node: ast.Call) -> Optional[str]:
    name = dotted_name(node.func)
    if name is not None:
        if name in _HOST_BUILTINS:
            return f"host I/O call '{name}()'"
        for p in _HOST_PREFIXES:
            if name.startswith(p):
                return f"host-only call '{name}'"
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in _TRACER_ESCAPES:
        return (f"'.{node.func.attr}()' forces a host sync on a tracer")
    return None


class DevicePurityPass(AnalysisPass):
    pass_ids = ("D001",)
    name = "device-purity"
    description = ("host-only APIs (time/random/IO/.item()) inside "
                   "functions that are jitted or registered as DeviceFn "
                   "bodies")

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("mmlspark_tpu/") and \
            not rel.startswith("mmlspark_tpu/testing/")

    def check(self, sf: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        if sf.tree is None:
            return findings
        jitted = _jitted_names(sf.tree)
        if not jitted:
            return findings
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name not in jitted:
                continue
            params: Set[str] = {a.arg for a in (
                node.args.posonlyargs + node.args.args
                + node.args.kwonlyargs)}
            # a name rebound inside the body (`devd = dict(devd)`) is a
            # local copy — mutating it is not mutating the traced input
            for inner in ast.walk(node):
                if isinstance(inner, ast.Assign):
                    for t in inner.targets:
                        for n2 in ast.walk(t):
                            if isinstance(n2, ast.Name) \
                                    and isinstance(n2.ctx, ast.Store):
                                params.discard(n2.id)
            for inner in ast.walk(node):
                if isinstance(inner, ast.Call):
                    reason = _host_call_reason(inner)
                    if reason:
                        findings.append(Finding(
                            sf.rel, inner.lineno, "D001",
                            f"{reason} inside jittable '{node.name}' — "
                            f"device functions must be trace-pure"))
                elif isinstance(inner, (ast.Assign, ast.AugAssign)):
                    targets = inner.targets if isinstance(
                        inner, ast.Assign) else [inner.target]
                    for t in targets:
                        if (isinstance(t, ast.Subscript)
                                and isinstance(t.value, ast.Name)
                                and t.value.id in params):
                            findings.append(Finding(
                                sf.rel, t.lineno, "D001",
                                f"in-place mutation of parameter "
                                f"'{t.value.id}' inside jittable "
                                f"'{node.name}' — use .at[].set()"))
        return findings
