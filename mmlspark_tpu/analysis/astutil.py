"""Small shared AST helpers for the analysis passes."""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Tuple


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.lax.pcast`` -> "jax.lax.pcast"; None for non-name chains
    (calls, subscripts, literals anywhere in the chain)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def self_attr(node: ast.AST) -> Optional[str]:
    """"X" for a ``self.X`` attribute node, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def assigned_attrs(stmt: ast.stmt) -> Iterator[Tuple[str, int]]:
    """(attr, line) for every ``self.X`` stored to by an assignment
    statement, including tuple unpacking and augmented assignment."""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        for node in ast.walk(t):
            attr = self_attr(node)
            if attr is not None and isinstance(node.ctx, ast.Store):
                yield attr, node.lineno


def call_keyword(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def walk_skipping_nested_functions(body) -> Iterator[ast.AST]:
    """Walk statements of one function body without descending into nested
    (a)sync function definitions — their bodies run in a different context
    (e.g. an executor thread) and are analyzed on their own."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.append(child)
