"""AST-based, dependency-free static analysis with project-specific passes.

Seven PRs of history show this codebase's worst shipped bugs were statically
visible: reset()-vs-build races in CompileCache (PR 7), close-vs-producer
races in the batcher (PR 1), a renderer emitting broken bootstrap args
(PR 6), and two rounds of manual bare-assert audits (PRs 1, 3). This package
derives those facts from the AST and fails CI on violations instead of
re-auditing by hand every few PRs — Automap (PAPERS.md) applied defensively:
program structure is analyzed mechanically, here for concurrency and
device-purity properties rather than parallelism ones.

Entry points:

  - ``tools/analyze.py``            CLI (human + ``--json`` output)
  - ``analysis.run_analysis(root)`` library API (the CLI and tests use this)
  - ``analysis.analyze_source``     single-snippet API (fixture tests)

Pass catalog and suppression syntax: docs/static_analysis.md.
"""

from .framework import (  # noqa: F401
    Finding,
    SourceFile,
    AnalysisPass,
    run_analysis,
    analyze_source,
    default_passes,
    CHECKED_DIRS,
    SUPPRESSION_FILE,
)
