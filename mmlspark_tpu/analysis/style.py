"""S0xx — the committed style rule set (scalastyle-config.xml equivalent).

Folded in from ``tools/ci/stylecheck.py`` so one driver runs every gate;
``tools/ci/stylecheck.py`` remains as a thin compatibility shim over this
pass (same rules, same message text, same exit codes).

  S001 line too long | S002 tab | S003 trailing whitespace
  S004 merge-conflict marker | S005 mutable default argument
  S006 star import in library code | S007 missing trailing newline
  S008 multiple trailing newlines
"""

from __future__ import annotations

import re
from typing import Iterable, List

from .framework import AnalysisPass, Finding, SourceFile

MAX_LINE = 100
_MUTABLE_DEFAULT = re.compile(r"def \w+\([^)]*=\s*(\[\]|\{\}|set\(\))")
_CONFLICT = re.compile(r"^(<{7}|>{7}|={7})( |$)")


def style_findings(sf: SourceFile) -> List[Finding]:
    """The rule set, line-for-line the historical stylecheck semantics."""
    out: List[Finding] = []

    def add(line: int, pass_id: str, msg: str) -> None:
        out.append(Finding(sf.rel, line, pass_id, msg))

    for i, line in enumerate(sf.lines, 1):
        if len(line) > MAX_LINE:
            add(i, "S001", f"line too long ({len(line)} > {MAX_LINE})")
        if "\t" in line:
            add(i, "S002", "tab character")
        if line != line.rstrip():
            add(i, "S003", "trailing whitespace")
        if _CONFLICT.match(line):
            add(i, "S004", "merge conflict marker")
        if _MUTABLE_DEFAULT.search(line):
            add(i, "S005", "mutable default argument")
        if ("import *" in line and line.strip().startswith("from")
                and "mmlspark_tpu" in sf.rel):
            add(i, "S006", "star import in library code")
    if sf.text and not sf.text.endswith("\n"):
        add(len(sf.lines), "S007", "missing trailing newline")
    if sf.text.endswith("\n\n"):
        add(len(sf.lines), "S008", "multiple trailing newlines")
    return out


class StylePass(AnalysisPass):
    pass_ids = ("S001", "S002", "S003", "S004", "S005", "S006", "S007",
                "S008")
    name = "style"
    description = ("committed style rules: line length, whitespace, conflict "
                   "markers, mutable defaults, star imports, final newline")

    def check(self, sf: SourceFile) -> Iterable[Finding]:
        return style_findings(sf)
