"""C0xx — per-class lock model, lock-order graph, async blocking calls.

The historical bug classes this pass re-detects mechanically:

  C001  an attribute written both inside and outside ``with self._lock``
        blocks of the same class — the CompileCache reset()-vs-build race
        (PR 7's generation guard) and the batcher close-vs-producer race
        (PR 1) were exactly this shape.
  C002  cross-class lock-acquisition order inversion: while holding lock A
        some method calls into a class that takes lock B, and elsewhere the
        acquisition happens B-then-A — a deadlock candidate.
  C003  blocking calls (``time.sleep``, socket/file I/O, ``.result()``,
        bare ``lock.acquire()``, ``queue.get()`` without timeout) inside
        ``async def`` bodies — each stalls the entire event loop
        (``serving/aio.py`` runs every connection on one loop).

Model notes (kept deliberately conservative to stay quiet on sound code):

  - a "lock attribute" is any ``self.X = threading.Lock()/RLock()/
    Condition()`` assignment in the class;
  - writes in ``__init__``/``__post_init__``/``__setstate__``/``__del__``
    never count as unlocked writes (the object is not shared yet/anymore);
  - C002 resolves ``self.m()`` within the class; for ``other.m()`` the
    callee is matched by method name only when exactly one lock-holding
    class defines ``m`` (ambiguous names are skipped, not guessed).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .astutil import (assigned_attrs, dotted_name, self_attr,
                      walk_skipping_nested_functions)
from .framework import AnalysisPass, Finding, SourceFile

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
_BIRTH_METHODS = {"__init__", "__post_init__", "__setstate__", "__del__",
                  "__new__"}
# receivers whose .get() looks like a queue, not a dict
_QUEUEISH = ("queue", "_q")


def _is_lock_factory(value: ast.expr) -> bool:
    if not isinstance(value, ast.Call):
        return False
    name = dotted_name(value.func)
    if name is None:
        return False
    return name.rsplit(".", 1)[-1] in _LOCK_FACTORIES


@dataclasses.dataclass
class _Write:
    attr: str
    method: str
    line: int
    locked_by: Optional[str]  # lock attr held at the write site, else None


@dataclasses.dataclass
class _RegionCall:
    lock: str          # lock attr held at the call site
    receiver: str      # "self" | "other"
    callee: str        # method/function name
    line: int


class _ClassModel:
    """Lock facts for one class: lock attrs, attribute writes with their
    lock context, and calls made while holding each lock."""

    def __init__(self, rel: str, node: ast.ClassDef):
        self.rel = rel
        self.name = node.name
        self.lock_attrs: Set[str] = set()
        self.writes: List[_Write] = []
        self.region_calls: List[_RegionCall] = []
        self.method_locks: Dict[str, Set[str]] = {}
        methods = [s for s in node.body
                   if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in methods:
            for n in ast.walk(fn):
                if isinstance(n, ast.Assign) and _is_lock_factory(n.value):
                    for t in n.targets:
                        attr = self_attr(t)
                        if attr is not None:
                            self.lock_attrs.add(attr)
        for fn in methods:
            self._scan_method(fn)

    def _with_locks(self, node) -> List[str]:
        locks = []
        for item in node.items:
            attr = self_attr(item.context_expr)
            if attr in self.lock_attrs:
                locks.append(attr)
        return locks

    def _scan_method(self, fn) -> None:
        method = fn.name
        acquired = self.method_locks.setdefault(method, set())

        def visit(node: ast.AST, held: Optional[str]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return  # nested def: runs later, in a different context
            if isinstance(node, (ast.With, ast.AsyncWith)):
                locks = self._with_locks(node)
                acquired.update(locks)
                for item in node.items:  # headers evaluate pre-acquisition
                    visit(item.context_expr, held)
                inner = locks[0] if locks else held
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                for attr, line in assigned_attrs(node):
                    if attr not in self.lock_attrs:
                        self.writes.append(_Write(attr, method, line, held))
            if isinstance(node, ast.Call) and held is not None:
                self._record_call(node, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, None)

    def _record_call(self, node: ast.Call, held: str) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            is_self = (isinstance(func.value, ast.Name)
                       and func.value.id == "self")
            self.region_calls.append(_RegionCall(
                held, "self" if is_self else "other", func.attr,
                node.lineno))
        elif isinstance(func, ast.Name):
            self.region_calls.append(
                _RegionCall(held, "other", func.id, node.lineno))


def _unlocked_write_findings(model: _ClassModel) -> List[Finding]:
    locked: Dict[str, Set[str]] = {}
    for w in model.writes:
        if w.locked_by is not None:
            locked.setdefault(w.attr, set()).add(w.locked_by)
    out = []
    for w in model.writes:
        if w.locked_by is not None or w.method in _BIRTH_METHODS:
            continue
        if w.attr in locked:
            lock = sorted(locked[w.attr])[0]
            out.append(Finding(
                model.rel, w.line, "C001",
                f"'{model.name}.{w.attr}' written in {w.method}() without "
                f"'self.{lock}', but written under that lock elsewhere in "
                f"the class — data-race candidate"))
    return out


_BLOCKING_ROOTS = ("time.sleep", "socket.", "subprocess.", "urllib.",
                   "requests.")
_BLOCKING_BUILTINS = {"open", "input", "sleep"}


def _async_findings(sf: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    if sf.tree is None:
        return out
    awaited = {id(n.value) for n in ast.walk(sf.tree)
               if isinstance(n, ast.Await)}
    for fn in ast.walk(sf.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        for node in walk_skipping_nested_functions(fn.body):
            if not isinstance(node, ast.Call) or id(node) in awaited:
                continue
            msg = _blocking_call_reason(node)
            if msg:
                out.append(Finding(
                    sf.rel, node.lineno, "C003",
                    f"{msg} inside 'async def {fn.name}' blocks the event "
                    f"loop — await an async equivalent or move it to an "
                    f"executor"))
    return out


def _blocking_call_reason(node: ast.Call) -> Optional[str]:
    func = node.func
    name = dotted_name(func)
    if name is not None and "." not in name:
        if name in _BLOCKING_BUILTINS:
            return f"blocking call '{name}()'"
    if name is not None:
        for root in _BLOCKING_ROOTS:
            if name == root or name.startswith(root):
                return f"blocking call '{name}'"
    if isinstance(func, ast.Attribute):
        attr = func.attr
        recv = dotted_name(func.value) or ""
        recv_last = recv.rsplit(".", 1)[-1].lower()
        if attr == "result":
            return f"blocking Future '.result()' on '{recv or '<expr>'}'"
        if attr == "acquire" and "lock" in recv_last:
            return f"bare '{recv}.acquire()'"
        if (attr == "get" and not node.args
                and not any(kw.arg == "timeout" for kw in node.keywords)
                and (recv_last == "q"
                     or any(h in recv_last for h in _QUEUEISH))):
            return f"'{recv}.get()' without timeout"
    return None


class ConcurrencyPass(AnalysisPass):
    pass_ids = ("C001", "C002", "C003")
    name = "concurrency"
    description = ("per-class lock model (unlocked writes), cross-class "
                   "lock-order cycles, blocking calls in async bodies")

    def __init__(self):
        self._models: List[_ClassModel] = []

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("mmlspark_tpu/") and \
            not rel.startswith("mmlspark_tpu/testing/")

    def check(self, sf: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        if sf.tree is None:
            return findings
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                model = _ClassModel(sf.rel, node)
                if model.lock_attrs:
                    self._models.append(model)
                    findings.extend(_unlocked_write_findings(model))
        findings.extend(_async_findings(sf))
        return findings

    def finish(self) -> Iterable[Finding]:
        return _lock_order_findings(self._models)


# builtin-container method names: `self._d.clear()` is a dict call, not a
# call into another lock-holding class that happens to define clear() —
# never resolve these across classes
_GENERIC_METHODS = {"clear", "get", "put", "pop", "update", "append", "add",
                    "remove", "extend", "discard", "copy", "insert",
                    "setdefault", "keys", "values", "items", "count",
                    "index", "sort", "reverse", "join", "popleft"}


def _lock_order_findings(models: List[_ClassModel]) -> List[Finding]:
    """Build the cross-class lock graph, report one finding per cycle."""
    by_method: Dict[str, List[_ClassModel]] = {}
    for m in models:
        for meth, locks in m.method_locks.items():
            if locks:
                by_method.setdefault(meth, []).append(m)
    # edges: (class, lock) -> {(class, lock): (rel, line, callee)}
    edges: Dict[Tuple[str, str],
                Dict[Tuple[str, str], Tuple[str, int, str]]] = {}
    for m in models:
        for call in m.region_calls:
            if call.receiver == "self":
                callees = [m] if m.method_locks.get(call.callee) else []
            elif call.callee in _GENERIC_METHODS:
                callees = []  # almost certainly a dict/list/set/queue call
            else:
                cands = [c for c in by_method.get(call.callee, [])
                         if c is not m]
                callees = cands if len(cands) == 1 else []
            src = (m.name, call.lock)
            for callee_model in callees:
                for lock in callee_model.method_locks.get(call.callee, ()):
                    dst = (callee_model.name, lock)
                    if dst == src:
                        continue  # re-entrant same-lock: RLock territory
                    edges.setdefault(src, {}).setdefault(
                        dst, (m.rel, call.line, call.callee))
    return _find_cycles(edges)


def _find_cycles(edges) -> List[Finding]:
    findings: List[Finding] = []
    seen_cycles: Set[Tuple] = set()
    for start in sorted(edges):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(edges.get(node, {})):
                if nxt == start:
                    cyc = tuple(sorted(path))
                    if cyc in seen_cycles:
                        continue
                    seen_cycles.add(cyc)
                    rel, line, callee = edges[node][start]
                    chain = " -> ".join(
                        f"{c}.{lk}" for c, lk in path + [start])
                    findings.append(Finding(
                        rel, line, "C002",
                        f"lock-order inversion: {chain} (via call to "
                        f"'{callee}()' while holding "
                        f"'{node[0]}.{node[1]}') — deadlock candidate"))
                elif nxt not in path and len(path) < 6:
                    stack.append((nxt, path + [nxt]))
    return findings
