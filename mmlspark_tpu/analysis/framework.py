"""Analysis driver: one tree walk, one parse per file, N passes.

The framework owns everything pass-independent: file discovery, parsing,
the suppression mechanism, and finding aggregation. Passes see a parsed
``SourceFile`` and yield ``Finding``s; cross-file passes (the lock-order
graph) accumulate state per file and emit in ``finish()``.

Suppression contract (every suppression carries a justification):

  - inline:  ``# analysis: allow C001 -- <one-line justification>``
    on the finding's line, or alone on the line directly above it.
    Multiple ids: ``allow C001, J001 -- ...``.
  - file-scope: a line in ``tools/ci/analysis_suppressions.txt``:
    ``<repo-relative-path>: <PASS-ID>: <justification>``.

A suppression with an empty justification does not suppress — it becomes a
``SUP1`` finding itself, so CI rejects undocumented silencing.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# same scope as the historical style gate (tools/ci/stylecheck.py)
CHECKED_DIRS = ("mmlspark_tpu", "tests", "tools", "examples")
SUPPRESSION_FILE = Path("tools") / "ci" / "analysis_suppressions.txt"

_INLINE_RE = re.compile(
    r"#\s*analysis:\s*allow\s+"
    r"(?P<ids>[A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*)"
    r"(?:\s*--\s*(?P<why>.*?))?\s*$")
_FILE_RULE_RE = re.compile(
    r"^(?P<path>[^:#][^:]*?)\s*:\s*(?P<id>[A-Z]+\d+)\s*:\s*(?P<why>.*)$")


@dataclasses.dataclass
class Finding:
    """One analyzer result: ``path:line: pass_id message``."""

    path: str          # repo-relative, posix separators
    line: int
    pass_id: str
    message: str
    suppressed: bool = False
    justification: Optional[str] = None

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.pass_id} {self.message}"

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class _InlineRule:
    ids: Tuple[str, ...]
    justification: str  # "" = missing (invalid)
    comment_only: bool  # alone on its line -> applies to the next line


class SourceFile:
    """One parsed file: text, line list, AST (None on syntax error), and
    the inline suppression rules found in its comments."""

    def __init__(self, rel: str, text: str):
        self.rel = rel
        self.text = text
        self.lines = text.split("\n")
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(text)
        except SyntaxError as e:  # surfaced as an E001 finding by the driver
            self.parse_error = f"line {e.lineno}: {e.msg}"
        self.inline_rules: Dict[int, _InlineRule] = {}
        for i, line in enumerate(self.lines, 1):
            m = _INLINE_RE.search(line)
            if not m:
                continue
            ids = tuple(s.strip() for s in m.group("ids").split(","))
            why = (m.group("why") or "").strip()
            comment_only = line.strip().startswith("#")
            self.inline_rules[i] = _InlineRule(ids, why, comment_only)

    def suppression_for(self, finding: Finding) -> Optional[_InlineRule]:
        """Inline rule covering ``finding``, if any (same line, or a
        comment-only rule on the line above)."""
        rule = self.inline_rules.get(finding.line)
        if rule and finding.pass_id in rule.ids:
            return rule
        above = self.inline_rules.get(finding.line - 1)
        if above and above.comment_only and finding.pass_id in above.ids:
            return above
        return None


class AnalysisPass:
    """Base pass: subclasses set ``pass_ids``/``name`` and implement
    ``check``; cross-file passes also implement ``finish``."""

    pass_ids: Tuple[str, ...] = ()
    name: str = ""
    description: str = ""

    def applies_to(self, rel: str) -> bool:
        return True

    def check(self, sf: SourceFile) -> Iterable[Finding]:
        return ()

    def finish(self) -> Iterable[Finding]:
        """Called once after every file was checked (cross-file findings)."""
        return ()


def default_passes() -> List[AnalysisPass]:
    # local import: passes import framework types, avoid the cycle
    from . import concurrency, hygiene, jaxcompat, purity, style

    return [
        style.StylePass(),
        concurrency.ConcurrencyPass(),
        jaxcompat.JaxCompatPass(),
        purity.DevicePurityPass(),
        hygiene.HygienePass(),
    ]


def iter_repo_files(root: Path,
                    paths: Optional[Sequence[Path]] = None) -> List[Path]:
    """The analyzed file set: ``*.py`` under CHECKED_DIRS (or under the
    explicit ``paths``), __pycache__ excluded, sorted for determinism."""
    files: List[Path] = []
    if paths:
        for p in paths:
            p = p if p.is_absolute() else root / p
            if p.is_dir():
                files.extend(sorted(p.rglob("*.py")))
            elif p.suffix == ".py":
                files.append(p)
    else:
        for d in CHECKED_DIRS:
            base = root / d
            if base.is_dir():
                files.extend(sorted(base.rglob("*.py")))
    return [f for f in files if "__pycache__" not in f.parts]


def _load_file_rules(root: Path) -> Tuple[Dict[Tuple[str, str], str],
                                          List[Finding]]:
    """Parse the file-scope suppression list. Returns
    ({(rel_path, pass_id): justification}, findings-for-bad-rules)."""
    rules: Dict[Tuple[str, str], str] = {}
    findings: List[Finding] = []
    sup_path = root / SUPPRESSION_FILE
    if not sup_path.is_file():
        return rules, findings
    rel_sup = SUPPRESSION_FILE.as_posix()
    for i, line in enumerate(sup_path.read_text().split("\n"), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _FILE_RULE_RE.match(line)
        if not m or not m.group("why").strip():
            findings.append(Finding(
                rel_sup, i, "SUP1",
                "suppression rule needs '<path>: <PASS-ID>: <justification>'"
                f" (got: {line!r})"))
            continue
        rules[(m.group("path").strip(), m.group("id"))] = \
            m.group("why").strip()
    return rules, findings


def _apply_suppressions(findings: List[Finding],
                        sources: Dict[str, SourceFile],
                        file_rules: Dict[Tuple[str, str], str]) -> List[Finding]:
    out: List[Finding] = []
    for f in findings:
        if f.pass_id == "SUP1":     # a bad suppression can't suppress itself
            out.append(f)
            continue
        why = file_rules.get((f.path, f.pass_id))
        if why is not None:
            f.suppressed, f.justification = True, why
            out.append(f)
            continue
        sf = sources.get(f.path)
        rule = sf.suppression_for(f) if sf else None
        if rule is not None:
            if rule.justification:
                f.suppressed, f.justification = True, rule.justification
            # else: the SUP1 emitted for that rule keeps CI red
        out.append(f)
    return out


def _check_inline_rules(sf: SourceFile) -> List[Finding]:
    return [
        Finding(sf.rel, line, "SUP1",
                "suppression missing justification "
                "(use '# analysis: allow <ID> -- <why>')")
        for line, rule in sf.inline_rules.items()
        if not rule.justification
    ]


def run_analysis(root: Path,
                 paths: Optional[Sequence[Path]] = None,
                 passes: Optional[Sequence[AnalysisPass]] = None,
                 ) -> Tuple[List[Finding], int]:
    """Walk the tree once, dispatch every pass, apply suppressions.

    Returns (findings, n_files); ``findings`` includes suppressed ones
    (marked) so ``--json`` consumers can diff the full picture.
    """
    root = Path(root)
    passes = list(passes) if passes is not None else default_passes()
    file_rules, findings = _load_file_rules(root)
    sources: Dict[str, SourceFile] = {}
    n_files = 0
    for path in iter_repo_files(root, paths):
        n_files += 1
        rel = path.relative_to(root).as_posix()
        try:
            text = path.read_text(encoding="utf-8")
        except UnicodeDecodeError:
            findings.append(Finding(rel, 1, "E001", "not valid utf-8"))
            continue
        sf = SourceFile(rel, text)
        sources[rel] = sf
        findings.extend(_check_inline_rules(sf))
        if sf.parse_error is not None and rel.startswith("mmlspark_tpu/"):
            findings.append(Finding(
                rel, 1, "E001", f"syntax error: {sf.parse_error}"))
        for p in passes:
            if p.applies_to(rel):
                findings.extend(p.check(sf))
    for p in passes:
        findings.extend(p.finish())
    findings = _apply_suppressions(findings, sources, file_rules)
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id))
    return findings, n_files


def analyze_source(text: str, rel: str = "mmlspark_tpu/_snippet.py",
                   passes: Optional[Sequence[AnalysisPass]] = None,
                   ) -> List[Finding]:
    """Analyze one in-memory snippet (fixture tests). ``rel`` picks which
    passes apply (their ``applies_to`` sees it as the repo-relative path)."""
    passes = list(passes) if passes is not None else default_passes()
    sf = SourceFile(rel, text)
    findings = _check_inline_rules(sf)
    if sf.parse_error is not None:
        findings.append(Finding(rel, 1, "E001",
                                f"syntax error: {sf.parse_error}"))
    for p in passes:
        if p.applies_to(rel):
            findings.extend(p.check(sf))
    for p in passes:
        findings.extend(p.finish())
    findings = _apply_suppressions(findings, {rel: sf}, {})
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id))
    return findings
