"""J001 — version-gated jax APIs must route through the compat shim.

This container runs jax 0.4.37: ``jax.shard_map``, ``jax.sharding.AxisType``
and ``jax.lax.pcast``/``pvary`` do not exist, and ``jax.experimental.
shard_map`` moved in later versions. ``parallel/mesh.py`` is the one place
allowed to touch these names (``shard_map_compat``, the getattr-gated
AxisType handling); everywhere else a direct reference is a latent
ImportError/AttributeError on exactly the hardware we target.

What counts as a direct reference (AST-level, so comments/docstrings and
``getattr(obj, "name", default)``/``hasattr(obj, "name")`` probes — which
are themselves gates — never trigger):

  - an attribute access ``X.shard_map`` / ``jax.lax.pcast`` / ...
  - ``from jax.experimental.shard_map import shard_map`` (or importing any
    gated name from a jax module)
  - ``import jax.experimental.shard_map``

A reference that is itself behind a ``hasattr`` check is still flagged —
suppress it with a justification saying so (the suppression documents the
gate for the next reader).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set, Tuple

from .framework import AnalysisPass, Finding, SourceFile

GATED_NAMES = ("shard_map", "AxisType", "pcast", "pvary")
SHIM_MODULE = "mmlspark_tpu/parallel/mesh.py"
_HINT = "route through parallel/mesh.py compat helpers (jax 0.4.37)"


class JaxCompatPass(AnalysisPass):
    pass_ids = ("J001",)
    name = "jax-compat"
    description = ("direct references to version-gated jax APIs "
                   f"({', '.join(GATED_NAMES)}) outside the compat shim")

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("mmlspark_tpu/") and rel != SHIM_MODULE

    def check(self, sf: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        if sf.tree is None:
            return findings
        seen: Set[Tuple[int, str]] = set()

        def add(line: int, what: str, detail: str) -> None:
            if (line, what) in seen:
                return
            seen.add((line, what))
            findings.append(Finding(
                sf.rel, line, "J001",
                f"direct reference to version-gated jax API {detail} — "
                f"{_HINT}"))

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Attribute) and node.attr in GATED_NAMES:
                add(node.lineno, node.attr, f"'.{node.attr}'")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if "shard_map" in mod:
                    add(node.lineno, mod, f"module '{mod}'")
                elif mod == "jax" or mod.startswith("jax."):
                    for alias in node.names:
                        if alias.name in GATED_NAMES:
                            add(node.lineno, alias.name,
                                f"'{mod}.{alias.name}'")
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if "shard_map" in alias.name:
                        add(node.lineno, alias.name,
                            f"module '{alias.name}'")
        return findings
