"""H0xx — API hygiene: bare asserts in runtime code, metric-name rules.

  H001  ``assert`` in library runtime code. Asserts are stripped under
        ``python -O``, so validation written as an assert silently stops
        validating in optimized deployments — the PR-1/PR-3 audits converted
        these by hand; this pass keeps them converted. ``mmlspark_tpu/
        testing/`` is exempt by rule (test-support code, not runtime).

  H002  metric names registered on the MetricsRegistry (``.counter()`` /
        ``.gauge()`` / ``.histogram()`` with a literal name) must follow
        docs/observability.md: prefix ``mmlspark_``, lowercase
        ``[a-z0-9_]``, and monotonic counters end ``_total``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List

from .framework import AnalysisPass, Finding, SourceFile

_METRIC_METHODS = {"counter", "gauge", "histogram"}
_METRIC_NAME = re.compile(r"^mmlspark_[a-z0-9_]*[a-z0-9]$")


class HygienePass(AnalysisPass):
    pass_ids = ("H001", "H002")
    name = "api-hygiene"
    description = ("bare assert in runtime library code; mmlspark_* metric "
                   "name conformance (docs/observability.md)")

    def applies_to(self, rel: str) -> bool:
        return rel.startswith("mmlspark_tpu/") and \
            not rel.startswith("mmlspark_tpu/testing/")

    def check(self, sf: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        if sf.tree is None:
            return findings
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assert):
                findings.append(Finding(
                    sf.rel, node.lineno, "H001",
                    "bare assert in runtime code (stripped under "
                    "'python -O') — raise ValueError/RuntimeError instead"))
            elif isinstance(node, ast.Call):
                findings.extend(self._metric_findings(sf, node))
        return findings

    def _metric_findings(self, sf: SourceFile,
                         node: ast.Call) -> List[Finding]:
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in _METRIC_METHODS):
            return []
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            return []
        name = node.args[0].value
        out: List[Finding] = []
        if not _METRIC_NAME.match(name):
            out.append(Finding(
                sf.rel, node.lineno, "H002",
                f"metric name '{name}' must match 'mmlspark_[a-z0-9_]+' "
                f"(docs/observability.md naming conventions)"))
        elif func.attr == "counter" and not name.endswith("_total"):
            out.append(Finding(
                sf.rel, node.lineno, "H002",
                f"counter '{name}' must end '_total' (monotonic-count "
                f"convention, docs/observability.md)"))
        return out
