"""Hyperparameter distributions and sampling spaces (automl/ParamSpace.scala)."""

from __future__ import annotations

import itertools
from typing import Any, Dict, Iterator, List, Sequence, Tuple

import numpy as np


class Dist:
    """A sampling distribution over one hyperparameter."""

    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError

    def grid_values(self) -> List[Any]:
        raise NotImplementedError


class RangeHyperParam(Dist):
    """Uniform over [lo, hi]; integer-valued when both ends are ints
    (RangeHyperParam in ParamSpace.scala)."""

    def __init__(self, lo, hi, seed: int = 0):
        self.lo, self.hi = lo, hi
        self.is_int = isinstance(lo, int) and isinstance(hi, int)

    def sample(self, rng):
        if self.is_int:
            return int(rng.integers(self.lo, self.hi + 1))
        return float(rng.uniform(self.lo, self.hi))

    def grid_values(self, n: int = 3) -> List[Any]:
        if self.is_int:
            return sorted({int(v) for v in np.linspace(self.lo, self.hi, n)})
        return [float(v) for v in np.linspace(self.lo, self.hi, n)]


class DiscreteHyperParam(Dist):
    """Uniform over an explicit value list (DiscreteHyperParam)."""

    def __init__(self, values: Sequence[Any], seed: int = 0):
        self.values = list(values)

    def sample(self, rng):
        return self.values[int(rng.integers(len(self.values)))]

    def grid_values(self) -> List[Any]:
        return list(self.values)


class HyperparamBuilder:
    """Collects (estimator, param-name) -> Dist entries
    (automl/HyperparamBuilder + the Python overlay HyperparamBuilder.py)."""

    def __init__(self):
        self._entries: List[Tuple[Any, str, Dist]] = []

    def add_hyperparam(self, estimator, param_name: str, dist: Dist
                       ) -> "HyperparamBuilder":
        estimator.param(param_name)  # validate it exists
        self._entries.append((estimator, param_name, dist))
        return self

    def build(self) -> List[Tuple[Any, str, Dist]]:
        return list(self._entries)


class ParamSpace:
    """Random sampling space: infinite iterator of param settings."""

    def __init__(self, entries: List[Tuple[Any, str, Dist]], seed: int = 0):
        self.entries = entries
        self.seed = seed

    def param_maps(self) -> Iterator[List[Tuple[Any, str, Any]]]:
        rng = np.random.default_rng(self.seed)
        while True:
            yield [(est, name, dist.sample(rng)) for est, name, dist in self.entries]


class GridSpace:
    """Exhaustive cartesian grid over each Dist's grid values."""

    def __init__(self, entries: List[Tuple[Any, str, Dist]]):
        self.entries = entries

    def param_maps(self) -> Iterator[List[Tuple[Any, str, Any]]]:
        grids = [d.grid_values() for _, _, d in self.entries]
        for combo in itertools.product(*grids):
            yield [(est, name, v)
                   for (est, name, _), v in zip(self.entries, combo)]

    def space_size(self) -> int:
        out = 1
        for _, _, d in self.entries:
            out *= len(d.grid_values())
        return out
