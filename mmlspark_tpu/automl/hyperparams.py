"""Default hyperparameter search ranges per learner family
(reference automl/DefaultHyperparams.scala)."""

from __future__ import annotations

from typing import List, Tuple

from .params import DiscreteHyperParam, Dist, RangeHyperParam


class DefaultHyperparams:
    @staticmethod
    def lightgbm_classifier() -> List[Tuple[str, Dist]]:
        return [
            ("numLeaves", DiscreteHyperParam([7, 15, 31, 63])),
            ("numIterations", DiscreteHyperParam([25, 50, 100])),
            ("learningRate", RangeHyperParam(0.05, 0.3)),
            ("minDataInLeaf", DiscreteHyperParam([5, 10, 20])),
            ("baggingFraction", RangeHyperParam(0.7, 1.0)),
        ]

    @staticmethod
    def lightgbm_regressor() -> List[Tuple[str, Dist]]:
        return DefaultHyperparams.lightgbm_classifier()

    @staticmethod
    def vw_classifier() -> List[Tuple[str, Dist]]:
        return [
            ("learningRate", RangeHyperParam(0.05, 1.0)),
            ("numPasses", DiscreteHyperParam([1, 3, 5, 10])),
            ("l2", DiscreteHyperParam([0.0, 1e-6, 1e-4])),
        ]

    @staticmethod
    def for_estimator(estimator) -> List[Tuple[str, Dist]]:
        name = type(estimator).__name__
        if "LightGBM" in name and "Regressor" in name:
            return DefaultHyperparams.lightgbm_regressor()
        if "LightGBM" in name:
            return DefaultHyperparams.lightgbm_classifier()
        if "VowpalWabbit" in name:
            return DefaultHyperparams.vw_classifier()
        return [(n, DiscreteHyperParam([p.default]))
                for n, p in estimator.params().items()
                if p.default is not None and isinstance(p.default, (int, float))
                and not isinstance(p.default, bool)][:3]
