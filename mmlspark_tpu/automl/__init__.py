"""AutoML: hyperparameter tuning and model selection (reference automl/ package).

TuneHyperparameters (k-fold CV x random/grid sweep, round-robin over estimators,
parallel via thread pool — automl/TuneHyperparameters.scala:130-203),
HyperparamBuilder/ParamSpace/GridSpace (automl/ParamSpace.scala),
DefaultHyperparams per-learner ranges, FindBestModel
(automl/FindBestModel.scala:55-150), EvaluationUtils metric dispatch.
"""

from .params import (
    DiscreteHyperParam,
    GridSpace,
    HyperparamBuilder,
    ParamSpace,
    RangeHyperParam,
)
from .hyperparams import DefaultHyperparams
from .tuning import (
    BestModel,
    FindBestModel,
    MetricEvaluator,
    TuneHyperparameters,
    TuneHyperparametersModel,
)

__all__ = [
    "BestModel", "DefaultHyperparams", "DiscreteHyperParam", "FindBestModel",
    "GridSpace", "HyperparamBuilder", "MetricEvaluator", "ParamSpace",
    "RangeHyperParam", "TuneHyperparameters", "TuneHyperparametersModel",
]
