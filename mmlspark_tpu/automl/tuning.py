"""Hyperparameter tuning and model selection.

Reference: automl/TuneHyperparameters.scala:130-203 — k-fold CV over sampled
param maps, round-robin across multiple estimators, futures-parallel;
automl/FindBestModel.scala:55-150 — evaluate fitted models on one dataset and
keep the best; automl/EvaluationUtils.scala — metric name -> ordering.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasEvaluationMetric, HasLabelCol, Param
from ..core.pipeline import Estimator, Evaluator, Model
from ..train.metrics import auc_score, classification_metrics, regression_metrics
from .params import GridSpace, ParamSpace

_HIGHER_BETTER = {"accuracy", "precision", "recall", "AUC", "R^2"}
_LOWER_BETTER = {"mean_squared_error", "root_mean_squared_error",
                 "mean_absolute_error", "log_loss"}


def _trial_instruments():
    """Per-candidate trial instruments on the process-default registry
    (obs/metrics.py), created lazily INSIDE fit so the
    ``mmlspark_automl_trial_*`` families exist only once tuning has
    actually run (the absent-when-unused exposition contract). Returns
    None when obs is unavailable — tuning never depends on it."""
    try:
        from ..obs.metrics import default_registry

        reg = default_registry()
        return (
            reg.histogram(
                "mmlspark_automl_trial_seconds",
                "wall seconds per tuning candidate (k folds: fit + score)",
                ("estimator",)),
            reg.gauge(
                "mmlspark_automl_trial_metric",
                "last candidate's cross-validated eval metric",
                ("estimator", "metric")),
            reg.counter(
                "mmlspark_automl_trials_total",
                "tuning candidates evaluated", ("estimator",)),
        )
    except Exception:  # noqa: BLE001 — obs must never fail a fit
        return None


def metric_is_higher_better(metric: str) -> bool:
    if metric in _HIGHER_BETTER:
        return True
    if metric in _LOWER_BETTER:
        return False
    raise ValueError(f"Unknown metric {metric!r}")


class MetricEvaluator(Evaluator, HasLabelCol, HasEvaluationMetric):
    """Evaluate a scored DataFrame by metric name (EvaluationUtils parity).

    Understands the standardized scored columns (scored_labels /
    scored_probabilities) and plain prediction columns.
    """

    def __init__(self, metric: str = "accuracy", **kwargs):
        super().__init__(**kwargs)
        self.set("evaluationMetric", metric)

    def evaluate(self, df: DataFrame) -> float:
        metric = self.get_or_throw("evaluationMetric")
        data = df.collect()
        y = np.asarray(data[self.get_or_throw("labelCol")], dtype=np.float64)
        pred_col = "scored_labels" if "scored_labels" in df.schema else "prediction"
        if metric in ("accuracy", "precision", "recall", "AUC"):
            pred = np.asarray(data[pred_col], dtype=np.float64)
            scores = None
            for sc in ("scored_probabilities", "probability"):
                if sc in df.schema:
                    raw = data[sc]
                    scores = np.array([
                        float(np.asarray(v).reshape(-1)[-1]) if v is not None
                        and np.asarray(v).ndim > 0 else float(v)
                        for v in raw])
                    break
            m = classification_metrics(y, pred, scores)
            return float(m[metric])
        pred = np.asarray(data[pred_col], dtype=np.float64)
        return float(regression_metrics(y, pred)[metric])

    def is_larger_better(self) -> bool:
        return metric_is_higher_better(self.get_or_throw("evaluationMetric"))


class TuneHyperparameters(Estimator, HasEvaluationMetric):
    """CV-tune one or more estimators over a param space."""

    models = ComplexParam("models", "Estimators to tune (round-robin)")
    paramSpace = ComplexParam("paramSpace", "ParamSpace/GridSpace of settings")
    numFolds = Param("numFolds", "Cross-validation folds", 3,
                     lambda v: v >= 2, int)
    numRuns = Param("numRuns", "Sampled settings per estimator", 10,
                    lambda v: v > 0, int)
    parallelism = Param("parallelism", "Concurrent fits", 1, lambda v: v > 0, int)
    seed = Param("seed", "Fold-split seed", 0, ptype=int)
    labelCol = Param("labelCol", "Label column for evaluation", "label", ptype=str)

    def fit(self, df: DataFrame) -> "TuneHyperparametersModel":
        estimators = self.get_or_throw("models")
        if not isinstance(estimators, (list, tuple)):
            estimators = [estimators]
        space = self.get_or_throw("paramSpace")
        metric = self.get_or_throw("evaluationMetric")
        evaluator = MetricEvaluator(metric, labelCol=self.get("labelCol"))
        higher = evaluator.is_larger_better()
        n_folds = self.get("numFolds")
        n_runs = self.get("numRuns")

        # pre-split folds once
        folds = df.random_split([1.0] * n_folds, seed=self.get("seed"))

        settings: List[List[Tuple[Any, str, Any]]] = []
        gen = space.param_maps()
        if isinstance(space, GridSpace):
            settings = list(gen)
        else:
            for _ in range(n_runs):
                settings.append(next(gen))

        # round-robin: every estimator tries every sampled setting's values that
        # belong to it (settings may bind params to specific estimators)
        candidates: List[Tuple[Any, Dict[str, Any]]] = []
        for est in estimators:
            for setting in settings:
                pmap = {name: v for (e, name, v) in setting
                        if e is est or e is None or type(e) is type(est)}
                candidates.append((est, pmap))

        instruments = _trial_instruments()

        def run_candidate(args):
            est, pmap = args
            t0 = time.perf_counter()
            vals = []
            for i in range(n_folds):
                train_parts = [folds[j] for j in range(n_folds) if j != i]
                train_df = train_parts[0]
                for t in train_parts[1:]:
                    train_df = train_df.union(t)
                stage = est.copy(pmap)
                model = stage.fit(train_df)
                scored = model.transform(folds[i])
                vals.append(evaluator.evaluate(scored))
            result = float(np.mean(vals))
            if instruments is not None:
                # per-candidate wall seconds + eval metric (H002 families,
                # absent while automl is unused — created above, not at
                # import); instruments are thread-safe under parallelism
                try:
                    wall_h, metric_g, trials_c = instruments
                    name = type(est).__name__
                    wall_h.labels(estimator=name).observe(
                        time.perf_counter() - t0)
                    metric_g.labels(estimator=name, metric=metric).set(
                        result)
                    trials_c.labels(estimator=name).inc()
                except Exception:  # noqa: BLE001 — obs never fails a fit
                    pass
            return result

        par = self.get("parallelism")
        if par > 1:
            with ThreadPoolExecutor(max_workers=par) as pool:
                results = list(pool.map(run_candidate, candidates))
        else:
            results = [run_candidate(c) for c in candidates]

        best_i = int(np.argmax(results) if higher else np.argmin(results))
        best_est, best_pmap = candidates[best_i]
        best_model = best_est.copy(best_pmap).fit(df)
        return TuneHyperparametersModel(
            bestModel=best_model, bestMetric=float(results[best_i]),
            bestParams=dict(best_pmap),
            allMetrics=[float(r) for r in results])


class TuneHyperparametersModel(Model):
    bestModel = ComplexParam("bestModel", "Winning fitted model")
    bestMetric = Param("bestMetric", "Winning CV metric", None, ptype=float)
    bestParams = Param("bestParams", "Winning param values", None, ptype=dict)
    allMetrics = Param("allMetrics", "Every candidate's CV metric", None, ptype=list)

    def transform(self, df: DataFrame) -> DataFrame:
        return self.get_or_throw("bestModel").transform(df)

    def get_best_model_info(self) -> str:
        return f"params={self.get('bestParams')} metric={self.get('bestMetric')}"


class BestModel(Model):
    """Product of FindBestModel (automl/FindBestModel.scala)."""

    bestModel = ComplexParam("bestModel", "Winning fitted model")
    bestScoredDataset = ComplexParam("bestScoredDataset", "Winner's scored output")
    allModelMetrics = ComplexParam("allModelMetrics", "Per-model metrics DataFrame")
    bestMetric = Param("bestMetric", "Winning metric value", None, ptype=float)

    def transform(self, df: DataFrame) -> DataFrame:
        return self.get_or_throw("bestModel").transform(df)

    def get_evaluation_results(self) -> DataFrame:
        return self.get_or_throw("allModelMetrics")


class FindBestModel(Estimator, HasEvaluationMetric):
    """Evaluate already-fitted models on one dataset; keep the best."""

    models = ComplexParam("models", "Fitted models to compare")
    labelCol = Param("labelCol", "Label column", "label", ptype=str)

    def fit(self, df: DataFrame) -> BestModel:
        models = self.get_or_throw("models")
        metric = self.get_or_throw("evaluationMetric")
        evaluator = MetricEvaluator(metric, labelCol=self.get("labelCol"))
        higher = evaluator.is_larger_better()
        rows = []
        scores = []
        scored_frames = []
        for m in models:
            scored = m.transform(df)
            val = evaluator.evaluate(scored)
            scores.append(val)
            scored_frames.append(scored)
            rows.append({"model": type(m).__name__, metric: val})
        best_i = int(np.argmax(scores) if higher else np.argmin(scores))
        return BestModel(
            bestModel=models[best_i],
            bestScoredDataset=scored_frames[best_i],
            allModelMetrics=DataFrame.from_rows(rows),
            bestMetric=float(scores[best_i]))
