"""PowerBI writer (reference io/powerbi/PowerBIWriter.scala:1-114): stream
DataFrame rows to a PowerBI push-dataset REST endpoint in JSON batches."""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame
from .http import HTTPRequestData, send_with_retries


class PowerBIWriter:
    @staticmethod
    def write(df: DataFrame, url: str, batch_size: int = 1000,
              handler=None) -> int:
        """POST rows as {"rows": [...]} JSON batches; returns batches sent."""
        handler = handler or send_with_retries
        rows = df.rows()
        sent = 0
        for start in range(0, len(rows), batch_size):
            chunk = rows[start:start + batch_size]
            clean = [{k: (v.tolist() if isinstance(v, np.ndarray) else v)
                      for k, v in r.items()} for r in chunk]
            req = HTTPRequestData(
                url=url, method="POST",
                headers={"Content-Type": "application/json"},
                entity=json.dumps({"rows": clean}).encode("utf-8"))
            resp = handler(req)
            if resp.statusCode not in (200, 202):
                raise RuntimeError(
                    f"PowerBI write failed: {resp.statusCode} {resp.statusLine}")
            sent += 1
        return sent
