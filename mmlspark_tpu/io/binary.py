"""Binary I/O: whole-file reader + the zero-copy columnar wire frame.

Whole-file reader (reference io/binary/BinaryFileFormat.scala:1-251): reads a
directory tree into a DataFrame of {path, bytes} rows with recursive glob,
extension filtering, sampling, and zip inspection — partitioned for
downstream parallel decode.

Wire frame (``encode_frame`` / ``decode_frame``): the serving stack's binary
request format, negotiated via Content-Type ``application/x-mmlspark-frame``.
A frame is a length-prefixed header (magic + version + per-column
name/dtype/shape table) followed by the columns' raw payload bytes — no JSON,
no base64, so a uint8 image ships at 1x instead of the 4/3x base64-JSON tax,
and ``decode_frame`` returns numpy VIEWS over the request buffer (zero-copy).
The first copy on the ingest path is either the batch stack
(parallel/ingest.rows_to_batch) or — on the slot-staging path — the direct
deposit into a pre-allocated H2D staging slot:
``decode_frame(buf, out=...)`` / ``deposit_frame`` validate the frame fully,
check every destination (dtype/shape/contiguity/writeability), and only then
write payload bytes straight into the slot, so a hostile frame raises
``FrameError`` before any slot byte changes (all-or-nothing).

Frame layout (all integers little-endian; docs/serving.md has the diagram):

    0..3    magic  b"MMSF"
    4       version u8 (= 1)
    5       flags u8 (reserved, 0)
    6       ncols u8 (1..MAX_FRAME_COLS)
    7..14   total_len u64  — whole frame, magic through last payload byte
    15..16  header_len u16 — column-table bytes (bounded: <= MAX_HEADER_LEN)
    17..    column table, ncols entries:
              name_len u8, name utf-8 bytes,
              dtype u8 (DTYPE_CODES), ndim u8 (0..MAX_FRAME_NDIM),
              dims u32 x ndim, payload_len u32
    then the payloads, concatenated in column order.

Every length field is validated against the actual buffer before any view is
built — a hostile length can only produce a ``FrameError``, never an
allocation sized by the attacker (the decoder allocates nothing but views).
"""

from __future__ import annotations

import fnmatch
import os
import struct
import zipfile
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from ..core.dataframe import DataFrame
from ..core.schema import ColType, Schema


def _walk(path: str, recursive: bool, pattern: Optional[str]) -> List[str]:
    out: List[str] = []
    if os.path.isfile(path):
        return [path]
    for root, dirs, files in os.walk(path):
        for f in sorted(files):
            if pattern and not fnmatch.fnmatch(f, pattern):
                continue
            out.append(os.path.join(root, f))
        if not recursive:
            break
        dirs.sort()
    return out


def read_binary_files(path: str, recursive: bool = True,
                      sample_ratio: float = 1.0, inspect_zip: bool = True,
                      seed: int = 0, num_partitions: int = 1,
                      pattern: Optional[str] = None) -> DataFrame:
    """Directory/file -> DataFrame[{path, bytes}] (BinaryFileReader parity)."""
    files = _walk(path, recursive, pattern)
    rng = np.random.default_rng(seed)
    if sample_ratio < 1.0:
        files = [f for f in files if rng.random() < sample_ratio]
    paths: List[str] = []
    blobs: List[bytes] = []
    for f in files:
        if inspect_zip and zipfile.is_zipfile(f):
            with zipfile.ZipFile(f) as z:
                for name in z.namelist():
                    if name.endswith("/"):
                        continue
                    if pattern and not fnmatch.fnmatch(os.path.basename(name),
                                                       pattern):
                        continue
                    if sample_ratio < 1.0 and rng.random() >= sample_ratio:
                        continue
                    paths.append(f"{f}/{name}")
                    blobs.append(z.read(name))
        else:
            with open(f, "rb") as fh:
                paths.append(f)
                blobs.append(fh.read())
    path_col = np.empty(len(paths), dtype=object)
    blob_col = np.empty(len(blobs), dtype=object)
    for i, (p, b) in enumerate(zip(paths, blobs)):
        path_col[i] = p
        blob_col[i] = b
    df = DataFrame([{"path": path_col, "bytes": blob_col}])
    return df.repartition(num_partitions) if num_partitions > 1 else df


class BinaryFileReader:
    """Object-style facade mirroring the reference reader options API."""

    def __init__(self):
        self._recursive = True
        self._sample_ratio = 1.0
        self._inspect_zip = True
        self._seed = 0
        self._pattern: Optional[str] = None
        self._partitions = 1

    def option(self, key: str, value) -> "BinaryFileReader":
        key = key.lower()
        if key == "recursive":
            self._recursive = bool(value)
        elif key in ("sampleratio", "subsample"):
            self._sample_ratio = float(value)
        elif key == "inspectzip":
            self._inspect_zip = bool(value)
        elif key == "seed":
            self._seed = int(value)
        elif key in ("pathfilter", "pattern"):
            self._pattern = str(value)
        elif key in ("numpartitions", "partitions"):
            self._partitions = int(value)
        else:
            raise KeyError(f"Unknown binary reader option {key!r}")
        return self

    def load(self, path: str) -> DataFrame:
        return read_binary_files(
            path, self._recursive, self._sample_ratio, self._inspect_zip,
            self._seed, self._partitions, self._pattern)


# ---------------------------------------------------------------------------
# Zero-copy columnar wire frame
# ---------------------------------------------------------------------------

#: Content-Type the serving stack negotiates the binary wire on
FRAME_CONTENT_TYPE = "application/x-mmlspark-frame"
FRAME_MAGIC = b"MMSF"
FRAME_VERSION = 1

#: header bounds — enforced BEFORE any length field is trusted, so a hostile
#: frame can never trigger an attacker-sized allocation or column walk
MAX_FRAME_COLS = 64
MAX_FRAME_NDIM = 8
MAX_HEADER_LEN = 8192
MAX_NAME_LEN = 64
#: default cap on a whole frame (callers pass their own ``max_bytes``; HTTP
#: ingress uses the request body length, already bounded by admission)
MAX_FRAME_BYTES = 1 << 31

#: wire dtype codes <-> numpy (little-endian on the wire; native here — the
#: wire is LE and so is every supported host/TPU platform)
DTYPE_CODES: Dict[int, np.dtype] = {
    1: np.dtype(np.uint8), 2: np.dtype(np.int8),
    3: np.dtype(np.uint16), 4: np.dtype(np.int16),
    5: np.dtype(np.uint32), 6: np.dtype(np.int32),
    7: np.dtype(np.uint64), 8: np.dtype(np.int64),
    9: np.dtype(np.float16), 10: np.dtype(np.float32),
    11: np.dtype(np.float64), 12: np.dtype(np.bool_),
}
_DTYPE_TO_CODE = {dt: code for code, dt in DTYPE_CODES.items()}

_FIXED = struct.Struct("<4sBBBQH")  # magic, version, flags, ncols,
#                                     total_len, header_len


class FrameError(ValueError):
    """Malformed, truncated, oversized, or otherwise rejected wire frame."""


def is_frame(buf: Union[bytes, bytearray, memoryview]) -> bool:
    """Cheap magic sniff (used by the journal to pick the record variant)."""
    return len(buf) >= 4 and bytes(buf[:4]) == FRAME_MAGIC


def encode_frame(columns: Dict[str, np.ndarray]) -> bytes:
    """Encode named arrays as one wire frame (column order preserved)."""
    if not columns:
        raise FrameError("frame needs at least one column")
    if len(columns) > MAX_FRAME_COLS:
        raise FrameError(f"too many columns ({len(columns)})")
    table = bytearray()
    payloads: List[bytes] = []
    for name, arr in columns.items():
        arr = np.asarray(arr)
        if not arr.flags["C_CONTIGUOUS"]:  # ascontiguousarray would also
            arr = np.ascontiguousarray(arr)  # promote 0-d to 1-d

        code = _DTYPE_TO_CODE.get(arr.dtype)
        if code is None:
            raise FrameError(f"unsupported dtype {arr.dtype} for {name!r}")
        nm = name.encode("utf-8")
        if not 1 <= len(nm) <= MAX_NAME_LEN:
            raise FrameError(f"bad column name {name!r}")
        if arr.ndim > MAX_FRAME_NDIM:
            raise FrameError(f"rank {arr.ndim} exceeds {MAX_FRAME_NDIM}")
        if arr.nbytes > 0xFFFFFFFF:
            raise FrameError(f"column {name!r} exceeds u32 payload bound")
        table += struct.pack(f"<B{len(nm)}sBB", len(nm), nm, code, arr.ndim)
        table += struct.pack(f"<{arr.ndim}I", *arr.shape)
        table += struct.pack("<I", arr.nbytes)
        payloads.append(arr.tobytes())
    if len(table) > MAX_HEADER_LEN:
        raise FrameError("column table exceeds MAX_HEADER_LEN")
    total = _FIXED.size + len(table) + sum(len(p) for p in payloads)
    head = _FIXED.pack(FRAME_MAGIC, FRAME_VERSION, 0, len(columns),
                       total, len(table))
    return b"".join([head, bytes(table)] + payloads)


def frame_info(buf: Union[bytes, bytearray, memoryview],
               max_bytes: int = MAX_FRAME_BYTES) -> Dict[str, object]:
    """Validate a frame's bounded header WITHOUT touching the payloads:
    returns {version, total_len, columns: [(name, dtype, shape)]}. The
    serving ingress calls this on arrival so malformed frames 400 before a
    batch slot, journal write, or transform is spent on them."""
    mv = memoryview(buf)
    if len(mv) < _FIXED.size:
        raise FrameError(f"truncated frame header ({len(mv)} bytes)")
    magic, version, _flags, ncols, total, hlen = _FIXED.unpack(
        mv[:_FIXED.size])
    if magic != FRAME_MAGIC:
        raise FrameError("bad magic")
    if version != FRAME_VERSION:
        raise FrameError(f"unsupported frame version {version}")
    if not 1 <= ncols <= MAX_FRAME_COLS:
        raise FrameError(f"bad column count {ncols}")
    if hlen > MAX_HEADER_LEN:
        raise FrameError(f"header length {hlen} exceeds bound")
    if total > max_bytes:
        raise FrameError(f"frame length {total} exceeds cap {max_bytes}")
    if total != len(mv):
        raise FrameError(
            f"frame length field {total} != buffer size {len(mv)}")
    if _FIXED.size + hlen > len(mv):
        raise FrameError("column table overruns buffer")
    table = mv[_FIXED.size:_FIXED.size + hlen]
    cols: List[Tuple[str, np.dtype, Tuple[int, ...], int]] = []
    off = 0
    payload_total = 0
    for _ in range(ncols):
        if off + 1 > len(table):
            raise FrameError("truncated column table")
        nlen = table[off]
        off += 1
        if not 1 <= nlen <= MAX_NAME_LEN or off + nlen + 2 > len(table):
            raise FrameError("bad column name length")
        name = bytes(table[off:off + nlen]).decode("utf-8", errors="strict")
        off += nlen
        code, ndim = table[off], table[off + 1]
        off += 2
        dt = DTYPE_CODES.get(code)
        if dt is None:
            raise FrameError(f"unknown dtype code {code}")
        if ndim > MAX_FRAME_NDIM or off + 4 * ndim + 4 > len(table):
            raise FrameError("bad column rank")
        shape = struct.unpack_from(f"<{ndim}I", table, off)
        off += 4 * ndim
        (plen,) = struct.unpack_from("<I", table, off)
        off += 4
        nelem = 1
        for d in shape:
            nelem *= d
        if plen != nelem * dt.itemsize:
            raise FrameError(
                f"column {name!r}: payload {plen} != shape {shape} x "
                f"{dt.itemsize}")
        cols.append((name, dt, tuple(int(d) for d in shape), plen))
        payload_total += plen
    if off != len(table):
        raise FrameError("column table has trailing bytes")
    if _FIXED.size + hlen + payload_total != total:
        raise FrameError("payload lengths do not sum to frame length")
    return {"version": version, "total_len": int(total),
            "columns": [(n, d, s) for n, d, s, _ in cols],
            "_spans": cols}


def _payload_offset(info: Dict[str, object]) -> int:
    """Byte offset of the first payload (fixed header + column table)."""
    return _FIXED.size + sum(
        1 + len(n.encode("utf-8")) + 2 + 4 * len(s) + 4
        for n, _, s in info["columns"])


def decode_frame(buf: Union[bytes, bytearray, memoryview],
                 max_bytes: int = MAX_FRAME_BYTES,
                 out: Optional[Dict[str, np.ndarray]] = None
                 ) -> Dict[str, np.ndarray]:
    """Frame bytes -> {name: ndarray}. The arrays are read-only VIEWS over
    ``buf`` (np.frombuffer — zero-copy); they stay valid as long as the
    caller keeps ``buf`` alive (the serving path keeps the request body in
    the batch rows, so views outlive the transform).

    ``out``: the deposit path (``deposit_frame``) — payloads land directly
    in the caller's pre-allocated staging arrays instead of views."""
    if out is not None:
        return deposit_frame(buf, out, max_bytes=max_bytes)
    info = frame_info(buf, max_bytes=max_bytes)
    mv = memoryview(buf)
    res: Dict[str, np.ndarray] = {}
    off = _payload_offset(info)
    for name, dt, shape, plen in info["_spans"]:
        arr = np.frombuffer(mv[off:off + plen], dtype=dt).reshape(shape)
        res[name] = arr
        off += plen
    return res


def deposit_frame(buf: Union[bytes, bytearray, memoryview],
                  out: Dict[str, np.ndarray],
                  max_bytes: int = MAX_FRAME_BYTES) -> Dict[str, np.ndarray]:
    """Socket-to-slot decode: copy each column's payload bytes DIRECTLY
    into a caller-owned staging destination (a pre-pinned TransferRing
    slot, parallel/ingest.py ``SlotPool``) — one memcpy per column, no
    intermediate views or allocations.

    Deposit contract (docs/ingest.md): the ENTIRE frame is validated
    (``frame_info``) and every destination checked — present, C-contiguous,
    writeable, exact dtype and shape — BEFORE the first byte is written.
    A hostile frame (bad magic/lengths, truncated or misaligned payloads)
    or a mismatched destination raises ``FrameError`` with every slot
    untouched; a half-deposited slot is impossible. Extra ``out`` entries
    the frame doesn't name are left as-is. Returns {name: destination}
    for the frame's columns."""
    info = frame_info(buf, max_bytes=max_bytes)
    mv = memoryview(buf)
    spans = info["_spans"]
    for name, dt, shape, _plen in spans:
        dst = out.get(name)
        if dst is None:
            raise FrameError(f"no staging destination for column {name!r}")
        if not isinstance(dst, np.ndarray):
            raise FrameError(
                f"staging destination for {name!r} is not an ndarray")
        if not dst.flags["C_CONTIGUOUS"] or not dst.flags["WRITEABLE"]:
            raise FrameError(
                f"staging destination for {name!r} must be C-contiguous "
                f"and writeable")
        if dst.dtype != dt:
            raise FrameError(
                f"column {name!r}: frame dtype {dt} != slot dtype "
                f"{dst.dtype}")
        if tuple(dst.shape) != shape:
            raise FrameError(
                f"column {name!r}: frame shape {shape} != slot shape "
                f"{tuple(dst.shape)}")
    off = _payload_offset(info)
    res: Dict[str, np.ndarray] = {}
    for name, dt, shape, plen in spans:
        dst = out[name]
        # raw byte copy through the buffer protocol: the one host copy on
        # the deposit path (socket buffer -> staging slot); 0-d slots go
        # through a 1-element view (memoryview.cast needs ndim >= 1)
        flat = dst if dst.ndim else dst.reshape(1)
        memoryview(flat).cast("B")[:] = mv[off:off + plen]
        res[name] = dst
        off += plen
    return res


# ---------------------------------------------------------------------------
# CSR columns on the wire (docs/sparse.md)
# ---------------------------------------------------------------------------

#: reserved sub-column suffixes a CSR triple ships under; a frame column
#: named ``{c}:indptr`` declares sparse column ``c`` and requires its three
#: siblings (``:width`` rides along so decode never guesses the feature
#: count from the data)
CSR_SUFFIXES = (":indptr", ":indices", ":values", ":width")


def encode_csr_columns(name: str, indptr: np.ndarray, indices: np.ndarray,
                       values: np.ndarray, width: int
                       ) -> Dict[str, np.ndarray]:
    """One host CSR column -> the four wire sub-columns ``encode_frame``
    ships (i32 indptr / i32 indices / f32 values / 0-d i32 width). The
    triple is validated before encoding — a malformed CSR never leaves the
    encoder, so every reject lives in one place (``validate_csr_triple``)."""
    cols = {
        f"{name}:indptr": np.ascontiguousarray(indptr, dtype=np.int32),
        f"{name}:indices": np.ascontiguousarray(indices, dtype=np.int32),
        f"{name}:values": np.ascontiguousarray(values, dtype=np.float32),
        f"{name}:width": np.asarray(int(width), dtype=np.int32),
    }
    validate_csr_triple(name, cols[f"{name}:indptr"],
                        cols[f"{name}:indices"], cols[f"{name}:values"],
                        int(width))
    return cols


def validate_csr_triple(name: str, indptr: np.ndarray, indices: np.ndarray,
                        values: np.ndarray, width: int,
                        rows: Optional[int] = None) -> None:
    """Reject a hostile CSR triple with ``FrameError`` (all-or-nothing:
    callers validate EVERY declared triple before materializing any).
    Checked: rank-1 i32 indptr anchored at 0, non-decreasing, closing
    exactly on len(indices) == len(values); every index in [0, width);
    positive width; the row count when the caller knows it."""
    if indptr.ndim != 1 or indices.ndim != 1 or values.ndim != 1:
        raise FrameError(f"sparse column {name!r}: CSR parts must be rank-1")
    if len(indptr) < 1:
        raise FrameError(f"sparse column {name!r}: empty indptr")
    if rows is not None and len(indptr) != int(rows) + 1:
        raise FrameError(
            f"sparse column {name!r}: indptr rows {len(indptr) - 1} != "
            f"frame rows {rows}")
    if int(width) <= 0:
        raise FrameError(f"sparse column {name!r}: width must be positive")
    ip = np.asarray(indptr, dtype=np.int64)
    if ip[0] != 0:
        raise FrameError(f"sparse column {name!r}: indptr[0] != 0")
    if len(ip) > 1 and np.any(np.diff(ip) < 0):
        raise FrameError(f"sparse column {name!r}: non-monotone indptr")
    if int(ip[-1]) != len(indices) or len(indices) != len(values):
        raise FrameError(
            f"sparse column {name!r}: indptr[-1] {int(ip[-1])} != nnz "
            f"{len(indices)}/{len(values)}")
    if len(indices) and (int(np.min(indices)) < 0
                         or int(np.max(indices)) >= int(width)):
        raise FrameError(
            f"sparse column {name!r}: index out of [0, {int(width)})")


def decode_csr_columns(columns: Dict[str, np.ndarray]
                       ) -> Dict[str, np.ndarray]:
    """Decoded frame columns -> ingest rows, materializing each declared
    CSR group as one object column of per-row ``{"indices", "values",
    "size"}`` dicts — the sparse-row form the whole host stack consumes
    (parallel/ingest.py, gbdt/sparse.py ``rows_to_csr``).

    All-or-nothing, like ``deposit_frame``: EVERY declared triple is
    validated (complete sibling set, ``validate_csr_triple``, equal row
    counts across groups and against any dense column) before the first
    row dict is built, so a hostile triple raises ``FrameError`` with
    nothing materialized. Dense columns pass through untouched; a frame
    with no ``:indptr`` columns returns byte-identical input."""
    bases = [c[:-len(":indptr")] for c in columns if c.endswith(":indptr")]
    if not bases:
        return columns
    rows: Optional[int] = None
    for c, v in columns.items():
        if any(c.endswith(s) for s in CSR_SUFFIXES):
            continue
        n = len(v) if np.ndim(v) else None
        if n is not None:
            if rows is not None and n != rows:
                raise FrameError("dense columns disagree on row count")
            rows = n
    triples: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray, int]] = {}
    for base in sorted(bases):
        parts = {}
        for suffix in CSR_SUFFIXES:
            part = columns.get(base + suffix)
            if part is None:
                raise FrameError(
                    f"sparse column {base!r}: missing {suffix} sibling")
            parts[suffix] = part
        if parts[":width"].ndim != 0:
            raise FrameError(f"sparse column {base!r}: width must be 0-d")
        width = int(parts[":width"])
        validate_csr_triple(base, parts[":indptr"], parts[":indices"],
                            parts[":values"], width, rows=rows)
        if rows is None:
            rows = len(parts[":indptr"]) - 1
        triples[base] = (np.asarray(parts[":indptr"], dtype=np.int64),
                         parts[":indices"], parts[":values"], width)
    out: Dict[str, np.ndarray] = {
        c: v for c, v in columns.items()
        if not any(c.endswith(s) for s in CSR_SUFFIXES)}
    for base, (ip, idx, val, width) in triples.items():
        col = np.empty(rows or 0, dtype=object)
        for i in range(rows or 0):
            lo, hi = int(ip[i]), int(ip[i + 1])
            col[i] = {"indices": np.asarray(idx[lo:hi], dtype=np.int64),
                      "values": np.asarray(val[lo:hi], dtype=np.float64),
                      "size": width}
        out[base] = col
    return out
