"""Whole-file binary reader (reference io/binary/BinaryFileFormat.scala:1-251).

Reads a directory tree into a DataFrame of {path, bytes} rows with recursive
glob, extension filtering, sampling, and zip inspection — partitioned for
downstream parallel decode.
"""

from __future__ import annotations

import fnmatch
import os
import zipfile
from typing import List, Optional, Tuple

import numpy as np

from ..core.dataframe import DataFrame
from ..core.schema import ColType, Schema


def _walk(path: str, recursive: bool, pattern: Optional[str]) -> List[str]:
    out: List[str] = []
    if os.path.isfile(path):
        return [path]
    for root, dirs, files in os.walk(path):
        for f in sorted(files):
            if pattern and not fnmatch.fnmatch(f, pattern):
                continue
            out.append(os.path.join(root, f))
        if not recursive:
            break
        dirs.sort()
    return out


def read_binary_files(path: str, recursive: bool = True,
                      sample_ratio: float = 1.0, inspect_zip: bool = True,
                      seed: int = 0, num_partitions: int = 1,
                      pattern: Optional[str] = None) -> DataFrame:
    """Directory/file -> DataFrame[{path, bytes}] (BinaryFileReader parity)."""
    files = _walk(path, recursive, pattern)
    rng = np.random.default_rng(seed)
    if sample_ratio < 1.0:
        files = [f for f in files if rng.random() < sample_ratio]
    paths: List[str] = []
    blobs: List[bytes] = []
    for f in files:
        if inspect_zip and zipfile.is_zipfile(f):
            with zipfile.ZipFile(f) as z:
                for name in z.namelist():
                    if name.endswith("/"):
                        continue
                    if pattern and not fnmatch.fnmatch(os.path.basename(name),
                                                       pattern):
                        continue
                    if sample_ratio < 1.0 and rng.random() >= sample_ratio:
                        continue
                    paths.append(f"{f}/{name}")
                    blobs.append(z.read(name))
        else:
            with open(f, "rb") as fh:
                paths.append(f)
                blobs.append(fh.read())
    path_col = np.empty(len(paths), dtype=object)
    blob_col = np.empty(len(blobs), dtype=object)
    for i, (p, b) in enumerate(zip(paths, blobs)):
        path_col[i] = p
        blob_col[i] = b
    df = DataFrame([{"path": path_col, "bytes": blob_col}])
    return df.repartition(num_partitions) if num_partitions > 1 else df


class BinaryFileReader:
    """Object-style facade mirroring the reference reader options API."""

    def __init__(self):
        self._recursive = True
        self._sample_ratio = 1.0
        self._inspect_zip = True
        self._seed = 0
        self._pattern: Optional[str] = None
        self._partitions = 1

    def option(self, key: str, value) -> "BinaryFileReader":
        key = key.lower()
        if key == "recursive":
            self._recursive = bool(value)
        elif key in ("sampleratio", "subsample"):
            self._sample_ratio = float(value)
        elif key == "inspectzip":
            self._inspect_zip = bool(value)
        elif key == "seed":
            self._seed = int(value)
        elif key in ("pathfilter", "pattern"):
            self._pattern = str(value)
        elif key in ("numpartitions", "partitions"):
            self._partitions = int(value)
        else:
            raise KeyError(f"Unknown binary reader option {key!r}")
        return self

    def load(self, path: str) -> DataFrame:
        return read_binary_files(
            path, self._recursive, self._sample_ratio, self._inspect_zip,
            self._seed, self._partitions, self._pattern)
