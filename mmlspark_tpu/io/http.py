"""HTTP-on-pipeline: typed request/response schema, clients, transformer stages.

Reference stack (io/http/):
  - HTTPSchema.scala:1-342           -> HTTPRequestData / HTTPResponseData
  - Clients.scala:1-63 + HTTPClients.scala:64-150 -> send_with_retries
    (status-aware retry incl. 429 Retry-After sleep)
  - HTTPTransformer.scala:79-129     -> HTTPTransformer (request col ->
    response col, shared client per partition, bounded concurrency)
  - SimpleHTTPTransformer.scala:1-166 + Parsers.scala:1-271 ->
    SimpleHTTPTransformer with JSON/Custom/String parsers + error column
  - SharedVariable.scala:1-65        -> SharedVariable / SharedSingleton
  - PartitionConsolidator.scala:19-132 -> PartitionConsolidator
"""

from __future__ import annotations

import dataclasses
import json
import random
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core import faults
from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasInputCol, HasOutputCol, Param
from ..core.pipeline import Transformer
from ..core.schema import Binding, ColType, Schema

# ---------------------------------------------------------------------------
# Schema (HTTPSchema.scala parity)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class HTTPRequestData:
    url: str
    method: str = "GET"
    headers: Optional[Dict[str, str]] = None
    entity: Optional[bytes] = None

    def to_row(self) -> Dict[str, Any]:
        return Binding.to_row(self)

    @staticmethod
    def from_row(row: Dict[str, Any]) -> "HTTPRequestData":
        return Binding.from_row(HTTPRequestData, row)


@dataclasses.dataclass
class HTTPResponseData:
    statusCode: int
    statusLine: str = ""
    entity: Optional[bytes] = None
    headers: Optional[Dict[str, str]] = None

    def to_row(self) -> Dict[str, Any]:
        return Binding.to_row(self)

    @staticmethod
    def from_row(row: Dict[str, Any]) -> "HTTPResponseData":
        return Binding.from_row(HTTPResponseData, row)


# ---------------------------------------------------------------------------
# Client with retries (HandlingUtils.sendWithRetries parity)
# ---------------------------------------------------------------------------


RETRYABLE_CODES = {403, 408, 429, 500, 502, 503, 504}

#: +/- jitter fraction applied to the legacy fixed backoff list (decorrelates
#: synchronized retry storms from many partitions hitting one rate-limited
#: host; a seeded RetryPolicy gives a deterministic stream instead)
_LEGACY_JITTER = 0.2


def send_request(req: HTTPRequestData, timeout: float = 60.0,
                 deadline: Optional[faults.Deadline] = None
                 ) -> HTTPResponseData:
    if deadline is not None:
        timeout = max(deadline.cap(timeout), 1e-3)
    r = urllib.request.Request(req.url, data=req.entity,
                               headers=req.headers or {},
                               method=req.method or "GET")
    try:
        faults.fire(faults.HTTP_SEND, url=req.url, method=req.method)
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return HTTPResponseData(
                statusCode=resp.status,
                statusLine=getattr(resp, "reason", "") or "",
                entity=resp.read(),
                headers=dict(resp.headers.items()))
    except urllib.error.HTTPError as e:
        return HTTPResponseData(statusCode=e.code, statusLine=str(e.reason),
                                entity=e.read() if e.fp else None,
                                headers=dict(e.headers.items()) if e.headers else {})
    except Exception as e:  # connection errors -> 0 status (retryable)
        return HTTPResponseData(statusCode=0, statusLine=str(e))


def parse_retry_after(value: Optional[str],
                      now: Optional[float] = None) -> Optional[float]:
    """Seconds to wait from a Retry-After header: numeric seconds OR an
    HTTP-date (RFC 9110 both forms). None when unparseable."""
    if not value:
        return None
    try:
        return max(0.0, float(value))
    except (TypeError, ValueError):
        pass
    from email.utils import parsedate_to_datetime

    try:
        dt = parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if dt is None:
        return None
    if dt.tzinfo is None:
        from datetime import timezone

        dt = dt.replace(tzinfo=timezone.utc)
    return max(0.0, dt.timestamp() - (time.time() if now is None else now))


def send_with_retries(req: HTTPRequestData, retry_backoffs_ms=(100, 500, 1000),
                      timeout: float = 60.0,
                      sleep_fn: Callable[[float], None] = time.sleep,
                      policy: Optional[faults.RetryPolicy] = None,
                      deadline: Optional[faults.Deadline] = None,
                      send: Optional[Callable] = None
                      ) -> HTTPResponseData:
    """Status-aware retry: retryable codes back off with jitter; 429/503
    honor Retry-After (numeric seconds or HTTP-date), and every honored wait
    is capped at the request deadline (io/http/HTTPClients.scala:73-117).

    ``policy``: a core.faults.RetryPolicy replacing the legacy fixed backoff
    list (seedable jitter, sleep budget). ``deadline``: when set, no sleep or
    socket timeout extends past it; once expired the last response returns
    as-is instead of retrying into a lost cause. ``send``: per-attempt
    transport override (``(req, timeout[, deadline]) -> HTTPResponseData``)
    so callers can inject an offline transport while keeping the full retry
    behavior.
    """
    rng = policy.make_rng() if policy is not None else random.Random()
    n_attempts = policy.max_retries if policy is not None \
        else len(retry_backoffs_ms)
    budget_left = policy.budget_s if policy is not None else None

    def _send():
        # the deadline arg is only threaded through when set: injected test
        # handlers replace send_request with (req, timeout) signatures
        if send is not None:
            return send(req, timeout) if deadline is None \
                else send(req, timeout, deadline)
        if deadline is None:
            return send_request(req, timeout)
        return send_request(req, timeout, deadline)

    resp = _send()
    for attempt in range(n_attempts):
        if resp.statusCode == 200 or resp.statusCode not in RETRYABLE_CODES | {0}:
            return resp
        if policy is not None:
            wait = policy.next_wait(attempt, rng)
        else:
            base = retry_backoffs_ms[attempt] / 1000.0
            wait = max(0.0, base * (1.0 + _LEGACY_JITTER * rng.uniform(-1, 1)))
        if resp.statusCode in (429, 503) and resp.headers:
            ra = parse_retry_after(
                resp.headers.get("Retry-After")
                or resp.headers.get("retry-after"))
            if ra is not None:
                wait = ra  # server-directed wait: exact, not jittered
        if budget_left is not None:
            if budget_left <= 0:
                return resp
            wait = min(wait, budget_left)
            budget_left -= wait
        if deadline is not None:
            remaining = deadline.remaining()
            if remaining <= 0:
                return resp
            wait = min(wait, remaining)  # cap the honored wait at the deadline
        sleep_fn(wait)
        resp = _send()
    return resp


# ---------------------------------------------------------------------------
# Shared per-process singletons (SharedVariable.scala parity)
# ---------------------------------------------------------------------------


class SharedVariable:
    """Lazily-initialized per-process singleton (one instance per holder)."""

    def __init__(self, factory: Callable[[], Any]):
        self._factory = factory
        self._value = None
        self._init = False
        self._lock = threading.Lock()

    def get(self) -> Any:
        if not self._init:
            with self._lock:
                if not self._init:
                    self._value = self._factory()
                    self._init = True
        return self._value


class SharedSingleton:
    """Process-wide keyed singletons."""

    _instances: Dict[str, Any] = {}
    _lock = threading.Lock()

    @classmethod
    def get_or_create(cls, key: str, factory: Callable[[], Any]) -> Any:
        if key not in cls._instances:
            with cls._lock:
                if key not in cls._instances:
                    cls._instances[key] = factory()
        return cls._instances[key]


# ---------------------------------------------------------------------------
# HTTPTransformer (request col -> response col)
# ---------------------------------------------------------------------------


class HTTPTransformer(Transformer, HasInputCol, HasOutputCol):
    """Each input row holds an HTTPRequestData (or its dict row form); output
    rows hold HTTPResponseData dicts (HTTPTransformer.scala:79-129)."""

    concurrency = Param("concurrency", "Concurrent requests per partition", 1,
                        lambda v: v > 0, int)
    timeout = Param("timeout", "Per-request timeout (s)", 60.0, ptype=float)
    handler = ComplexParam("handler", "Custom (request) -> response callable")

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        conc = self.get("concurrency")
        timeout = self.get("timeout")
        handler = self.get("handler") or (
            lambda r: send_with_retries(
                r, timeout=timeout,
                deadline=faults.deadline_from_headers(r.headers)))

        def fn(p):
            col = p[in_col]
            reqs = [None if v is None else
                    (v if isinstance(v, HTTPRequestData)
                     else HTTPRequestData.from_row(v)) for v in col]
            out = np.empty(len(reqs), dtype=object)

            def run(i_req):
                i, r = i_req
                return i, (None if r is None else handler(r))

            if conc > 1:
                with ThreadPoolExecutor(max_workers=conc) as pool:
                    for i, resp in pool.map(run, enumerate(reqs)):
                        out[i] = resp.to_row() if resp is not None else None
            else:
                for i, r in enumerate(reqs):
                    out[i] = handler(r).to_row() if r is not None else None
            return out

        return df.with_column(out_col, fn)


# ---------------------------------------------------------------------------
# Parsers (Parsers.scala parity)
# ---------------------------------------------------------------------------


class JSONInputParser:
    """Row dict -> POST request with JSON body (JSONInputParser)."""

    def __init__(self, url: str, headers: Optional[Dict[str, str]] = None,
                 method: str = "POST"):
        self.url = url
        self.headers = {"Content-Type": "application/json", **(headers or {})}
        self.method = method

    def parse(self, row: Dict[str, Any]) -> HTTPRequestData:
        clean = {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                 for k, v in row.items()}
        return HTTPRequestData(url=self.url, method=self.method,
                               headers=dict(self.headers),
                               entity=json.dumps(clean).encode("utf-8"))


class CustomInputParser:
    def __init__(self, fn: Callable[[Dict[str, Any]], HTTPRequestData]):
        self.fn = fn

    def parse(self, row: Dict[str, Any]) -> HTTPRequestData:
        return self.fn(row)


class JSONOutputParser:
    """Response body -> parsed JSON (optionally projected by a dataclass)."""

    def __init__(self, binding: Optional[type] = None):
        self.binding = binding

    def parse(self, resp: Optional[HTTPResponseData]) -> Any:
        if resp is None or resp.entity is None:
            return None
        obj = json.loads(resp.entity.decode("utf-8"))
        if self.binding is not None:
            return Binding.from_row(self.binding, obj)
        return obj


class StringOutputParser:
    def parse(self, resp: Optional[HTTPResponseData]) -> Optional[str]:
        if resp is None or resp.entity is None:
            return None
        return resp.entity.decode("utf-8")


class CustomOutputParser:
    def __init__(self, fn: Callable[[HTTPResponseData], Any]):
        self.fn = fn

    def parse(self, resp: Optional[HTTPResponseData]) -> Any:
        return None if resp is None else self.fn(resp)


class SimpleHTTPTransformer(Transformer, HasInputCol, HasOutputCol):
    """input row -> request (input parser) -> HTTP -> parsed output column
    (SimpleHTTPTransformer.scala:1-166).

    ``inputCol`` may name a STRUCT column of per-row dicts, or None to use all
    columns as the row payload. ``errorCol`` receives the response status when
    the call failed (handleResponseErrors parity).
    """

    inputParser = ComplexParam("inputParser", "Row -> HTTPRequestData parser")
    outputParser = ComplexParam("outputParser", "HTTPResponseData -> value parser")
    errorCol = Param("errorCol", "Error output column", "errors", ptype=str)
    concurrency = Param("concurrency", "Concurrent requests", 1, ptype=int)
    timeout = Param("timeout", "Per-request timeout (s)", 60.0, ptype=float)
    handler = ComplexParam("handler", "Custom (request) -> response callable")

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get("inputCol")
        out_col = self.get_or_throw("outputCol")
        err_col = self.get("errorCol")
        in_parser = self.get_or_throw("inputParser")
        out_parser = self.get("outputParser") or JSONOutputParser()
        handler = self.get("handler") or (
            lambda r: send_with_retries(
                r, timeout=self.get("timeout"),
                deadline=faults.deadline_from_headers(r.headers)))
        conc = self.get("concurrency")

        def fn(part):
            names = list(part)
            n = len(part[names[0]]) if names else 0
            out = np.empty(n, dtype=object)
            errs = np.empty(n, dtype=object)

            def payload(i):
                if in_col and in_col in part:
                    v = part[in_col][i]
                    return v if isinstance(v, dict) else {"value": v}
                return {k: part[k][i] for k in names}

            def run(i):
                req = in_parser.parse(payload(i))
                resp = handler(req)
                return i, resp

            def consume(results):
                for i, resp in results:
                    if resp is not None and resp.statusCode == 200:
                        try:
                            out[i] = out_parser.parse(resp)
                            errs[i] = None
                        except Exception as e:  # malformed 200 -> errorCol
                            out[i] = None
                            errs[i] = f"parse failed: {e}"
                    else:
                        out[i] = None
                        errs[i] = (f"{resp.statusCode}: {resp.statusLine}"
                                   if resp is not None else "no response")

            if conc > 1:
                with ThreadPoolExecutor(max_workers=conc) as pool:
                    consume(pool.map(run, range(n)))
            else:
                consume(map(run, range(n)))
            part[out_col] = out
            if err_col:
                part[err_col] = errs
            return part

        return df.map_partitions(fn)


class PartitionConsolidator(Transformer):
    """Funnel rows from many partitions into fewer (for rate-limited resources:
    one connection per host — io/http/PartitionConsolidator.scala:19-132)."""

    targetPartitions = Param("targetPartitions", "Partitions after consolidation",
                             1, lambda v: v > 0, int)

    def transform(self, df: DataFrame) -> DataFrame:
        return df.coalesce(self.get("targetPartitions"))
