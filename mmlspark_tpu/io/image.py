"""Image reading/decoding into ImageSchema struct columns.

Reference: io/image/ImageUtils.scala:1-159 (decode/encode BufferedImage <->
ImageSchema rows) + org/apache/spark/ml/source/image/PatchedImageFileFormat.scala
(the streaming-capable image datasource).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.schema import ImageSchema
from ..ops.image import decode_image
from .binary import read_binary_files


def read_images(path: str, recursive: bool = True, sample_ratio: float = 1.0,
                drop_invalid: bool = True, num_partitions: int = 1,
                seed: int = 0) -> DataFrame:
    """Directory -> DataFrame[{image: ImageSchema struct}] (image datasource)."""
    raw = read_binary_files(path, recursive, sample_ratio, inspect_zip=True,
                            seed=seed, num_partitions=num_partitions)
    df = to_image_column(raw, bytes_col="bytes", path_col="path",
                         output_col="image")
    df = df.drop("bytes")
    if drop_invalid:
        df = df.dropna(subset=["image"])
    return df


def to_image_column(df: DataFrame, bytes_col: str = "bytes",
                    path_col: Optional[str] = None,
                    output_col: str = "image") -> DataFrame:
    """Decode an encoded-bytes column into ImageSchema structs
    (ImageUtils.decode parity; undecodable rows become None)."""

    def fn(p):
        col = p[bytes_col]
        origins = p[path_col] if path_col and path_col in p else None
        out = np.empty(len(col), dtype=object)
        for i, blob in enumerate(col):
            if blob is None:
                out[i] = None
                continue
            arr = decode_image(bytes(blob))
            if arr is None:
                out[i] = None
            else:
                out[i] = ImageSchema.make(
                    arr, str(origins[i]) if origins is not None else "")
        return out

    return df.with_column(output_col, fn)
