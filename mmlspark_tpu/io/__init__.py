"""IO layer: binary/image readers, HTTP client stack, writers (reference io/).

Readers produce partitioned DataFrames of file bytes / decoded images
(io/binary/BinaryFileFormat.scala, io/image/ImageUtils.scala); the HTTP stack
turns web services into pipeline stages (io/http/*); PowerBIWriter streams
DataFrames to the PowerBI REST API.
"""

from .binary import BinaryFileReader, read_binary_files
from .image import read_images, to_image_column
from .http import (
    HTTPRequestData,
    HTTPResponseData,
    HTTPTransformer,
    JSONInputParser,
    JSONOutputParser,
    PartitionConsolidator,
    SharedSingleton,
    SharedVariable,
    SimpleHTTPTransformer,
    StringOutputParser,
    send_with_retries,
)
from .powerbi import PowerBIWriter

__all__ = [
    "BinaryFileReader", "HTTPRequestData", "HTTPResponseData", "HTTPTransformer",
    "JSONInputParser", "JSONOutputParser", "PartitionConsolidator",
    "PowerBIWriter", "SharedSingleton", "SharedVariable", "SimpleHTTPTransformer",
    "StringOutputParser", "read_binary_files", "read_images", "send_with_retries",
    "to_image_column",
]
