"""Codegen: API documentation + stage inventory from the stage registry.

The reference reflects over the compiled jar to generate PySpark/SparklyR
wrappers and their smoke tests (src/it/codegen, SURVEY §2.4). This framework IS
Python, so binding generation collapses into: (a) a generated API reference
with every stage's params/docs, (b) a machine-readable stage inventory that the
fuzzing harness uses to enforce test coverage (FuzzingTest reflection parity).
"""

from .docs import generate_docs, stage_inventory

__all__ = ["generate_docs", "stage_inventory"]
