"""Reflected command-line binding surface for every registered stage.

The reference generates PySpark + R wrapper classes per stage by reflecting
over param metadata (codegen/WrapperGenerator.scala:22-100, PySparkWrapper.scala,
SparkRWrapper.scala) and emits a smoke test per generated wrapper
(PySparkWrapperTest.scala). The TPU-native redesign keeps the same contract —
every stage reachable from a second, non-Python surface, derived entirely
from the Param registry, with reflection-enforced coverage — but binds at
runtime instead of emitting wrapper source files: the CLI builds each stage's
interface on demand from ``cls.params()``, so it can never drift from the
code the way generated files can.

    python -m mmlspark_tpu list
    python -m mmlspark_tpu describe LightGBMClassifier
    python -m mmlspark_tpu run LightGBMClassifier \
        --input train.json --output scored.json \
        -p labelCol=label -p numIterations=50 [--save model_dir]
    python -m mmlspark_tpu docs --out docs/

tests/test_codegen_cli.py is the PySparkWrapperTest tier: it walks the full
inventory and smoke-tests describe/construct for every stage.
"""

from __future__ import annotations

import argparse
import csv
import json
import sys
from typing import Any, Dict, List, Optional

import numpy as np

from .docs import stage_inventory


# -- table IO ---------------------------------------------------------------

def read_table(path: str):
    """JSON (list of row dicts or column dict) or CSV -> DataFrame."""
    from ..core.dataframe import DataFrame

    if path.endswith(".csv"):
        with open(path, newline="") as fh:
            rows = list(csv.DictReader(fh))
        conv: List[Dict[str, Any]] = []
        for r in rows:
            out: Dict[str, Any] = {}
            for k, v in r.items():
                if v == "" or v is None:  # empty cell -> missing, not ""
                    out[k] = None
                    continue
                try:
                    out[k] = float(v) if "." in v or "e" in v.lower() \
                        else int(v)
                except ValueError:
                    out[k] = v
            conv.append(out)
        return DataFrame.from_rows(conv)
    with open(path) as fh:
        obj = json.load(fh)
    if isinstance(obj, list):
        return DataFrame.from_rows(obj)
    return DataFrame.from_dict({k: np.asarray(v) for k, v in obj.items()})


def write_table(df, path: str) -> None:
    rows = []
    for r in df.rows():
        rows.append({k: (v.tolist() if isinstance(v, np.ndarray) else v)
                     for k, v in r.items()})
    with open(path, "w") as fh:
        json.dump(rows, fh, default=_json_default)


def _json_default(o):
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, bytes):
        return o.decode("utf-8", errors="replace")
    return str(o)


# -- stage construction from CLI params ------------------------------------

def parse_param_value(raw: str) -> Any:
    """JSON decode with bare-string fallback: 5 -> int, true -> bool,
    [1,2] -> list, foo -> 'foo'."""
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        return raw


def build_stage(name: str, params: Dict[str, Any]):
    inv = stage_inventory()
    if name not in inv:
        close = [k for k in inv if name.lower() in k.lower()]
        hint = f" Did you mean: {', '.join(close)}?" if close else ""
        raise SystemExit(f"Unknown stage {name!r}.{hint} "
                         f"(`list` shows all {len(inv)} stages)")
    cls = inv[name]
    declared = cls.params()
    unknown = set(params) - set(declared)
    if unknown:
        raise SystemExit(f"{name} has no params {sorted(unknown)}; "
                         f"declared: {sorted(declared)}")
    return cls(**params)


def describe(name: str) -> str:
    inv = stage_inventory()
    if name not in inv:
        raise SystemExit(f"Unknown stage {name!r}")
    cls = inv[name]
    lines = [f"{name}  ({cls.__module__})", ""]
    doc = (cls.__doc__ or "").strip()
    if doc:
        lines += [doc, ""]
    lines.append("Params:")
    for pname, p in sorted(cls.params().items()):
        kind = "complex" if p.is_complex else \
            (p.ptype.__name__ if isinstance(p.ptype, type) else "any")
        lines.append(f"  {pname:28s} {kind:9s} default={p.default!r}  {p.doc}")
    return "\n".join(lines)


# -- entry -----------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mmlspark_tpu",
        description="Run any registered stage from the command line.")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list", help="list every registered stage")
    d = sub.add_parser("describe", help="show a stage's params")
    d.add_argument("stage")
    r = sub.add_parser("run", help="fit/transform a stage over a table")
    r.add_argument("stage")
    r.add_argument("--input", required=True, help="input table (.json/.csv)")
    r.add_argument("--output", help="output table path (.json)")
    r.add_argument("-p", "--param", action="append", default=[],
                   metavar="NAME=VALUE", help="stage param (JSON-typed)")
    r.add_argument("--save", help="directory to save the (fitted) stage")
    g = sub.add_parser("docs", help="generate API docs")
    g.add_argument("--out", default="docs")
    args = ap.parse_args(argv)

    if args.cmd == "list":
        for name, cls in stage_inventory().items():
            first = (cls.__doc__ or "").strip().splitlines()
            print(f"{name:36s} {first[0] if first else ''}")
        return 0
    if args.cmd == "describe":
        print(describe(args.stage))
        return 0
    if args.cmd == "docs":
        from .docs import generate_docs

        files = generate_docs(args.out)
        print(f"{len(files)} doc files written to {args.out}/")
        return 0

    # run
    params: Dict[str, Any] = {}
    for kv in args.param:
        if "=" not in kv:
            raise SystemExit(f"--param wants NAME=VALUE, got {kv!r}")
        k, v = kv.split("=", 1)
        params[k] = parse_param_value(v)
    stage = build_stage(args.stage, params)
    df = read_table(args.input)
    from ..core.pipeline import Estimator

    if isinstance(stage, Estimator):
        fitted = stage.fit(df)
        out = fitted.transform(df)
    else:
        fitted = stage
        out = stage.transform(df)
    if args.save:
        fitted.save(args.save)
        print(f"saved to {args.save}", file=sys.stderr)
    if args.output:
        write_table(out, args.output)
        print(f"wrote {out.count()} rows to {args.output}", file=sys.stderr)
    else:
        for row in out.head(10):
            print(json.dumps(row, default=_json_default))
    return 0
