"""Generate API docs + stage inventory by walking the stage registry."""

from __future__ import annotations

import importlib
import os
from typing import Any, Dict, List, Optional

# importing these populates the stage registry (codegen reflects the full jar
# in the reference; here: import the full package surface)
_PACKAGES = [
    "mmlspark_tpu.automl",
    "mmlspark_tpu.cognitive",
    "mmlspark_tpu.featurize",
    "mmlspark_tpu.gbdt",
    "mmlspark_tpu.image",
    "mmlspark_tpu.io",
    "mmlspark_tpu.lime",
    "mmlspark_tpu.models",
    "mmlspark_tpu.recommendation",
    "mmlspark_tpu.stages",
    "mmlspark_tpu.train",
    "mmlspark_tpu.vw",
]


#: index lines for hand-maintained API pages (non-stage surfaces)
_EXTRA_INDEX = [
    "- [serving](serving.md) (hand-maintained; not stage-registry classes): "
    "`ServingServer`, `serve_pipeline`, `AdaptiveBatchController`, "
    "`ReplicaSet`, `PipelinedExecutor`, `RoutingFront`, `AsyncHTTPServer`, "
    "`AsyncConnectionPool`, `TenantAdmission`",
    "- [obs](obs.md) (hand-maintained; not stage-registry classes): "
    "`MetricsRegistry`, `Counter`, `Gauge`, `Histogram`, `Tracer`, "
    "`SpanContext`, `TrainRecorder`, bridge adapters, perf attribution "
    "(`extract_cost`, `attribute_segments`, `SLOConfig`, `SLOTracker`)",
    "- wire frames (`mmlspark_tpu.io.binary`, hand-maintained spec in "
    "[docs/serving.md](../serving.md)): `encode_frame`, `decode_frame`, "
    "`frame_info`, `FRAME_CONTENT_TYPE` — the zero-copy binary columnar "
    "request format",
    "- static analysis (`mmlspark_tpu.analysis`, hand-maintained guide in "
    "[docs/static_analysis.md](../static_analysis.md)): `run_analysis`, "
    "`analyze_source`, `AnalysisPass`, `Finding` — the AST lint framework "
    "behind `tools/analyze.py` (concurrency-lint, jax-compat-gate, "
    "device-purity, API-hygiene, style)",
    "- auto-tuning (`mmlspark_tpu.core.costmodel` / `.core.tune`, "
    "hand-maintained guide in [docs/autotune.md](../autotune.md)): "
    "`SegmentCostModel` (analytical-then-learned per-(segment, bucket) "
    "batch cost, `predict_ms` + calibration confidence), `Tuner` / "
    "`KnobSet` (measure→refit→apply loop, journaled knob decisions, "
    "one-step rollback) — the cost-model-driven replacement for the "
    "static bucket / fuse-vs-demote / batching-window / inflight knobs",
    "- sharded execution (`mmlspark_tpu.parallel.shardplan`, "
    "hand-maintained guide in [docs/sharding.md](../sharding.md)): "
    "`candidates` / `sharding_for` (per-segment partition-spec planning), "
    "`SegmentSharding` (pjit shardings, cache keys, donation gating), "
    "`measure_collectives` (all-reduce/all-gather probe calibration), "
    "`shard_groups` / `submesh_excluding` / `MeshSupervision` "
    "(shard-group quarantine + submesh re-planning)",
    "- ONNX interchange (`mmlspark_tpu.onnx`, dependency-free protobuf "
    "subset in `onnx/proto.py`): `import_onnx` (ONNX graph → "
    "`FunctionModel` with a structural `cache_token()`), `export_onnx` "
    "(module + params → ONNX bytes), `proto` (eval-free model "
    "reader/writer: `load_model`, `make_model`, `make_node`, "
    "`make_tensor`)",
    "- compiler search (`mmlspark_tpu.core.kernels` + the fusion stitch, "
    "hand-maintained guide in "
    "[docs/compiler_search.md](../compiler_search.md)): `KernelVariant` / "
    "`register` / `activate` / `variants_for` (autotuned Pallas / "
    "forest-traversal kernel variants; exact variants enforced bitwise, "
    "reduction-order-sensitive ones behind a declared tolerance), "
    "cross-segment stitching through transpiled `device_finalize` shims "
    "(`Segment.mark_stitched`, `SegmentCostModel.stitch_decision`), and "
    "the journaled `kernel_variants` / `stitch` knobs with one-step "
    "bitwise rollback (the `tuner.kernel_apply` chaos seam)",
    "- model lifecycle (`mmlspark_tpu.serving.lifecycle`, hand-maintained "
    "guide in [docs/lifecycle.md](../lifecycle.md)): `ModelRegistry` / "
    "`ModelVersion` (versioned states, journaled transitions, two-phase "
    "`swap_live`), `CanaryController` / `CanaryConfig` (shadow-scored "
    "ramped rollout gated on SLO burn + divergence, one-step rollback), "
    "`LifecyclePlane` / `make_lifecycle` (the served data path; "
    "`serve_pipeline(..., lifecycle=...)`), `OnlineTrainer` / "
    "`FeedbackJournal` / `VWOnlineAdapter` / `GBDTRefitAdapter` "
    "(journaled train-on-serve with bitwise-replayable checkpoints)",
    "- sparse end-to-end (`mmlspark_tpu.gbdt.pallas_sparse` + the CSR "
    "wire/staging seams, hand-maintained guide in "
    "[docs/sparse.md](../sparse.md)): `encode_csr_columns` / "
    "`decode_csr_columns` / `validate_csr_triple` / `CSR_SUFFIXES` "
    "(io/binary.py: CSR triples as validated frame sub-columns, hostile "
    "frames rejected all-or-nothing), `csr_gather` / "
    "`sparse_histogram_mxu` / `used_features` / `remap_ensemble` (the "
    "Pallas sparse kernels behind the `forest.csr` / `hist.csr` "
    "variants), `SegmentCostModel.observe_nnz` / `nnz_bytes` / "
    "`choose_layout` (the nnz-predicted, journaled `layout` knob), and "
    "`split_csr_rows` / `ragged_allgather_bytes` (shardplan's row-split "
    "`csr_row` partition spec)",
]


def _import_all() -> None:
    for pkg in _PACKAGES:
        importlib.import_module(pkg)


def stage_inventory() -> Dict[str, type]:
    """Every registered concrete stage, keyed by class name (dedup'd)."""
    from ..core.pipeline import registered_stages

    _import_all()
    out: Dict[str, type] = {}
    for name, cls in registered_stages().items():
        if "." in name:
            continue  # keep short names only
        if not cls.__module__.startswith("mmlspark_tpu."):
            continue
        out[name] = cls
    return dict(sorted(out.items()))


def _stage_doc(name: str, cls: type) -> str:
    lines = [f"### `{name}`", ""]
    doc = (cls.__doc__ or "").strip()
    if doc:
        lines.append(doc)
        lines.append("")
    lines.append(f"*Module:* `{cls.__module__}`")
    params = cls.params()
    if params:
        lines.append("")
        lines.append("| Param | Default | Doc |")
        lines.append("|---|---|---|")
        for pname, p in sorted(params.items()):
            kind = (" (complex)" if p.is_complex
                    else " (value-or-column)" if p.is_service else "")
            default = repr(p.default)
            if len(default) > 40:
                default = default[:37] + "..."
            doc_txt = (p.doc or "").replace("|", "\\|")
            lines.append(f"| `{pname}`{kind} | `{default}` | {doc_txt} |")
    lines.append("")
    return "\n".join(lines)


def generate_docs(path: str = "docs/api") -> List[str]:
    """Write per-package markdown API docs; returns written file paths."""
    inventory = stage_inventory()
    by_module: Dict[str, List[str]] = {}
    for name, cls in inventory.items():
        pkg = cls.__module__.split(".")[1]
        by_module.setdefault(pkg, []).append(name)

    os.makedirs(path, exist_ok=True)
    written: List[str] = []
    index = ["# mmlspark_tpu API reference", "",
             f"{len(inventory)} pipeline stages across "
             f"{len(by_module)} packages.", ""]
    for pkg, names in sorted(by_module.items()):
        fname = os.path.join(path, f"{pkg}.md")
        sections = [f"# mmlspark_tpu.{pkg}", ""]
        for name in names:
            sections.append(_stage_doc(name, inventory[name]))
        with open(fname, "w") as f:
            f.write("\n".join(sections))
        written.append(fname)
        index.append(f"- [{pkg}]({pkg}.md): " + ", ".join(
            f"`{n}`" for n in names))
    # hand-maintained pages for surfaces that are not registered stages
    # (kept out of the reflection walk; listed so the index stays complete)
    index.extend(_EXTRA_INDEX)
    with open(os.path.join(path, "README.md"), "w") as f:
        f.write("\n".join(index) + "\n")
    written.append(os.path.join(path, "README.md"))
    return written
