"""Jitted online linear learner: per-example adaptive SGD / FTRL over hashed features.

Replaces VW's C++ learn loop (driven per-row through JNI at
vw/VowpalWabbitBase.scala:239-258) with a ``lax.scan`` over examples inside one
XLA program: each step gathers the example's weights, computes the loss gradient,
and scatter-updates — the whole pass is one device launch instead of N JNI calls.

Distributed (VW AllReduce spanning-tree parity, VowpalWabbitBase.scala:314-342):
each mesh shard scans its rows independently, then weights are averaged with
``psum`` under ``shard_map`` after every pass — exactly VW's between-pass model
averaging, over ICI instead of driver-rooted TCP.

Sparse rows are padded to a fixed nnz per row; padded slots (index 0, value 0) are
inert because both the gradient and the l2 decay are gated on value != 0, and padded
rows (example weight 0) don't advance the learning-rate clock.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..parallel.mesh import fetch_global


@dataclasses.dataclass
class LearnerConfig:
    num_bits: int = 18
    learning_rate: float = 0.5
    power_t: float = 0.5           # lr decay exponent (VW --power_t)
    initial_t: float = 0.0
    l1: float = 0.0
    l2: float = 0.0
    loss_function: str = "squared"  # squared | logistic | hinge | quantile
    quantile_tau: float = 0.5
    adaptive: bool = True           # AdaGrad per-weight scaling (VW default)
    num_passes: int = 1
    ftrl: bool = False
    ftrl_alpha: float = 0.005
    ftrl_beta: float = 0.1
    seed: int = 0


@dataclasses.dataclass
class SparseDataset:
    """Padded sparse matrix: [N, max_nnz] indices/values (+label/weight)."""

    indices: np.ndarray   # int32 [N, K]
    values: np.ndarray    # float32 [N, K]
    labels: np.ndarray    # float32 [N]
    weights: np.ndarray   # float32 [N]

    @staticmethod
    def from_rows(rows, labels, weights=None, num_bits: int = 18) -> "SparseDataset":
        mask = (1 << num_bits) - 1
        n = len(rows)
        nnz = [0 if r is None else len(r["indices"]) for r in rows]
        k = max(max(nnz, default=1), 1)
        idx = np.zeros((n, k), dtype=np.int32)
        val = np.zeros((n, k), dtype=np.float32)
        for i, r in enumerate(rows):
            if r is None or len(r["indices"]) == 0:
                continue
            m = len(r["indices"])
            idx[i, :m] = (np.asarray(r["indices"], dtype=np.int64) & mask)
            val[i, :m] = r["values"]
        return SparseDataset(
            idx, val,
            np.asarray(labels, dtype=np.float32),
            np.asarray(weights if weights is not None else np.ones(n),
                       dtype=np.float32))


def _loss_grad(loss: str, pred, label, tau: float):
    """dLoss/dPred for the supported VW loss functions."""
    import jax.numpy as jnp

    if loss == "squared":
        return pred - label
    if loss == "logistic":
        # labels in {-1, +1} (VW convention)
        return -label / (1.0 + jnp.exp(label * pred))
    if loss == "hinge":
        return jnp.where(label * pred < 1.0, -label, 0.0)
    if loss == "quantile":
        return jnp.where(pred > label, 1.0 - tau, -tau)
    raise ValueError(f"Unknown loss {loss!r}")


def make_scan_pass(config: LearnerConfig):
    """Build the jitted single-pass scan: (state, dataset) -> (state, example_losses).

    State: (w, g2, t) for adaptive SGD, or (z, n_acc) for FTRL.
    """
    import jax
    import jax.numpy as jnp

    loss = config.loss_function
    tau = config.quantile_tau
    lr = config.learning_rate
    power_t = config.power_t
    l2 = config.l2
    l1 = config.l1

    if config.ftrl:
        def step(state, ex):
            z, n_acc = state
            idx, val, label, wgt = ex
            # FTRL-proximal weight reconstruction for active coords
            zi = z[idx]
            ni = n_acc[idx]
            sign = jnp.sign(zi)
            wi = jnp.where(
                jnp.abs(zi) <= l1, 0.0,
                -(zi - sign * l1) / ((config.ftrl_beta + jnp.sqrt(ni))
                                     / config.ftrl_alpha + l2))
            pred = jnp.sum(wi * val)
            g = _loss_grad(loss, pred, label, tau) * wgt
            gi = g * val
            sigma = (jnp.sqrt(ni + gi * gi) - jnp.sqrt(ni)) / config.ftrl_alpha
            z = z.at[idx].add(gi - sigma * wi)
            n_acc = n_acc.at[idx].add(gi * gi)
            return (z, n_acc), _example_loss(loss, pred, label, tau) * wgt

        def run_pass(state, ds):
            return jax.lax.scan(step, state,
                                (ds["indices"], ds["values"], ds["labels"],
                                 ds["weights"]))
    else:
        def step(state, ex):
            w, g2, t = state
            idx, val, label, wgt = ex
            wi = w[idx]
            pred = jnp.sum(wi * val)
            g = _loss_grad(loss, pred, label, tau) * wgt
            # gate the l2 decay on active slots: padded nnz slots are (index 0,
            # value 0) and must not decay weight bucket 0 / pollute its AdaGrad
            # accumulator
            gi = g * val + l2 * wi * (val != 0)
            # padded rows (example weight 0) must not advance the lr-decay clock
            t = t + (wgt > 0)
            if config.adaptive:
                # VW adaptive: per-weight rate lr * g2^(-power_t)
                g2 = g2.at[idx].add(gi * gi)
                scale = jnp.power(g2[idx] + 1e-16, power_t) + 1e-8
                w = w.at[idx].add(-lr * gi / scale)
            else:
                eta = lr / jnp.power(t + config.initial_t, power_t)
                w = w.at[idx].add(-eta * gi)
            return (w, g2, t), _example_loss(loss, pred, label, tau) * wgt

        def run_pass(state, ds):
            return jax.lax.scan(step, state,
                                (ds["indices"], ds["values"], ds["labels"],
                                 ds["weights"]))

    return jax.jit(run_pass)


def _example_loss(loss: str, pred, label, tau: float):
    import jax.numpy as jnp

    if loss == "squared":
        return 0.5 * (pred - label) ** 2
    if loss == "logistic":
        return jnp.logaddexp(0.0, -label * pred)  # stable for large |margin|
    if loss == "hinge":
        return jnp.maximum(0.0, 1.0 - label * pred)
    if loss == "quantile":
        d = pred - label
        return jnp.where(d > 0, (1 - tau) * d, -tau * d)
    raise ValueError(loss)


@dataclasses.dataclass
class TrainingStats:
    """Per-worker diagnostics (VowpalWabbitBase TrainingStats parity,
    vw/VowpalWabbitBase.scala:29-48)."""

    partition_id: int
    num_examples: int
    total_time_ns: int
    learn_time_ns: int
    average_loss: float
    weighted_example_sum: float


def _ftrl_weights(config: LearnerConfig, z, n_acc):
    """Reconstruct dense weights from FTRL-proximal (z, n) state."""
    import jax.numpy as jnp

    sign = jnp.sign(z)
    return jnp.where(
        jnp.abs(z) <= config.l1, 0.0,
        -(z - sign * config.l1) / ((config.ftrl_beta + jnp.sqrt(n_acc))
                                   / config.ftrl_alpha + config.l2))


def _native_pass_ok(config: LearnerConfig) -> bool:
    """Route single-shard training to the native C++ sequential learner?

    Default on: a sequential per-example update stream is latency-bound on
    an accelerator, exactly like the reference's VW (a C++ core driven
    per row). FTRL and unsupported losses stay on the scan path;
    MMLSPARK_TPU_NATIVE_VW=0 disables (tests pin the scan path with it)."""
    import os

    if os.environ.get("MMLSPARK_TPU_NATIVE_VW", "") in ("0", "false"):
        return False
    if config.ftrl:
        return False
    if config.loss_function not in ("squared", "logistic", "hinge",
                                    "quantile"):
        return False
    from .. import native_loader

    return native_loader.load() is not None


def train_linear(config: LearnerConfig, dataset: SparseDataset,
                 initial_weights: Optional[np.ndarray] = None,
                 mesh=None) -> Tuple[np.ndarray, List[TrainingStats]]:
    """Run ``num_passes`` scan passes; with a mesh, shards scan independently and
    state is psum-averaged between passes (AllReduce spanning-tree parity).

    Optimizer state (AdaGrad accumulators / FTRL z,n) carries across passes.
    """
    import time

    dim = 1 << config.num_bits
    n = len(dataset.labels)
    n_shards = 1
    if mesh is not None:
        from ..parallel.mesh import DATA_AXIS

        n_shards = int(mesh.shape.get(DATA_AXIS, 1))

    if (n_shards == 1 and _native_pass_ok(config)
            and int(np.min(dataset.indices, initial=0)) >= 0
            and int(np.max(dataset.indices, initial=-1)) < dim):
        # native C++ sequential pass (VW's own architecture: a C core doing
        # per-example updates, vw/VowpalWabbitBase.scala:218-305). Sequential
        # SGD is latency-bound on an accelerator (~115k ex/s through the
        # scan vs millions/s on one host core), so the single-shard regime
        # runs on the host; mesh fits keep the psum-averaged scan path.
        # Decided BEFORE any jnp state exists — this branch must never
        # initialize a device or ship the 2^bits weight vector anywhere.
        # Index bounds are validated above: the C kernel indexes raw memory
        # where XLA's scatter would clamp/drop OOB indices (datasets built
        # by from_rows are always masked in-range; hand-built ones may not
        # be and fall through to the scan engine).
        from .. import native_loader

        # FORCED copy: the in-place ctypes update must never alias (and
        # mutate) caller-owned initial_weights (a zero-copy jax-array view
        # is read-only; a caller numpy array would be silently trained on)
        w_np = (np.array(np.asarray(initial_weights), dtype=np.float32)
                if initial_weights is not None
                else np.zeros(dim, dtype=np.float32))
        g2_np = np.zeros(dim, dtype=np.float32)
        t_val = 0.0
        w_sum = float(dataset.weights.sum())
        stats = []
        native_ok = True
        for _ in range(config.num_passes):
            t0 = time.perf_counter_ns()
            res = native_loader.vw_train_pass(
                dataset.indices, dataset.values, dataset.labels,
                dataset.weights, w_np, g2_np, t_val,
                loss=config.loss_function, tau=config.quantile_tau,
                lr=config.learning_rate, power_t=config.power_t,
                initial_t=config.initial_t, l2=config.l2,
                adaptive=config.adaptive)
            dt = time.perf_counter_ns() - t0
            if res is None:
                # the .so (or its symbol) went away between the
                # _native_pass_ok probe and the call — fall through to the
                # jax scan engine below, restarting from initial_weights
                # (mirrors binning.transform_col's bin_column fallback; an
                # assert here would strip under python -O and unpack None)
                native_ok = False
                break
            t_val, loss_sum = res
            stats.append(TrainingStats(0, n, dt, dt,
                                       loss_sum / max(w_sum, 1e-12), w_sum))
        if native_ok:
            return w_np, stats

    import jax
    import jax.numpy as jnp

    w0 = (jnp.asarray(initial_weights, dtype=jnp.float32)
          if initial_weights is not None else jnp.zeros(dim, dtype=jnp.float32))
    if config.ftrl:
        # warm start: choose z so the reconstructed weights equal w0 at n=0
        # (ignores the l1 dead zone — exact for |z| > l1, the active coords)
        z0 = -w0 * (config.ftrl_beta / config.ftrl_alpha + config.l2)
        z0 = jnp.where(z0 != 0, z0 + jnp.sign(z0) * config.l1, 0.0)
        state = (z0, jnp.zeros(dim, dtype=jnp.float32))  # (z, n)
    else:
        state = (w0, jnp.zeros(dim, dtype=jnp.float32), jnp.float32(0.0))

    run_pass = make_scan_pass(config)
    stats: List[TrainingStats] = []

    if n_shards > 1:
        from jax.sharding import PartitionSpec as P

        # version-gated API (moved modules, renamed kwargs): route through
        # the compat shim instead of resolving jax.shard_map here
        from ..parallel.mesh import shard_map_compat as shard_map

        pad = (-n) % n_shards

        def padded(a, fill=0):
            if not pad:
                return a
            cfg = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            return np.pad(a, cfg, constant_values=fill)

        ds = {
            "indices": padded(dataset.indices),
            "values": padded(dataset.values),       # value 0 => no-op example
            "labels": padded(dataset.labels),
            "weights": padded(dataset.weights, 0),  # weight 0 => no grad
        }

        def shard_pass(state, indices, values, labels, weights):
            local = {"indices": indices, "values": values,
                     "labels": labels, "weights": weights}
            # carry starts replicated but the scan makes it shard-varying:
            # mark it varying up front (jax vma typing for scan-in-shard_map;
            # older jax has no pcast and no vma typing to satisfy)
            pcast = getattr(jax.lax, "pcast", None)
            if pcast is not None:
                state = jax.tree.map(
                    lambda s: pcast(s, (DATA_AXIS,), to="varying"), state)
            state, losses = run_pass(state, local)
            # between-pass model averaging over the data axis (VW sync point);
            # pmean also restores the replicated (invariant) type for out_specs P()
            state = jax.tree.map(
                lambda s: jax.lax.pmean(s, axis_name=DATA_AXIS), state)
            return state, jax.lax.psum(jnp.sum(losses), axis_name=DATA_AXIS)

        state_spec = jax.tree.map(lambda _: P(), state)
        sharded = jax.jit(shard_map(
            shard_pass, mesh=mesh,
            in_specs=(state_spec, P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS)),
            out_specs=(state_spec, P())))

        for _ in range(config.num_passes):
            t0 = time.perf_counter_ns()
            state, loss_sum = sharded(state, ds["indices"], ds["values"],
                                      ds["labels"], ds["weights"])
            # the loss fetch is the sync point: on async-dispatch plugins
            # (axon) the call above returns at enqueue, so timing it alone
            # records ~0 — fetch BEFORE reading the clock. fetch_global:
            # under a multi-PROCESS mesh the replicated loss spans
            # non-addressable devices and a bare float() raises
            loss_host = float(fetch_global(loss_sum))
            dt = time.perf_counter_ns() - t0
            w_sum = float(dataset.weights.sum())
            stats.append(TrainingStats(0, n, dt, dt,
                                       loss_host / max(w_sum, 1e-12),
                                       w_sum))
    else:
        ds = {"indices": jnp.asarray(dataset.indices),
              "values": jnp.asarray(dataset.values),
              "labels": jnp.asarray(dataset.labels),
              "weights": jnp.asarray(dataset.weights)}
        for _ in range(config.num_passes):
            t0 = time.perf_counter_ns()
            state, losses = run_pass(state, ds)
            # fetch-as-sync (see sharded branch): time the execution, not
            # the async enqueue
            loss_host = float(jnp.sum(losses))
            dt = time.perf_counter_ns() - t0
            w_sum = float(dataset.weights.sum())
            stats.append(TrainingStats(0, n, dt, dt,
                                       loss_host / max(w_sum, 1e-12),
                                       w_sum))

    # fetch BEFORE the FTRL weight transform: _ftrl_weights runs eager jnp
    # ops, which raise on non-addressable multi-process state just like a
    # bare np.asarray would
    s0, s1 = fetch_global((state[0], state[1]))
    if config.ftrl:
        w = _ftrl_weights(config, s0, s1)
    else:
        w = s0
    return np.asarray(w), stats


def predict_linear(w: np.ndarray, dataset: SparseDataset) -> np.ndarray:
    """Batched sparse dot product (jitted)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fwd(w, idx, val):
        return jnp.sum(w[idx] * val, axis=1)

    return np.asarray(fwd(jnp.asarray(w), jnp.asarray(dataset.indices),
                          jnp.asarray(dataset.values)))


class LinearLearner:
    """Incremental face of the scan pass: ``partial_fit(rows, labels)``
    folds one mini-batch into persistent optimizer state (the serving
    lifecycle's online-adapter contract; ``train_linear`` keeps its
    whole-pass semantics and native fast path untouched).

    Always the jax scan path, never the native engine — the C++ loop
    keeps its learning-rate clock internal, so its state cannot round-trip
    through a checkpoint bitwise. State (weights + AdaGrad/FTRL
    accumulators + lr clock) carries across calls: replaying the same
    example slices in the same order reproduces the state bitwise, which
    is exactly the online trainer's journal-resume contract.
    """

    def __init__(self, config: Optional[LearnerConfig] = None):
        self.config = config if config is not None else LearnerConfig()
        self._pass = None     # jitted scan, built on first partial_fit
        self._state = None    # (w, g2, t) adaptive/sgd or (z, n) FTRL
        self.examples_seen = 0

    def _ensure_state(self) -> None:
        if self._state is not None:
            return
        import jax.numpy as jnp

        dim = 1 << self.config.num_bits
        if self.config.ftrl:
            self._state = (jnp.zeros(dim, dtype=jnp.float32),
                           jnp.zeros(dim, dtype=jnp.float32))
        else:
            self._state = (jnp.zeros(dim, dtype=jnp.float32),
                           jnp.zeros(dim, dtype=jnp.float32),
                           jnp.float32(0.0))

    def partial_fit(self, rows, labels, weights=None) -> float:
        """One incremental step over ``rows`` (sparse dicts, the
        ``SparseDataset.from_rows`` shape); returns the summed weighted
        example loss of the batch."""
        import jax.numpy as jnp

        self._ensure_state()
        if self._pass is None:
            self._pass = make_scan_pass(self.config)
        ds = SparseDataset.from_rows(rows, labels, weights,
                                     num_bits=self.config.num_bits)
        batch = {"indices": jnp.asarray(ds.indices),
                 "values": jnp.asarray(ds.values),
                 "labels": jnp.asarray(ds.labels),
                 "weights": jnp.asarray(ds.weights)}
        self._state, losses = self._pass(self._state, batch)
        self.examples_seen += int(len(ds.labels))
        return float(jnp.sum(losses))

    @property
    def weights(self) -> np.ndarray:
        """Dense weight vector reconstructed from the current state."""
        self._ensure_state()
        if self.config.ftrl:
            return np.asarray(_ftrl_weights(self.config, self._state[0],
                                            self._state[1]))
        return np.asarray(self._state[0])

    def predict(self, rows) -> np.ndarray:
        ds = SparseDataset.from_rows(rows, np.zeros(len(rows)),
                                     num_bits=self.config.num_bits)
        return predict_linear(self.weights, ds)

    def state_dict(self) -> Dict[str, object]:
        """Exact numpy snapshot of the optimizer state (float32 arrays —
        a serialize/load round-trip continues training bitwise)."""
        self._ensure_state()
        arrs = [np.asarray(s) for s in self._state]
        if self.config.ftrl:
            return {"kind": "ftrl", "z": arrs[0], "n": arrs[1],
                    "examples_seen": self.examples_seen}
        return {"kind": "adaptive", "w": arrs[0], "g2": arrs[1],
                "t": float(arrs[2]), "examples_seen": self.examples_seen}

    def load_state_dict(self, d: Dict[str, object]) -> "LinearLearner":
        import jax.numpy as jnp

        expected = "ftrl" if self.config.ftrl else "adaptive"
        if d.get("kind") != expected:
            raise ValueError(f"state kind {d.get('kind')!r} does not match "
                             f"config ({expected})")
        if self.config.ftrl:
            self._state = (jnp.asarray(d["z"], dtype=jnp.float32),
                           jnp.asarray(d["n"], dtype=jnp.float32))
        else:
            self._state = (jnp.asarray(d["w"], dtype=jnp.float32),
                           jnp.asarray(d["g2"], dtype=jnp.float32),
                           jnp.float32(d["t"]))
        self.examples_seen = int(d.get("examples_seen", 0))
        return self
