"""VW pipeline stages: Classifier / Regressor (+Models) with CLI-args parity.

Reference: vw/VowpalWabbitBase.scala (args building :133-152, train :218-305),
vw/VowpalWabbitClassifier.scala, vw/VowpalWabbitBaseModel.scala:1-98. VW exposes
most knobs through its CLI string; the reference passes them via ``passThroughArgs``
plus typed params — both supported here and parsed into LearnerConfig.
"""

from __future__ import annotations

import shlex
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import (
    ComplexParam,
    HasFeaturesCol,
    HasLabelCol,
    HasWeightCol,
    Param,
)
from ..core.pipeline import Estimator, Model
from ..core.schema import ColType, Schema
from .learner import (
    LearnerConfig,
    SparseDataset,
    TrainingStats,
    predict_linear,
    train_linear,
)


def parse_vw_args(args: str, base: Optional[LearnerConfig] = None) -> LearnerConfig:
    """Parse the supported subset of VW CLI args into a LearnerConfig
    (VW defers defaults to native CLI parsing, VowpalWabbitBase.scala:92-94)."""
    cfg = base or LearnerConfig()
    toks = shlex.split(args or "")
    i = 0
    while i < len(toks):
        t = toks[i]

        def val():
            nonlocal i
            i += 1
            if i >= len(toks):
                raise ValueError(f"VW arg {t!r} expects a value but none was given")
            return toks[i]

        if t in ("-b", "--bit_precision"):
            cfg.num_bits = int(val())
        elif t in ("-l", "--learning_rate"):
            cfg.learning_rate = float(val())
        elif t == "--power_t":
            cfg.power_t = float(val())
        elif t == "--initial_t":
            cfg.initial_t = float(val())
        elif t == "--l1":
            cfg.l1 = float(val())
        elif t == "--l2":
            cfg.l2 = float(val())
        elif t == "--loss_function":
            cfg.loss_function = val()
        elif t == "--quantile_tau":
            cfg.quantile_tau = float(val())
        elif t == "--passes":
            cfg.num_passes = int(val())
        elif t == "--ftrl":
            cfg.ftrl = True
        elif t == "--ftrl_alpha":
            cfg.ftrl_alpha = float(val())
        elif t == "--ftrl_beta":
            cfg.ftrl_beta = float(val())
        elif t == "--adaptive":
            cfg.adaptive = True
        elif t == "--sgd":
            cfg.adaptive = False
        elif t == "--random_seed":
            cfg.seed = int(val())
        elif t in ("--quiet", "--no_stdin", "-q", "--interactions", "--holdout_off"):
            if t in ("-q", "--interactions"):
                val()  # interaction pairs handled by VowpalWabbitInteractions stage
        else:
            pass  # unknown args ignored (VW tolerates extra args in passthrough)
        i += 1
    return cfg


def parse_readable_model(text: str) -> Tuple[int, np.ndarray]:
    """Parse a ``--readable_model`` text dump back into (num_bits, weights).

    Closes the interchange loop of ``get_readable_model``: continued
    training from a text dump (the reference's initialModel semantics,
    vw/VowpalWabbitBase.scala:120-122, for the documented text surface —
    docs/vw.md). Accepts both this repo's dump (``bits:N`` header) and a
    real vw dump (``Num weight bits:N`` header, informational header lines
    before the ``index:weight`` section are skipped)."""
    num_bits = 18
    saw_bits = False
    entries = []
    for line in text.splitlines():
        line = line.strip()
        if not line or ":" not in line:
            continue
        key, _, val = line.rpartition(":")
        key = key.strip()
        if key in ("bits", "Num weight bits"):
            num_bits = int(val)
            saw_bits = True
            continue
        try:
            idx, w = int(key), float(val)
        except ValueError:
            continue  # vw header lines (Version, Min label, ...)
        entries.append((idx, w))
    if entries and not saw_bits:
        import warnings

        warnings.warn(
            "readable model has weight entries but no bits header "
            "('bits:N' / 'Num weight bits:N') — assuming the VW default of "
            "18; a dump from a different-bit model would load corrupted",
            stacklevel=2)
    size = 1 << num_bits
    oob = [i for i, _ in entries if i >= size or i < 0]
    if oob:
        # silently wrapping with `i & mask` would alias distinct weights
        # onto the same bucket — a corrupted model with no error signal
        why = "is missing" if not saw_bits else "disagrees with its entries"
        raise ValueError(
            f"readable model has {len(oob)} weight indices outside the "
            f"{num_bits}-bit feature space (max index {max(oob)} >= "
            f"{size}); the dump's bits header {why} — re-dump with the "
            f"matching numBits")
    weights = np.zeros(size, dtype=np.float64)
    for i, w in entries:
        weights[i] = w
    return num_bits, weights


class _VowpalWabbitBase(HasFeaturesCol, HasLabelCol, HasWeightCol):
    """Shared params (vw/VowpalWabbitBase.scala)."""

    passThroughArgs = Param("passThroughArgs", "VW-style CLI args", "", ptype=str)
    numBits = Param("numBits", "Feature space bits", 18, lambda v: 1 <= v <= 31, int)
    learningRate = Param("learningRate", "Learning rate", None, ptype=float)
    powerT = Param("powerT", "LR decay exponent", None, ptype=float)
    l1 = Param("l1", "L1 regularization", None, ptype=float)
    l2 = Param("l2", "L2 regularization", None, ptype=float)
    numPasses = Param("numPasses", "Passes over the data", 1, lambda v: v > 0, int)
    useBarrierExecutionMode = Param("useBarrierExecutionMode",
                                    "Parity no-op (SPMD is gang-scheduled)", False,
                                    ptype=bool)
    numWorkers = Param("numWorkers", "Worker/shard override (0=auto, 1=single)", 0,
                       ptype=int)
    initialModel = ComplexParam("initialModel", "Warm-start weights")
    additionalFeatures = Param(
        "additionalFeatures",
        "Extra sparse-feature columns merged with featuresCol per row "
        "(vw/VowpalWabbitBase.scala additionalFeatures — e.g. the output of "
        "VowpalWabbitInteractions)", None, ptype=(list, tuple))

    def _config(self, loss: str) -> LearnerConfig:
        cfg = LearnerConfig(loss_function=loss, num_bits=self.get("numBits"),
                            num_passes=self.get("numPasses"))
        if self.get("learningRate") is not None:
            cfg.learning_rate = self.get("learningRate")
        if self.get("powerT") is not None:
            cfg.power_t = self.get("powerT")
        if self.get("l1") is not None:
            cfg.l1 = self.get("l1")
        if self.get("l2") is not None:
            cfg.l2 = self.get("l2")
        return parse_vw_args(self.get("passThroughArgs"), cfg)

    def set_initial_model_readable(self, text: str) -> "_VowpalWabbitBase":
        """Warm-start from a ``--readable_model`` text dump: sets numBits
        from the dump's header and initialModel to its weights
        (initialModel continuation semantics,
        vw/VowpalWabbitBase.scala:120-122)."""
        bits, weights = parse_readable_model(text)
        self.set("numBits", bits)
        self.set("initialModel", weights)
        return self

    def _dataset(self, df: DataFrame, cfg: LearnerConfig,
                 label_transform=None) -> SparseDataset:
        data = df.collect()
        rows = data[self.get_or_throw("featuresCol")]
        rows = [_to_sparse(r) for r in rows]
        for extra_col in (self.get("additionalFeatures") or ()):
            extra = [_to_sparse(r) for r in data[extra_col]]
            rows = [_merge_sparse(a, b) for a, b in zip(rows, extra)]
        labels = np.asarray(data[self.get_or_throw("labelCol")], dtype=np.float64)
        if label_transform is not None:
            labels = label_transform(labels)
        weights = None
        if self.get("weightCol"):
            weights = np.asarray(data[self.get("weightCol")], dtype=np.float64)
        return SparseDataset.from_rows(rows, labels, weights, cfg.num_bits)

    def _mesh(self):
        if self.get("numWorkers") == 1:
            return None
        from ..parallel.mesh import DATA_AXIS, MeshContext

        try:
            # explicit meshes only (MeshContext.current): auto-adopting the
            # lazily-built all-device mesh drags small fits through the
            # distributed path (see LightGBM stage note)
            mesh = MeshContext.current()
            if mesh is not None and int(mesh.shape.get(DATA_AXIS, 1)) > 1:
                return mesh
        except Exception:
            pass
        return None


def _to_sparse(r) -> Optional[Dict[str, np.ndarray]]:
    """Accept featurizer structs OR dense vectors (auto-densify)."""
    if r is None:
        return None
    if isinstance(r, dict):
        return r
    arr = np.asarray(r, dtype=np.float64).reshape(-1)
    nz = np.nonzero(arr)[0]
    return {"indices": nz.astype(np.int64), "values": arr[nz].astype(np.float32)}


def _merge_sparse(a, b):
    """Union two sparse rows (values summed on index collision — VW merges
    namespaces into one example the same way)."""
    if a is None:
        return b
    if b is None:
        return a
    idx = np.concatenate([np.asarray(a["indices"], dtype=np.int64),
                          np.asarray(b["indices"], dtype=np.int64)])
    val = np.concatenate([np.asarray(a["values"], dtype=np.float32),
                          np.asarray(b["values"], dtype=np.float32)])
    uniq, inv = np.unique(idx, return_inverse=True)
    merged = np.zeros(len(uniq), dtype=np.float32)
    np.add.at(merged, inv, val)
    out = {"indices": uniq, "values": merged}
    size = max(int(a.get("size", 0)), int(b.get("size", 0)))
    if size:
        out["size"] = size
    return out


class _VowpalWabbitModelBase(Model, HasFeaturesCol):
    """Scoring base for VW models.

    Model interchange surface: ``get_readable_model()`` — the vw
    ``--readable_model`` text dump (bit-exact murmur hashing makes single
    weights directly comparable to a vw run). The reference's binary VW
    blob (``getModel``, vw/VowpalWabbitBaseModel.scala:1-98) is a
    version-pinned format and a documented NON-GOAL: see docs/vw.md for
    the rationale; framework persistence round-trips the full learner
    state (weights + AdaGrad/FTRL accumulators) instead.
    """

    weights = ComplexParam("weights", "Learned weight vector")
    numBits = Param("numBits", "Feature space bits", 18, ptype=int)
    testArgs = Param("testArgs", "Extra args used at test time (parity)", "", ptype=str)
    additionalFeatures = Param("additionalFeatures",
                               "Extra sparse columns merged at scoring, same "
                               "as at training", None, ptype=(list, tuple))

    def __init__(self, **kwargs):
        self._stats: List[TrainingStats] = kwargs.pop("stats", [])
        super().__init__(**kwargs)

    def _raw(self, part) -> np.ndarray:
        rows = [_to_sparse(r) for r in part[self.get_or_throw("featuresCol")]]
        for extra_col in (self.get("additionalFeatures") or ()):
            extra = [_to_sparse(r) for r in part[extra_col]]
            rows = [_merge_sparse(a, b) for a, b in zip(rows, extra)]
        ds = SparseDataset.from_rows(rows, np.zeros(len(rows)),
                                     num_bits=self.get("numBits"))
        return predict_linear(self.get_or_throw("weights"), ds)

    def get_readable_model(self, max_entries: int = 1 << 20) -> str:
        """The vw ``--readable_model`` text dump: one ``index:weight`` line
        per nonzero weight in the hashed feature space. The binary VW blob
        (getModel, vw/VowpalWabbitBaseModel.scala:1-98) is a version-pinned
        non-goal — see docs/vw.md; this text form cross-checks individual
        weights against a vw run (the hashing is bit-exact murmur)."""
        w = np.asarray(self.get_or_throw("weights"), dtype=np.float64)
        lines = [f"bits:{self.get('numBits')}"]
        nz = np.nonzero(w)[0]
        for i in nz[:max_entries]:
            lines.append(f"{int(i)}:{w[i]:.6f}")
        return "\n".join(lines) + "\n"

    def get_performance_statistics(self) -> DataFrame:
        """Training diagnostics DataFrame (VowpalWabbitBase.scala:344-368)."""
        if not self._stats:
            return DataFrame.empty(["partitionId", "numExamples", "totalTimeNs",
                                    "learnTimeNs", "averageLoss",
                                    "weightedExampleSum"])
        return DataFrame.from_rows([{
            "partitionId": s.partition_id,
            "numExamples": s.num_examples,
            "totalTimeNs": s.total_time_ns,
            "learnTimeNs": s.learn_time_ns,
            "averageLoss": s.average_loss,
            "weightedExampleSum": s.weighted_example_sum,
        } for s in self._stats])


class VowpalWabbitClassifier(Estimator, _VowpalWabbitBase):
    """Binary linear classifier with logistic loss
    (vw/VowpalWabbitClassifier.scala). Labels 0/1 are mapped to VW's -1/+1."""

    labelConversion = Param("labelConversion", "Map 0/1 labels to -1/+1", True,
                            ptype=bool)
    rawPredictionCol = Param("rawPredictionCol", "Raw margin column", "rawPrediction",
                             ptype=str)
    probabilityCol = Param("probabilityCol", "Probability column", "probability",
                           ptype=str)
    predictionCol = Param("predictionCol", "Predicted label column", "prediction",
                          ptype=str)

    def fit(self, df: DataFrame) -> "VowpalWabbitClassificationModel":
        cfg = self._config("logistic")
        convert = ((lambda y: np.where(y > 0, 1.0, -1.0))
                   if self.get("labelConversion") else None)
        ds = self._dataset(df, cfg, convert)
        init = self.get("initialModel")
        w, stats = train_linear(cfg, ds, initial_weights=init, mesh=self._mesh())
        return VowpalWabbitClassificationModel(
            weights=w, numBits=cfg.num_bits, stats=stats,
            featuresCol=self.get("featuresCol"),
            additionalFeatures=self.get("additionalFeatures"),
            rawPredictionCol=self.get("rawPredictionCol"),
            probabilityCol=self.get("probabilityCol"),
            predictionCol=self.get("predictionCol"))


class VowpalWabbitClassificationModel(_VowpalWabbitModelBase):
    rawPredictionCol = Param("rawPredictionCol", "Raw margin column", "rawPrediction",
                             ptype=str)
    probabilityCol = Param("probabilityCol", "Probability column", "probability",
                           ptype=str)
    predictionCol = Param("predictionCol", "Predicted label column", "prediction",
                          ptype=str)

    def transform(self, df: DataFrame) -> DataFrame:
        def score(part):
            raw = self._raw(part)
            p1 = 1.0 / (1.0 + np.exp(-raw))
            part[self.get("rawPredictionCol")] = raw
            part[self.get("probabilityCol")] = p1
            part[self.get("predictionCol")] = (p1 > 0.5).astype(np.float64)
            return part

        return df.map_partitions(score)


class VowpalWabbitRegressor(Estimator, _VowpalWabbitBase):
    """Linear regressor, squared/quantile loss (vw/VowpalWabbitRegressor.scala)."""

    predictionCol = Param("predictionCol", "Prediction column", "prediction", ptype=str)

    def fit(self, df: DataFrame) -> "VowpalWabbitRegressionModel":
        cfg = self._config("squared")
        ds = self._dataset(df, cfg)
        init = self.get("initialModel")
        w, stats = train_linear(cfg, ds, initial_weights=init, mesh=self._mesh())
        return VowpalWabbitRegressionModel(
            weights=w, numBits=cfg.num_bits, stats=stats,
            featuresCol=self.get("featuresCol"),
            additionalFeatures=self.get("additionalFeatures"),
            predictionCol=self.get("predictionCol"))


class VowpalWabbitRegressionModel(_VowpalWabbitModelBase):
    predictionCol = Param("predictionCol", "Prediction column", "prediction", ptype=str)

    def transform(self, df: DataFrame) -> DataFrame:
        def score(part):
            part[self.get("predictionCol")] = self._raw(part)
            return part

        return df.map_partitions(score)
