"""Online linear learning, TPU-native (Vowpal Wabbit parity).

The reference wraps VW's C++ core through JNI (vw/VowpalWabbitBase.scala): per-row
JNI example construction + learn() calls, AllReduce spanning tree for distributed
sync. Here:

  - feature hashing (murmur3) + namespace sparse features   -> featurizer.py
  - per-example adaptive SGD / FTRL as a jitted lax.scan     -> learner.py
  - pipeline stages with VW-args parsing + training stats    -> stages.py
  - distributed: per-shard scan + cross-shard weight average
    via psum (replaces the --span_server spanning tree)      -> learner.py
"""

from .featurizer import VowpalWabbitFeaturizer, VowpalWabbitInteractions
from .learner import LearnerConfig, SparseDataset, train_linear
from .stages import (
    VowpalWabbitClassificationModel,
    VowpalWabbitClassifier,
    VowpalWabbitRegressionModel,
    VowpalWabbitRegressor,
    parse_readable_model,
)

__all__ = [
    "LearnerConfig", "SparseDataset", "VowpalWabbitClassificationModel",
    "VowpalWabbitClassifier", "VowpalWabbitFeaturizer",
    "VowpalWabbitInteractions", "VowpalWabbitRegressionModel",
    "VowpalWabbitRegressor", "parse_readable_model", "train_linear",
]
