"""VowpalWabbitFeaturizer: hash heterogeneous columns into sparse features.

Reference: vw/VowpalWabbitFeaturizer.scala:62-180 + vw/featurizer/*.scala (9
type-dispatched featurizer classes). Behavior:

  - numeric column  -> feature index = hash(colName), value = the number
  - string column   -> index = hash(colName + "=" + value) (categorical), value 1
  - string-array    -> one categorical feature per element
  - map column      -> index = hash(colName + "." + key), value = map value
  - vector column   -> indices = hash(colName) + position (dense passthrough)

Output row = {"indices": int64[], "values": float32[]} struct (sorted, deduped by
summing — VW semantics for repeated indices), masked into ``numBits`` space.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import HasInputCols, HasOutputCol, Param
from ..core.pipeline import Transformer
from ..core.schema import ColType, Schema
from ..ops.hashing import hash_string


def _sort_dedup(idx, val, mask: int, sum_collisions: bool = True
                ) -> Dict[str, np.ndarray]:
    """Mask, sort, and merge duplicate indices (sum, or keep-first when
    ``sum_collisions`` is False — VW's sumCollisions semantics)."""
    if len(idx) == 0:
        return {"indices": np.empty(0, dtype=np.int64),
                "values": np.empty(0, dtype=np.float32)}
    arr_i = np.asarray(idx, dtype=np.int64) & mask
    arr_v = np.asarray(val, dtype=np.float32)
    order = np.argsort(arr_i, kind="stable")
    arr_i, arr_v = arr_i[order], arr_v[order]
    uniq, start = np.unique(arr_i, return_index=True)
    if sum_collisions:
        merged = np.add.reduceat(arr_v, start)
    else:
        merged = arr_v[start]  # first occurrence wins
    return {"indices": uniq, "values": merged.astype(np.float32)}


class VowpalWabbitFeaturizer(Transformer, HasInputCols, HasOutputCol):
    numBits = Param("numBits", "Feature space bits (mask = 2^bits - 1)", 30,
                    lambda v: 1 <= v <= 31, int)
    seed = Param("seed", "Murmur seed", 0, ptype=int)
    stringSplit = Param("stringSplit", "Tokenize strings on whitespace into words",
                        False, ptype=bool)
    sumCollisions = Param("sumCollisions", "Sum values on index collision (else keep)",
                          True, ptype=bool)
    prefixStringsWithColumnName = Param("prefixStringsWithColumnName",
                                        "Prefix hashed strings with the column name",
                                        True, ptype=bool)

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "features")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_cols = list(self.get_or_throw("inputCols"))
        out_col = self.get_or_throw("outputCol")
        seed = self.get("seed")
        mask = (1 << self.get("numBits")) - 1
        split = self.get("stringSplit")
        prefix = self.get("prefixStringsWithColumnName")
        sum_coll = self.get("sumCollisions")

        col_hash = {c: hash_string(c, seed) for c in in_cols}

        def featurize_row(p, i) -> Dict[str, np.ndarray]:
            idx: List[int] = []
            val: List[float] = []
            for c in in_cols:
                v = p[c][i]
                if v is None:
                    continue
                if isinstance(v, (int, float, np.integer, np.floating)) \
                        and not isinstance(v, bool):
                    if v != 0:
                        idx.append(col_hash[c])
                        val.append(float(v))
                elif isinstance(v, bool):
                    if v:
                        idx.append(col_hash[c])
                        val.append(1.0)
                elif isinstance(v, str):
                    tokens = v.split() if split else [v]
                    for t in tokens:
                        key = f"{c}={t}" if prefix else t
                        idx.append(hash_string(key, seed))
                        val.append(1.0)
                elif isinstance(v, dict):
                    for k, mv in v.items():
                        idx.append(hash_string(f"{c}.{k}", seed))
                        val.append(float(mv))
                elif isinstance(v, (list, tuple, np.ndarray)):
                    arr = np.asarray(v)
                    if arr.dtype.kind in "OUS":
                        for t in arr:
                            key = f"{c}={t}" if prefix else str(t)
                            idx.append(hash_string(key, seed))
                            val.append(1.0)
                    else:  # dense vector passthrough: base hash + position
                        base = col_hash[c]
                        nz = np.nonzero(arr)[0]
                        for j in nz:
                            idx.append(base + int(j))
                            val.append(float(arr[j]))
                else:
                    raise TypeError(f"Unsupported value type {type(v)} in col {c!r}")
            return _sort_dedup(idx, val, mask, sum_coll)

        def fn(p):
            n = len(next(iter(p.values()))) if p else 0
            out = np.empty(n, dtype=object)
            for i in range(n):
                out[i] = featurize_row(p, i)
            return out

        return df.with_column(out_col, fn)

    def transform_schema(self, schema: Schema) -> Schema:
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.STRUCT
        return out


class VowpalWabbitInteractions(Transformer, HasInputCols, HasOutputCol):
    """Quadratic/cubic interaction features: hash-combine indices and multiply
    values across the given sparse-feature columns
    (reference vw/VowpalWabbitInteractions.scala)."""

    numBits = Param("numBits", "Feature space bits", 30, lambda v: 1 <= v <= 31, int)
    sumCollisions = Param("sumCollisions", "Sum values on collision", True, ptype=bool)

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "interactions")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_cols = list(self.get_or_throw("inputCols"))
        out_col = self.get_or_throw("outputCol")
        mask = (1 << self.get("numBits")) - 1
        sum_coll = self.get("sumCollisions")

        def fn(p):
            n = len(next(iter(p.values()))) if p else 0
            out = np.empty(n, dtype=object)
            for i in range(n):
                feats = [p[c][i] for c in in_cols]
                if any(f is None for f in feats):
                    out[i] = {"indices": np.empty(0, dtype=np.int64),
                              "values": np.empty(0, dtype=np.float32)}
                    continue
                idx = feats[0]["indices"].astype(np.int64)
                val = feats[0]["values"].astype(np.float64)
                for f in feats[1:]:
                    # VW's interaction hash: i1 * magic + i2 (FNV-style combine)
                    i2 = f["indices"].astype(np.int64)
                    v2 = f["values"].astype(np.float64)
                    idx = ((idx[:, None] * np.int64(67108859) + i2[None, :])
                           .reshape(-1))
                    val = (val[:, None] * v2[None, :]).reshape(-1)
                out[i] = _sort_dedup(idx, val, mask, sum_coll)
            return out

        return df.with_column(out_col, fn)
