"""VowpalWabbitFeaturizer: hash heterogeneous columns into sparse features.

Reference: vw/VowpalWabbitFeaturizer.scala:62-180 + vw/featurizer/*.scala (9
type-dispatched featurizer classes). Hash scheme is reference/VW-exact so feature
spaces interoperate:

  - namespaceHash = murmur(outputCol, seed)  (VowpalWabbitFeaturizer.scala:115)
  - numeric/bool  -> index = murmur(prefixName, namespaceHash), value = the number
    (zero values filtered; NumericFeaturizer/BooleanFeaturizer)
  - string        -> index = murmur(prefixName + value, namespaceHash), value 1
    (StringFeaturizer; prefixName = colName when prefixStringsWithColumnName else "")
  - string-array  -> one such feature per element (StringArrayFeaturizer)
  - map           -> index = murmur(prefixName + key, namespaceHash), value = map
    value (MapFeaturizer); string-valued maps hash key+value with value 1
    (MapStringFeaturizer)
  - vector        -> raw positional indices + values passthrough (VectorFeaturizer)

Output row = {"indices": int64[], "values": float32[]} struct (sorted, deduped by
summing — VW semantics for repeated indices), masked into ``numBits`` space.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import HasInputCols, HasOutputCol, Param
from ..core.pipeline import Transformer
from ..core.schema import ColType, Schema
from ..ops.hashing import hash_string, hash_strings


def _sort_dedup(idx, val, mask: int, sum_collisions: bool = True
                ) -> Dict[str, np.ndarray]:
    """Mask, sort, and merge duplicate indices (sum, or keep-first when
    ``sum_collisions`` is False — VW's sumCollisions semantics)."""
    size = mask + 1  # declared width: densification must not depend on rows
    if len(idx) == 0:
        return {"size": size, "indices": np.empty(0, dtype=np.int64),
                "values": np.empty(0, dtype=np.float32)}
    arr_i = np.asarray(idx, dtype=np.int64) & mask
    arr_v = np.asarray(val, dtype=np.float32)
    order = np.argsort(arr_i, kind="stable")
    arr_i, arr_v = arr_i[order], arr_v[order]
    uniq, start = np.unique(arr_i, return_index=True)
    if sum_collisions:
        merged = np.add.reduceat(arr_v, start)
    else:
        merged = arr_v[start]  # first occurrence wins
    return {"size": size, "indices": uniq, "values": merged.astype(np.float32)}


class VowpalWabbitFeaturizer(Transformer, HasInputCols, HasOutputCol):
    numBits = Param("numBits", "Feature space bits (mask = 2^bits - 1)", 30,
                    lambda v: 1 <= v <= 31, int)
    seed = Param("seed", "Murmur seed", 0, ptype=int)
    stringSplit = Param("stringSplit", "Tokenize strings on whitespace into words",
                        False, ptype=bool)
    stringSplitInputCols = Param(
        "stringSplitInputCols",
        "Columns whose strings are whitespace-tokenized (the reference's "
        "param name, VowpalWabbitFeaturizer.scala; stringSplit=True applies "
        "to every column)", None, ptype=(list, tuple))
    sumCollisions = Param("sumCollisions", "Sum values on index collision (else keep)",
                          True, ptype=bool)
    prefixStringsWithColumnName = Param("prefixStringsWithColumnName",
                                        "Prefix hashed strings with the column name",
                                        True, ptype=bool)

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "features")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_cols = list(self.get_or_throw("inputCols"))
        out_col = self.get_or_throw("outputCol")
        seed = self.get("seed")
        mask = (1 << self.get("numBits")) - 1
        split_all = self.get("stringSplit")
        split_cols = set(self.get("stringSplitInputCols") or ())
        prefix = self.get("prefixStringsWithColumnName")
        sum_coll = self.get("sumCollisions")

        # namespaceHash seeds every per-feature hash (reference :115).
        # NOTE: the reference passes prefixName to ALL featurizers (numeric/bool/map
        # included, VowpalWabbitFeaturizer.scala:65-78), so with
        # prefixStringsWithColumnName=False numeric columns share one index — odd,
        # but reference-exact; leave the flag on (default) for distinct indices.
        ns_hash = hash_string(out_col, seed)
        prefix_of = {c: (c if prefix else "") for c in in_cols}
        col_hash = {c: hash_string(prefix_of[c], ns_hash) for c in in_cols}

        def fn(p):
            n = len(next(iter(p.values()))) if p else 0
            out = np.empty(n, dtype=object)
            # two passes: collect every string needing a hash across the WHOLE
            # partition (placeholder -1 in the row), hash them in ONE batched
            # C++ murmur call, then patch the placeholders. The per-token
            # scalar-murmur loop this replaces was the hot path (~50us/hash
            # through the numpy fallback).
            rows_idx: List[List[int]] = [[] for _ in range(n)]
            rows_val: List[List[float]] = [[] for _ in range(n)]
            strs: List[str] = []
            slots: List[Tuple[int, int]] = []  # (row, position) to patch

            def add_hashed(i, text):
                slots.append((i, len(rows_idx[i])))
                rows_idx[i].append(-1)
                rows_val[i].append(1.0)
                strs.append(text)

            for i in range(n):
                idx, val = rows_idx[i], rows_val[i]
                for c in in_cols:
                    v = p[c][i]
                    pn = prefix_of[c]
                    if v is None:
                        continue
                    if isinstance(v, (bool, np.bool_)):
                        if v:  # BooleanFeaturizer: fires only when true
                            idx.append(col_hash[c])
                            val.append(1.0)
                    elif isinstance(v, (int, float, np.integer, np.floating)):
                        if v != 0:  # NumericFeaturizer filters zeros
                            idx.append(col_hash[c])
                            val.append(float(v))
                    elif isinstance(v, str):
                        split = split_all or c in split_cols
                        for t in (v.split() if split else [v]):
                            add_hashed(i, pn + t)
                    elif isinstance(v, dict):
                        for k, mv in v.items():
                            if isinstance(mv, str):  # MapStringFeaturizer
                                add_hashed(i, pn + str(k) + mv)
                            elif mv != 0:  # MapFeaturizer, zero-filtered
                                slots.append((i, len(idx)))
                                idx.append(-1)
                                val.append(float(mv))
                                strs.append(pn + str(k))
                    elif isinstance(v, (list, tuple, np.ndarray)):
                        arr = np.asarray(v)
                        if arr.dtype.kind in "OUS":
                            for t in arr:  # StringArrayFeaturizer
                                add_hashed(i, pn + str(t))
                        else:  # VectorFeaturizer: raw positional passthrough
                            idx.extend(range(arr.size))
                            val.extend(float(x) for x in arr.ravel())
                    else:
                        raise TypeError(
                            f"Unsupported value type {type(v)} in col {c!r}")

            if strs:
                hashed = hash_strings(strs, ns_hash)
                for (i, j), h in zip(slots, hashed):
                    rows_idx[i][j] = int(h)
            for i in range(n):
                out[i] = _sort_dedup(rows_idx[i], rows_val[i], mask, sum_coll)
            return out

        return df.with_column(out_col, fn)

    def transform_schema(self, schema: Schema) -> Schema:
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.STRUCT
        return out


class VowpalWabbitInteractions(Transformer, HasInputCols, HasOutputCol):
    """Quadratic/cubic interaction features: hash-combine indices and multiply
    values across the given sparse-feature columns
    (reference vw/VowpalWabbitInteractions.scala)."""

    numBits = Param("numBits", "Feature space bits", 30, lambda v: 1 <= v <= 31, int)
    sumCollisions = Param("sumCollisions", "Sum values on collision", True, ptype=bool)

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "interactions")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_cols = list(self.get_or_throw("inputCols"))
        out_col = self.get_or_throw("outputCol")
        mask = (1 << self.get("numBits")) - 1
        sum_coll = self.get("sumCollisions")

        def fn(p):
            n = len(next(iter(p.values()))) if p else 0
            out = np.empty(n, dtype=object)
            for i in range(n):
                feats = [p[c][i] for c in in_cols]
                if any(f is None for f in feats):
                    out[i] = {"size": mask + 1,
                              "indices": np.empty(0, dtype=np.int64),
                              "values": np.empty(0, dtype=np.float32)}
                    continue
                # FNV-1 combine, 32-bit wraparound (VowpalWabbitInteractions.scala:43-57):
                # start idx=0, per column idx = (idx * 16777619) ^ idx_col
                fnv = np.uint32(16777619)
                idx = np.zeros(1, dtype=np.uint32)
                val = np.ones(1, dtype=np.float64)
                with np.errstate(over="ignore"):
                    for f in feats:
                        i2 = f["indices"].astype(np.uint32)
                        v2 = f["values"].astype(np.float64)
                        idx = ((idx[:, None] * fnv) ^ i2[None, :]).reshape(-1)
                        val = (val[:, None] * v2[None, :]).reshape(-1)
                out[i] = _sort_dedup(idx.astype(np.int64), val, mask, sum_coll)
            return out

        return df.with_column(out_col, fn)
