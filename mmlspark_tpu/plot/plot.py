"""Plotting helpers for model evaluation.

Reference parity: src/main/python/mmlspark/plot/plot.py (confusionMatrix +
roc over a Spark/pandas DataFrame, sklearn + matplotlib). Here the metric
math is the framework's own (train/metrics.py — no sklearn dependency) and
the input is the columnar DataFrame, pandas, or raw arrays. matplotlib is
imported lazily so the core library carries no hard dependency on it; pass
``ax`` to compose into an existing figure.
"""

from __future__ import annotations

import itertools
from typing import Any, Optional, Sequence

import numpy as np

from ..core.dataframe import DataFrame
from ..train.metrics import auc_score
from ..train.metrics import confusion_matrix as _confusion_counts


def _columns(df: Any, *names: str):
    """Pull named columns out of a DataFrame / pandas / dict-of-arrays."""
    if isinstance(df, DataFrame):
        data = df.select(*names).collect()
        return tuple(np.asarray(data[n]) for n in names)
    if hasattr(df, "to_numpy") and hasattr(df, "columns"):  # pandas
        return tuple(df[n].to_numpy() for n in names)
    return tuple(np.asarray(df[n]) for n in names)


def roc_curve_points(labels: np.ndarray, scores: np.ndarray):
    """(fpr, tpr, thresholds) by descending-score sweep — the standard
    construction, implemented directly (no sklearn)."""
    labels = np.asarray(labels, dtype=np.float64) > 0.5
    scores = np.asarray(scores, dtype=np.float64)
    if labels.size == 0:
        raise ValueError("roc_curve_points: empty input")
    order = np.argsort(-scores)
    labels, scores = labels[order], scores[order]
    # collapse ties: step only where the threshold actually changes
    distinct = np.r_[np.where(np.diff(scores))[0], labels.size - 1]
    tps = np.cumsum(labels)[distinct]
    fps = (distinct + 1) - tps
    n_pos = max(int(labels.sum()), 1)
    n_neg = max(int((~labels).sum()), 1)
    tpr = np.r_[0.0, tps / n_pos]
    fpr = np.r_[0.0, fps / n_neg]
    thresholds = np.r_[np.inf, scores[distinct]]
    return fpr, tpr, thresholds


def confusionMatrix(df: Any, y_col: str, y_hat_col: str,
                    labels: Sequence[Any], ax: Optional[Any] = None):
    """Render a row-normalized confusion-matrix heatmap with count annotations
    and an accuracy banner (reference plot.py confusionMatrix parity).

    ``labels`` maps class index -> display name (ticks), as in the reference.
    Returns the matplotlib Axes.
    """
    import matplotlib.pyplot as plt

    y, y_hat = _columns(df, y_col, y_hat_col)
    y = np.asarray(y).astype(np.int64)
    y_hat = np.asarray(y_hat).astype(np.int64)
    accuracy = float(np.mean(y == y_hat))
    k = len(labels)
    for name, arr in (("y", y), ("y_hat", y_hat)):
        if arr.size and (arr.min() < 0 or arr.max() >= k):
            raise ValueError(
                f"{name} values must be class indices in [0, {k}) matching "
                f"`labels`; got range [{arr.min()}, {arr.max()}]")
    cm = _confusion_counts(y, y_hat, k)
    row_sums = cm.sum(axis=1, keepdims=True)
    cmn = cm / np.maximum(row_sums, 1)

    if ax is None:
        ax = plt.gca()
    ax.text(-0.3, -0.55, f"Accuracy = {round(accuracy * 100, 1)}%",
            fontsize=14)
    ticks = np.arange(k)
    ax.set_xticks(ticks, labels=[str(v) for v in labels], rotation=0)
    ax.set_yticks(ticks, labels=[str(v) for v in labels], rotation=90)
    im = ax.imshow(cmn, interpolation="nearest", cmap="Blues", vmin=0, vmax=1)
    thresh = 0.1
    for i, j in itertools.product(range(k), range(k)):
        ax.text(j, i, int(cm[i, j]), horizontalalignment="center",
                fontsize=14, color="white" if cmn[i, j] > thresh else "black")
    ax.figure.colorbar(im, ax=ax)
    ax.set_xlabel("Predicted Label", fontsize=14)
    ax.set_ylabel("True Label", fontsize=14)
    return ax


def roc(df: Any, y_col: str, y_hat_col: str, thresh: float = 0.5,
        ax: Optional[Any] = None):
    """Plot the ROC curve of score column ``y_hat_col`` against binarized
    label column ``y_col`` (reference plot.py roc parity; label values are
    binarized at ``thresh`` the same way). Returns the Axes, with the AUC in
    the title (an addition — the reference leaves the plot unannotated).
    """
    import matplotlib.pyplot as plt

    y, scores = _columns(df, y_col, y_hat_col)
    labels = (np.asarray(y, dtype=np.float64) > thresh).astype(np.float64)
    fpr, tpr, _ = roc_curve_points(labels, np.asarray(scores, np.float64))
    if ax is None:
        ax = plt.gca()
    ax.plot(fpr, tpr)
    ax.set_xlabel("False Positive Rate", fontsize=16)
    ax.set_ylabel("True Positive Rate", fontsize=16)
    ax.set_title(f"AUC = {auc_score(labels, np.asarray(scores, np.float64)):.3f}")
    return ax
