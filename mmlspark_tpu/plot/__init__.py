"""Evaluation plotting (reference src/main/python/mmlspark/plot)."""

from .plot import confusionMatrix, roc, roc_curve_points

__all__ = ["confusionMatrix", "roc", "roc_curve_points"]
