"""Recommendation: SAR + ranking adapters/evaluation (reference recommendation/).

SAR (Smart Adaptive Recommendations): time-decayed user-item affinity x
item-item similarity, computed as device matmuls (recommendation/SAR.scala:66-120,
SARModel.scala:23-169). Ranking evaluation: NDCG@k / MAP / precision@k / recall@k
(RankingEvaluator.scala:15-152), per-user train/validation splitting
(RankingTrainValidationSplit.scala, RankingAdapter.scala).
"""

from .indexer import RecommendationIndexer, RecommendationIndexerModel
from .sar import SAR, SARModel
from .ranking import (
    RankingAdapter,
    RankingAdapterModel,
    RankingEvaluator,
    RankingTrainValidationSplit,
    RankingTrainValidationSplitModel,
)

__all__ = [
    "RankingAdapter", "RankingAdapterModel", "RankingEvaluator",
    "RankingTrainValidationSplit", "RankingTrainValidationSplitModel",
    "RecommendationIndexer", "RecommendationIndexerModel", "SAR", "SARModel",
]
