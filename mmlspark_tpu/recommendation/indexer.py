"""User/item id indexing for recommenders (reference
recommendation/RecommendationIndexer.scala)."""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param
from ..core.pipeline import Estimator, Model


class RecommendationIndexer(Estimator):
    userInputCol = Param("userInputCol", "Raw user id column", None, ptype=str)
    userOutputCol = Param("userOutputCol", "Indexed user column", None, ptype=str)
    itemInputCol = Param("itemInputCol", "Raw item id column", None, ptype=str)
    itemOutputCol = Param("itemOutputCol", "Indexed item column", None, ptype=str)
    ratingCol = Param("ratingCol", "Rating column (passthrough)", None, ptype=str)

    def fit(self, df: DataFrame) -> "RecommendationIndexerModel":
        users = sorted({str(v) for v in df.column(self.get_or_throw("userInputCol"))})
        items = sorted({str(v) for v in df.column(self.get_or_throw("itemInputCol"))})
        return RecommendationIndexerModel(
            userInputCol=self.get("userInputCol"),
            userOutputCol=self.get("userOutputCol"),
            itemInputCol=self.get("itemInputCol"),
            itemOutputCol=self.get("itemOutputCol"),
            userMap={u: i for i, u in enumerate(users)},
            itemMap={t: i for i, t in enumerate(items)})


class RecommendationIndexerModel(Model):
    userInputCol = Param("userInputCol", "Raw user id column", None, ptype=str)
    userOutputCol = Param("userOutputCol", "Indexed user column", None, ptype=str)
    itemInputCol = Param("itemInputCol", "Raw item id column", None, ptype=str)
    itemOutputCol = Param("itemOutputCol", "Indexed item column", None, ptype=str)
    userMap = ComplexParam("userMap", "user -> index")
    itemMap = ComplexParam("itemMap", "item -> index")

    def transform(self, df: DataFrame) -> DataFrame:
        umap = self.get_or_throw("userMap")
        imap = self.get_or_throw("itemMap")
        uin, uout = self.get_or_throw("userInputCol"), self.get_or_throw("userOutputCol")
        iin, iout = self.get_or_throw("itemInputCol"), self.get_or_throw("itemOutputCol")
        out = df.with_column(uout, lambda p: np.array(
            [float(umap.get(str(v), -1)) for v in p[uin]]))
        return out.with_column(iout, lambda p: np.array(
            [float(imap.get(str(v), -1)) for v in p[iin]]))

    def recover_user(self, idx: int) -> Any:
        inv = {v: k for k, v in self.get_or_throw("userMap").items()}
        return inv.get(idx)

    def recover_item(self, idx: int) -> Any:
        inv = {v: k for k, v in self.get_or_throw("itemMap").items()}
        return inv.get(idx)
