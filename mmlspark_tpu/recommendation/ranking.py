"""Ranking adapters, evaluation, and train/validation splitting.

Reference: recommendation/RankingAdapter.scala (wrap a recommender so transform
emits per-user (recommended items, ground-truth items) for evaluation),
recommendation/RankingEvaluator.scala:15-152 (NDCG@k, MAP, precision@k,
recall@k via AdvancedRankingMetrics), RankingTrainValidationSplit.scala:24-330
(per-user holdout split with min-ratings filtering).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param
from ..core.pipeline import Estimator, Evaluator, Model


class RankingEvaluator(Evaluator):
    k = Param("k", "Cutoff for @k metrics", 10, lambda v: v > 0, int)
    metricName = Param("metricName", "ndcgAt | map | precisionAtk | recallAtK",
                       "ndcgAt",
                       lambda v: v in ("ndcgAt", "map", "precisionAtk", "recallAtK"),
                       str)
    predictionCol = Param("predictionCol", "Recommended-items array column",
                          "recommendations", ptype=str)
    labelCol = Param("labelCol", "Ground-truth items array column", "label",
                     ptype=str)

    def evaluate(self, df: DataFrame) -> float:
        data = df.collect()
        preds = data[self.get("predictionCol")]
        truths = data[self.get("labelCol")]
        k = self.get("k")
        metric = self.get("metricName")
        vals = []
        for rec, truth in zip(preds, truths):
            if truth is None or len(truth) == 0:
                continue
            rec = list(np.asarray(rec).astype(np.int64)[:k]) if rec is not None else []
            truth_set = set(np.asarray(truth).astype(np.int64).tolist())
            if metric == "precisionAtk":
                vals.append(len(set(rec) & truth_set) / max(len(rec), 1))
            elif metric == "recallAtK":
                vals.append(len(set(rec) & truth_set) / len(truth_set))
            elif metric == "ndcgAt":
                dcg = sum(1.0 / np.log2(i + 2) for i, r in enumerate(rec)
                          if r in truth_set)
                ideal = sum(1.0 / np.log2(i + 2)
                            for i in range(min(len(truth_set), k)))
                vals.append(dcg / ideal if ideal > 0 else 0.0)
            elif metric == "map":
                hits, ap = 0, 0.0
                for i, r in enumerate(rec):
                    if r in truth_set:
                        hits += 1
                        ap += hits / (i + 1)
                vals.append(ap / min(len(truth_set), k) if truth_set else 0.0)
        return float(np.mean(vals)) if vals else 0.0

    def is_larger_better(self) -> bool:
        return True


class RankingAdapter(Estimator):
    """Fit a recommender; transform emits per-user (recommendations, label)
    rows ready for RankingEvaluator (RankingAdapter.scala)."""

    recommender = ComplexParam("recommender", "Inner recommender estimator")
    k = Param("k", "Recommendations per user", 10, lambda v: v > 0, int)
    userCol = Param("userCol", "User column", "user", ptype=str)
    itemCol = Param("itemCol", "Item column", "item", ptype=str)
    ratingCol = Param("ratingCol", "Rating column", "rating", ptype=str)
    minRatingsPerUser = Param("minRatingsPerUser", "Filter sparse users", 1,
                              ptype=int)

    def fit(self, df: DataFrame) -> "RankingAdapterModel":
        rec = self.get_or_throw("recommender").copy()
        for p in ("userCol", "itemCol", "ratingCol"):
            if rec.has_param(p):
                rec.set(p, self.get(p))
        model = rec.fit(df)
        return RankingAdapterModel(
            recommenderModel=model, k=self.get("k"),
            userCol=self.get("userCol"), itemCol=self.get("itemCol"))


class RankingAdapterModel(Model):
    recommenderModel = ComplexParam("recommenderModel", "Fitted recommender")
    k = Param("k", "Recommendations per user", 10, ptype=int)
    userCol = Param("userCol", "User column", "user", ptype=str)
    itemCol = Param("itemCol", "Item column", "item", ptype=str)

    def transform(self, df: DataFrame) -> DataFrame:
        """df = held-out interactions; emit per-user recs + ground truth."""
        model = self.get_or_throw("recommenderModel")
        recs = model.recommend_for_all_users(self.get("k"), remove_seen=True)
        rec_data = recs.collect()
        ucol = self.get("userCol")
        rec_of_user = {int(u): r for u, r in
                       zip(rec_data[ucol], rec_data["recommendations"])}
        data = df.collect()
        users = np.asarray(data[ucol], dtype=np.int64)
        items = np.asarray(data[self.get("itemCol")], dtype=np.int64)
        truth: Dict[int, List[int]] = {}
        for u, i in zip(users, items):
            truth.setdefault(int(u), []).append(int(i))
        rows = []
        for u, t in sorted(truth.items()):
            rows.append({
                self.get("userCol"): u,
                "recommendations": np.asarray(
                    rec_of_user.get(u, np.empty(0)), dtype=np.int64),
                "label": np.asarray(t, dtype=np.int64),
            })
        return DataFrame.from_rows(rows)


class RankingTrainValidationSplit(Estimator):
    """Per-user train/validation split + fit + evaluate
    (RankingTrainValidationSplit.scala:24-330)."""

    estimator = ComplexParam("estimator", "Recommender (or RankingAdapter)")
    evaluator = ComplexParam("evaluator", "RankingEvaluator")
    trainRatio = Param("trainRatio", "Fraction of each user's events for training",
                       0.75, lambda v: 0 < v < 1, float)
    userCol = Param("userCol", "User column", "user", ptype=str)
    itemCol = Param("itemCol", "Item column", "item", ptype=str)
    ratingCol = Param("ratingCol", "Rating column", "rating", ptype=str)
    minRatingsPerUser = Param("minRatingsPerUser", "Drop users with fewer events", 2,
                              lambda v: v >= 1, int)
    seed = Param("seed", "Split seed", 0, ptype=int)

    def split(self, df: DataFrame) -> Tuple[DataFrame, DataFrame]:
        """Stratified-by-user split (public for parity with the reference API)."""
        data = df.collect()
        ucol = self.get("userCol")
        users = np.asarray(data[ucol], dtype=np.int64)
        n = len(users)
        rng = np.random.default_rng(self.get("seed"))
        ratio = self.get("trainRatio")
        min_r = self.get("minRatingsPerUser")
        in_train = np.zeros(n, dtype=bool)
        keep = np.ones(n, dtype=bool)
        for u in np.unique(users):
            idx = np.where(users == u)[0]
            if len(idx) < min_r:
                keep[idx] = False
                continue
            perm = rng.permutation(len(idx))
            n_train = max(1, int(round(len(idx) * ratio)))
            n_train = min(n_train, len(idx) - 1)  # always hold out >= 1
            in_train[idx[perm[:n_train]]] = True
        train = {k: v[in_train & keep] for k, v in data.items()}
        val = {k: v[~in_train & keep] for k, v in data.items()}
        return DataFrame([train]), DataFrame([val])

    def fit(self, df: DataFrame) -> "RankingTrainValidationSplitModel":
        train, val = self.split(df)
        est = self.get_or_throw("estimator")
        if not isinstance(est, RankingAdapter):
            est = RankingAdapter(recommender=est, userCol=self.get("userCol"),
                                 itemCol=self.get("itemCol"),
                                 ratingCol=self.get("ratingCol"))
        model = est.fit(train)
        evaluator = self.get("evaluator") or RankingEvaluator()
        metric = evaluator.evaluate(model.transform(val))
        return RankingTrainValidationSplitModel(
            bestModel=model, validationMetric=float(metric))


class RankingTrainValidationSplitModel(Model):
    bestModel = ComplexParam("bestModel", "Fitted ranking adapter model")
    validationMetric = Param("validationMetric", "Held-out metric", None, ptype=float)

    def transform(self, df: DataFrame) -> DataFrame:
        return self.get_or_throw("bestModel").transform(df)
