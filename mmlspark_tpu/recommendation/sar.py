"""SAR — Smart Adaptive Recommendations, device-matmul formulation.

Reference: recommendation/SAR.scala:66-120 (time-decayed user-item affinity),
item-item co-occurrence similarity (jaccard / lift / cooccurrence) via sparse
matrix multiply, SARModel.recommendForAllUsers (SARModel.scala:23-169).

TPU design: the co-occurrence C = B^T B and the scoring A @ S are dense
f32 MXU matmuls (Precision.HIGHEST — similarity cells and recommendation
scores are gated against the reference's committed TLC fixtures at tight
tolerances, see tests/test_benchmarks.py; catalogs at recommender-benchmark
scale make the extra MXU passes immaterial).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param
from ..core.pipeline import Estimator, Model
from ..core.schema import ColType, Schema

SUPPORTED_SIMILARITIES = ("cooccurrence", "jaccard", "lift")


class SAR(Estimator):
    userCol = Param("userCol", "Indexed user column", "user", ptype=str)
    itemCol = Param("itemCol", "Indexed item column", "item", ptype=str)
    ratingCol = Param("ratingCol", "Rating column", "rating", ptype=str)
    timeCol = Param("timeCol", "Event-time column (unix seconds; optional)", None,
                    ptype=str)
    supportThreshold = Param("supportThreshold",
                             "Min co-occurrence count to keep similarity", 4,
                             lambda v: v >= 0, int)
    similarityFunction = Param("similarityFunction",
                               "cooccurrence | jaccard | lift", "jaccard",
                               lambda v: v in SUPPORTED_SIMILARITIES, str)
    timeDecayCoeff = Param("timeDecayCoeff", "Half-life in days for affinity decay",
                           30, lambda v: v > 0, int)
    startTime = Param("startTime", "Reference time (unix seconds; default max)",
                      None, ptype=float)

    def fit(self, df: DataFrame) -> "SARModel":
        import jax
        import jax.numpy as jnp

        data = df.collect()
        users = np.asarray(data[self.get_or_throw("userCol")], dtype=np.int64)
        items = np.asarray(data[self.get_or_throw("itemCol")], dtype=np.int64)
        ratings = (np.asarray(data[self.get("ratingCol")], dtype=np.float64)
                   if self.get("ratingCol") in df.schema
                   else np.ones(len(users)))
        n_users = int(users.max()) + 1 if len(users) else 0
        n_items = int(items.max()) + 1 if len(items) else 0

        # --- user-item affinity with time decay (SAR.scala:66-120)
        if self.get("timeCol") and self.get("timeCol") in df.schema:
            t = np.asarray(data[self.get("timeCol")], dtype=np.float64)
            t_ref = self.get("startTime") or float(t.max())
            half_life_s = self.get("timeDecayCoeff") * 86400.0
            decay = np.power(2.0, -(t_ref - t) / half_life_s)
        else:
            decay = np.ones(len(users))
        affinity = np.zeros((n_users, n_items), dtype=np.float32)
        np.add.at(affinity, (users, items), (ratings * decay).astype(np.float32))

        # --- item-item co-occurrence on device: C = B^T B
        binary = (affinity > 0).astype(np.float32)

        @jax.jit
        def cooccur(b):
            # full-f32 MXU passes: co-occurrence counts feed exact-parity
            # similarity gates (tests/test_benchmarks.py vs the reference's
            # TLC fixtures); 0/1 inputs make the f32 accumulation exact
            return jnp.dot(b.T, b, precision=jax.lax.Precision.HIGHEST,
                           preferred_element_type=jnp.float32)

        C = np.asarray(cooccur(binary))
        diag = np.diag(C).copy()
        thresh = float(self.get("supportThreshold"))
        kind = self.get("similarityFunction")
        if kind == "cooccurrence":
            S = C.copy()
        elif kind == "jaccard":
            denom = diag[:, None] + diag[None, :] - C
            S = np.where(denom > 0, C / np.maximum(denom, 1e-12), 0.0)
        else:  # lift
            denom = diag[:, None] * diag[None, :]
            S = np.where(denom > 0, C / np.maximum(denom, 1e-12), 0.0)
        S = np.where(C >= thresh, S, 0.0).astype(np.float32)
        np.fill_diagonal(S, np.where(diag >= thresh, S.diagonal(), 0.0))

        return SARModel(
            userCol=self.get("userCol"), itemCol=self.get("itemCol"),
            ratingCol=self.get("ratingCol"),
            userAffinity=affinity, itemSimilarity=S)


class SARModel(Model):
    userCol = Param("userCol", "Indexed user column", "user", ptype=str)
    itemCol = Param("itemCol", "Indexed item column", "item", ptype=str)
    ratingCol = Param("ratingCol", "Rating column", "rating", ptype=str)
    userAffinity = ComplexParam("userAffinity", "[U,I] affinity matrix")
    itemSimilarity = ComplexParam("itemSimilarity", "[I,I] similarity matrix")

    def _scores(self, remove_seen: bool = True) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        A = np.asarray(self.get_or_throw("userAffinity"), dtype=np.float32)
        S = np.asarray(self.get_or_throw("itemSimilarity"), dtype=np.float32)

        @jax.jit
        def score(a, s):
            # HIGHEST: recommendation scores are compared at 1e-3 absolute
            # against the reference's committed predictions; bf16 rounding
            # of the affinities costs more than that
            return jnp.dot(a, s, precision=jax.lax.Precision.HIGHEST,
                           preferred_element_type=jnp.float32)

        scores = np.asarray(score(A, S))
        if remove_seen:
            scores = np.where(A > 0, -np.inf, scores)
        return scores

    def recommend_for_all_users(self, num_items: int = 10,
                                remove_seen: bool = True) -> DataFrame:
        """One row per user: {user, recommendations: [itemIds], ratings: [scores]}
        (SARModel.recommendForAllUsers parity)."""
        scores = self._scores(remove_seen)
        n_users, n_items_total = scores.shape
        k = min(num_items, n_items_total)
        top = np.argsort(-scores, axis=1)[:, :k]
        top_scores = np.take_along_axis(scores, top, axis=1)
        recs = np.empty(n_users, dtype=object)
        vals = np.empty(n_users, dtype=object)
        for u in range(n_users):
            valid = np.isfinite(top_scores[u])
            recs[u] = top[u][valid].astype(np.int64)
            vals[u] = top_scores[u][valid].astype(np.float64)
        return DataFrame([{
            self.get("userCol"): np.arange(n_users, dtype=np.int64),
            "recommendations": recs,
            "ratings": vals,
        }])

    def transform(self, df: DataFrame) -> DataFrame:
        """Score (user, item) pairs: predicted affinity-weighted similarity."""
        scores = self._scores(remove_seen=False)
        ucol, icol = self.get("userCol"), self.get("itemCol")

        def fn(p):
            us = np.asarray(p[ucol], dtype=np.int64)
            its = np.asarray(p[icol], dtype=np.int64)
            ok = (us >= 0) & (us < scores.shape[0]) & \
                 (its >= 0) & (its < scores.shape[1])
            out = np.zeros(len(us), dtype=np.float64)
            out[ok] = scores[us[ok], its[ok]]
            return out

        return df.with_column("prediction", fn)
