"""Fuzzing framework: auto-derived experiment + serialization tests per stage.

Reference: core/test/fuzzing/Fuzzing.scala:16-205 — every stage suite provides
``testObjects(): Seq[TestObject[S]]`` and automatically gets ExperimentFuzzing
(run fit+transform) and SerializationFuzzing (save/load the stage, the fitted
model, and pipelines thereof; assert identical outputs). FuzzingTest.scala then
reflects over the whole jar and *fails if any stage lacks a fuzzing suite* —
coverage enforcement by reflection. tests/test_fuzzing.py is this package's
FuzzingTest: it walks ``registered_stages()`` and fails listing any concrete
stage without a declared ``TestObject`` fixture or an explicit waiver.
"""

from __future__ import annotations

import dataclasses
import importlib
import pkgutil
from typing import Callable, Dict, List, Optional, Sequence, Type

import numpy as np

from ..core.dataframe import DataFrame
from ..core.pipeline import (
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    PipelineStage,
    Transformer,
    registered_stages,
)

# Pipeline/PipelineModel must stay registered (nested-pipeline load resolves
# them by name); the other bases are kept out of the registry via _abstract.
_FRAMEWORK_BASES = (Pipeline, PipelineModel)


@dataclasses.dataclass
class TestObject:
    """One fuzzable configuration of a stage (Fuzzing.scala TestObject).

    ``level``:
      - "full": fit (if estimator) + transform + save/load + output equality
      - "serialize": construct + save/load + param equality only (stages whose
        transform needs an external service; the reference runs these suites
        against live Azure endpoints, which we don't have)
    ``covers``: extra stage-class names this object's run covers (e.g. the
    model class produced by fitting an estimator).
    """

    __test__ = False  # not a pytest class despite the name

    stage: PipelineStage
    fit_df: Optional[DataFrame] = None
    transform_df: Optional[DataFrame] = None
    level: str = "full"
    covers: Sequence[str] = ()
    # columns whose values may legitimately differ between runs (e.g. timing)
    unstable_cols: Sequence[str] = ()


def discover_all_stages() -> List[Type[PipelineStage]]:
    """Import every mmlspark_tpu submodule and return all concrete registered
    stage classes (FuzzingTest.scala's jar reflection equivalent)."""
    import mmlspark_tpu

    for m in pkgutil.walk_packages(mmlspark_tpu.__path__, "mmlspark_tpu."):
        importlib.import_module(m.name)
    classes = sorted(set(registered_stages().values()),
                     key=lambda c: (c.__module__, c.__name__))
    # only library stages: user/test-defined stages also auto-register (by
    # design, for their own persistence) but aren't ours to enforce
    return [c for c in classes if c not in _FRAMEWORK_BASES
            and c.__module__.startswith("mmlspark_tpu.")]


def _run(stage: PipelineStage, fit_df, transform_df):
    """fit (if estimator) then transform; returns (model_or_none, output_df)."""
    model = None
    out = None
    if isinstance(stage, Estimator):
        model = stage.fit(fit_df if fit_df is not None else transform_df)
        runner = model
    else:
        runner = stage
    if transform_df is not None and isinstance(runner, Transformer):
        out = runner.transform(transform_df)
    return model, out


def _df_equal(a: DataFrame, b: DataFrame, eps: float, skip=()):
    assert a.columns == b.columns, f"{a.columns} != {b.columns}"
    ca, cb = a.collect(), b.collect()
    for name in a.columns:
        if name in skip:
            continue
        x, y = ca[name], cb[name]
        assert len(x) == len(y), f"{name}: {len(x)} vs {len(y)}"
        if getattr(x, "dtype", None) is not None and x.dtype.kind in "fc":
            np.testing.assert_allclose(x, y, atol=eps, err_msg=name)
        else:
            for i, (u, v) in enumerate(zip(x, y)):
                _value_equal(u, v, eps, f"{name}[{i}]")


def _value_equal(u, v, eps: float, where: str):
    """Tolerant recursive equality over rows: arrays, dicts (structs), lists."""
    if isinstance(u, dict) and isinstance(v, dict):
        assert set(u) == set(v), f"{where}: keys {set(u)} != {set(v)}"
        for k in u:
            _value_equal(u[k], v[k], eps, f"{where}.{k}")
    elif isinstance(u, (np.ndarray,)) or isinstance(v, (np.ndarray,)):
        ua, va = np.asarray(u), np.asarray(v)
        assert ua.shape == va.shape, f"{where}: {ua.shape} != {va.shape}"
        if ua.dtype.kind in "fc" or va.dtype.kind in "fc":
            # no lossy cast: complex stays complex, ints promote exactly
            np.testing.assert_allclose(ua, va, atol=eps, err_msg=where)
        else:
            np.testing.assert_array_equal(ua, va, err_msg=where)
    elif isinstance(u, (list, tuple)) and isinstance(v, (list, tuple)):
        assert len(u) == len(v), f"{where}: len {len(u)} != {len(v)}"
        for j, (a, b) in enumerate(zip(u, v)):
            _value_equal(a, b, eps, f"{where}[{j}]")
    elif isinstance(u, float) and isinstance(v, float):
        assert abs(u - v) <= eps or (np.isnan(u) and np.isnan(v)), \
            f"{where}: {u!r} != {v!r}"
    else:
        assert u == v, f"{where}: {u!r} != {v!r}"


def experiment_fuzz(obj: TestObject, eps: float = 1e-4) -> None:
    """ExperimentFuzzing (Fuzzing.scala:75-103): the stage must fit/transform
    its declared data without error, twice, deterministically."""
    if obj.level != "full":
        return
    model1, out1 = _run(obj.stage, obj.fit_df, obj.transform_df)
    if type(obj.stage).__name__ not in obj.covers and model1 is not None:
        got = type(model1).__name__
        assert got in obj.covers, \
            f"fixture for {type(obj.stage).__name__} produced {got}, " \
            f"not declared in covers={list(obj.covers)}"
    _, out2 = _run(obj.stage, obj.fit_df, obj.transform_df)
    if out1 is not None and out2 is not None:
        _df_equal(out1, out2, eps, skip=obj.unstable_cols)


def serialization_fuzz(obj: TestObject, tmpdir: str, eps: float = 1e-4) -> None:
    """SerializationFuzzing (Fuzzing.scala:105-181): save/load the stage (and
    the fitted model), assert outputs (or params) survive the round trip."""
    stage = obj.stage
    p1 = f"{tmpdir}/stage"
    stage.save(p1)
    loaded = PipelineStage.load(p1)
    assert type(loaded) is type(stage)

    if obj.level != "full":
        # param-level equality for service stages
        for name, p in stage.params().items():
            if stage.is_set(name) and not p.is_complex:
                assert loaded.get(name) == stage.get(name), name
        return

    model, out = _run(stage, obj.fit_df, obj.transform_df)
    _, out_l = _run(loaded, obj.fit_df, obj.transform_df)
    if out is not None and out_l is not None:
        _df_equal(out, out_l, eps, skip=obj.unstable_cols)

    if model is not None and obj.transform_df is not None \
            and isinstance(model, Transformer):
        p2 = f"{tmpdir}/model"
        model.save(p2)
        model_l = PipelineStage.load(p2)
        _df_equal(out, model_l.transform(obj.transform_df), eps,
                  skip=obj.unstable_cols)
