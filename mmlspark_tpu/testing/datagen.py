"""Synthetic dataset generation for fuzz/property tests.

Re-designs the reference's datagen framework
(core/test/datagen/GenerateDataset.scala:15-112, DatasetOptions.scala:28-52,
DatasetConstraints.scala:11-62, GenerateRow.scala:29-53) for the columnar
substrate: instead of per-row RDD generators, whole columns are drawn
vectorized from a seeded ``numpy.random.Generator``, and missing values are
injected column-wise. The option space matches the reference — per-column
(column-kind x data-kind) choices sampled from a constrained set, optional
missing-value injection with a target rate — plus vector columns and
categorical columns, both of which the reference left as a TODO
(DatasetOptions.scala:12 "TODO: add Categorical, DenseVector,
SparseVector"; categorical is opt-in via EXTENDED_DATA_KINDS so seeded
draws from the default kind set are unchanged).

Used by tests/test_fuzzing.py to drive featurize stages over randomly-shaped
inputs, the way VerifyGenerateDataset + the featurize fuzz suites use it in
the reference.
"""

from __future__ import annotations

import dataclasses
import string
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..core.dataframe import DataFrame

#: data kinds the generator can draw (reference DataOptions.scala:17-20;
#: date/timestamp are drawn as numpy datetime64 -> object columns)
DATA_KINDS = ("string", "int", "double", "boolean", "date", "timestamp",
              "byte", "short")

#: extension kinds resolving the reference TODO (DatasetOptions.scala:12
#: "TODO: add Categorical, DenseVector, SparseVector"): ``categorical``
#: draws from a small per-column vocabulary (``cat_0..cat_{k-1}`` strings),
#: the low-cardinality shape ValueIndexer/observability mixed-dtype tests
#: need. Kept OUT of DATA_KINDS so the default sampling distribution — and
#: every seeded draw existing suites depend on — is unchanged; opt in per
#: column via ``ColumnOptions(data_kinds=("categorical", ...))``.
EXTENDED_DATA_KINDS = DATA_KINDS + ("categorical",)

#: categorical vocabulary size range drawn per column
CATEGORICAL_CARDINALITY = (2, 8)

#: column kinds (reference ColumnOptions — Scalar only; vector is our
#: extension for the VectorAssembler/featurize paths)
COLUMN_KINDS = ("scalar", "vector")


@dataclasses.dataclass(frozen=True)
class MissingOptions:
    """Missing-value injection (DatasetMissingValuesGenOptions parity).

    ``percent_missing``: fraction of cells nulled per eligible column.
    ``data_kinds``: kinds eligible for injection (empty = none).
    """

    percent_missing: float = 0.0
    data_kinds: Tuple[str, ...] = ()

    @property
    def has_missing(self) -> bool:
        return self.percent_missing > 0 and bool(self.data_kinds)


@dataclasses.dataclass(frozen=True)
class ColumnOptions:
    """Constrains one column's generation (DatasetOptions parity): the actual
    (column kind, data kind) pair is sampled per column from these sets."""

    data_kinds: Tuple[str, ...] = DATA_KINDS
    column_kinds: Tuple[str, ...] = ("scalar",)
    missing: MissingOptions = MissingOptions()

    def __post_init__(self):
        bad = set(self.data_kinds) - set(EXTENDED_DATA_KINDS)
        if bad:
            raise ValueError(f"unknown data kinds: {sorted(bad)}")
        bad = set(self.column_kinds) - set(COLUMN_KINDS)
        if bad:
            raise ValueError(f"unknown column kinds: {sorted(bad)}")


@dataclasses.dataclass(frozen=True)
class GenConstraints:
    """Dataset-level constraints (BasicDatasetGenConstraints parity)."""

    num_rows: int
    num_cols: int
    slots_per_col: Tuple[int, ...] = ()   # vector widths, cycled per column
    randomize_column_names: bool = True


@dataclasses.dataclass(frozen=True)
class RandomGenConstraints:
    """Ranges resolved to concrete constraints with the run's rng
    (RandomDatasetGenConstraints parity)."""

    min_rows: int = 1
    max_rows: int = 100
    min_cols: int = 1
    max_cols: int = 10
    min_slots: int = 1
    max_slots: int = 8

    def resolve(self, rng: np.random.Generator) -> GenConstraints:
        cols = int(rng.integers(self.min_cols, self.max_cols + 1))
        return GenConstraints(
            num_rows=int(rng.integers(self.min_rows, self.max_rows + 1)),
            num_cols=cols,
            slots_per_col=tuple(int(rng.integers(self.min_slots,
                                                 self.max_slots + 1))
                                for _ in range(cols)))


_ALPHABET = np.array(list(string.ascii_letters + string.digits))


def _random_name(rng: np.random.Generator) -> str:
    n = int(rng.integers(4, 12))
    return "col_" + "".join(rng.choice(_ALPHABET, size=n))


def _draw_scalar(kind: str, n: int, rng: np.random.Generator) -> np.ndarray:
    if kind == "string":
        out = np.empty(n, dtype=object)
        for i in range(n):
            ln = int(rng.integers(0, 16))
            out[i] = "".join(rng.choice(_ALPHABET, size=ln))
        return out
    if kind == "int":
        return rng.integers(np.iinfo(np.int32).min, np.iinfo(np.int32).max,
                            size=n, dtype=np.int64).astype(np.int32)
    if kind == "double":
        return rng.standard_normal(n) * 1e3
    if kind == "boolean":
        return rng.integers(0, 2, size=n).astype(bool)
    if kind == "byte":
        return rng.integers(-128, 128, size=n, dtype=np.int64).astype(np.int32)
    if kind == "short":
        return rng.integers(-32768, 32768, size=n,
                            dtype=np.int64).astype(np.int32)
    if kind in ("date", "timestamp"):
        # epoch range ~1970..2100; dates floor to days
        secs = rng.integers(0, 4_102_444_800, size=n)
        out = np.empty(n, dtype=object)
        for i in range(n):
            ts = np.datetime64(int(secs[i]), "s")
            out[i] = ts.astype("datetime64[D]") if kind == "date" else ts
        return out
    if kind == "categorical":
        # low-cardinality string vocabulary (the reference TODO's
        # Categorical): k levels drawn once per column, then sampled per
        # row — every level name is stable across seeds for a fixed rng
        # stream, so ValueIndexer round-trips are reproducible
        lo, hi = CATEGORICAL_CARDINALITY
        k = int(rng.integers(lo, hi + 1))
        levels = np.array([f"cat_{i}" for i in range(k)], dtype=object)
        return levels[rng.integers(0, k, size=n)]
    raise ValueError(f"unknown data kind {kind!r}")


def _inject_missing(col: np.ndarray, kind: str, frac: float,
                    rng: np.random.Generator) -> np.ndarray:
    mask = rng.random(len(col)) < frac
    if not mask.any():
        return col
    if kind == "double" and col.dtype != object:
        out = col.astype(np.float64)
        out[mask] = np.nan
        return out
    out = col.astype(object)
    out[mask] = None
    return out


def generate_dataset(constraints, seed: int,
                     per_column: Optional[Dict[int, ColumnOptions]] = None,
                     default: Optional[ColumnOptions] = None,
                     num_partitions: int = 1) -> DataFrame:
    """Generate a random DataFrame (GenerateDataset.generateDatasetFromOptions
    parity). ``per_column`` maps 0-based column index -> ColumnOptions;
    unmapped columns use ``default`` (reference default: all kinds, 50%
    missing eligible everywhere — we default to no missing unless asked).
    """
    rng = np.random.default_rng(seed)
    if isinstance(constraints, RandomGenConstraints):
        constraints = constraints.resolve(rng)
    per_column = per_column or {}
    default = default or ColumnOptions()

    data: Dict[str, np.ndarray] = {}
    for ci in range(constraints.num_cols):
        opts = per_column.get(ci, default)
        kind = str(rng.choice(opts.data_kinds))
        ckind = str(rng.choice(opts.column_kinds))
        name = (_random_name(rng) if constraints.randomize_column_names
                else f"col_{ci}")
        while name in data:  # random names must stay unique
            name = _random_name(rng)
        n = constraints.num_rows
        if ckind == "vector":
            slots = (constraints.slots_per_col[ci % len(constraints.slots_per_col)]
                     if constraints.slots_per_col else 4)
            col = np.empty(n, dtype=object)
            for i in range(n):
                col[i] = rng.standard_normal(slots)
        else:
            col = _draw_scalar(kind, n, rng)
        if opts.missing.has_missing and kind in opts.missing.data_kinds \
                and ckind == "scalar":
            col = _inject_missing(col, kind, opts.missing.percent_missing, rng)
        data[name] = col
    return DataFrame.from_dict(data, num_partitions=num_partitions)


def options_from_schema(df: DataFrame) -> Dict[int, ColumnOptions]:
    """Derive per-column options matching an existing DataFrame's schema
    (GenerateDataset.getOptionsFromSchema parity), so ``generate_like`` can
    draw fresh data in the same shape."""
    from ..core.schema import ColType

    mapping = {
        ColType.STRING: "string", ColType.INT32: "int", ColType.INT64: "int",
        ColType.FLOAT32: "double", ColType.FLOAT64: "double",
        ColType.BOOL: "boolean",
    }
    out: Dict[int, ColumnOptions] = {}
    for i, name in enumerate(df.columns):
        ctype = df.schema[name]
        if ctype in (ColType.VECTOR, ColType.TENSOR):
            out[i] = ColumnOptions(column_kinds=("vector",))
        else:
            out[i] = ColumnOptions(
                data_kinds=(mapping.get(ctype, "string"),))
    return out


def generate_like(df: DataFrame, num_rows: int, seed: int,
                  num_partitions: int = 1) -> DataFrame:
    """Fresh random data with ``df``'s column names and kinds — the
    schema-driven entry the reference's fuzz suites use."""
    opts = options_from_schema(df)
    gen = generate_dataset(
        GenConstraints(num_rows=num_rows, num_cols=len(df.columns),
                       randomize_column_names=False),
        seed=seed, per_column=opts, num_partitions=num_partitions)
    # rebuild with the target names positionally (renaming in place could
    # collide when df's own names overlap the col_i placeholders)
    data = {new: gen.column(old)
            for old, new in zip(gen.columns, df.columns)}
    return DataFrame.from_dict(data, num_partitions=num_partitions)
