"""Test-framework utilities shipped with the library (reference parity:
com/microsoft/ml/spark/core/test — TestBase fixtures, DataFrameEquality,
the Fuzzing framework and its reflection-based coverage enforcement)."""

from .datagen import (  # noqa: F401
    ColumnOptions,
    GenConstraints,
    MissingOptions,
    RandomGenConstraints,
    generate_dataset,
    generate_like,
    options_from_schema,
)
from .fuzzing import (  # noqa: F401
    TestObject,
    discover_all_stages,
    experiment_fuzz,
    serialization_fuzz,
)
