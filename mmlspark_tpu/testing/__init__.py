"""Test-framework utilities shipped with the library (reference parity:
com/microsoft/ml/spark/core/test — TestBase fixtures, DataFrameEquality,
the Fuzzing framework and its reflection-based coverage enforcement)."""

from .fuzzing import (  # noqa: F401
    TestObject,
    discover_all_stages,
    experiment_fuzz,
    serialization_fuzz,
)
