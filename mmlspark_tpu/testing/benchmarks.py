"""Benchmark accuracy-regression gates (core/test/benchmarks/Benchmarks.scala
parity): metric values are recorded to CSV and compared against a committed
file; drift beyond the per-metric precision fails the suite.

CSV format matches the reference exactly (``name,value,precision,
higherIsBetter``; Benchmark.toCSVEntry), and the comparison rule matches
compareBenchmark (Benchmarks.scala:71-86): a higher-is-better metric may
exceed the committed value freely but not fall more than ``precision`` below
it; a lower-is-better metric the reverse.
"""

from __future__ import annotations

import csv
import dataclasses
import os
from typing import Dict, List


@dataclasses.dataclass
class Benchmark:
    name: str
    value: float
    precision: float
    higher_is_better: bool = True

    def to_csv_entry(self) -> str:
        hib = "true" if self.higher_is_better else "false"
        return f"{self.name},{self.value},{self.precision},{hib}"


class Benchmarks:
    """Accumulate benchmarks during a suite; verify against a committed CSV."""

    def __init__(self):
        self._benchmarks: List[Benchmark] = []

    def add_benchmark(self, name: str, value: float, precision: float = 1e-3,
                      higher_is_better: bool = True) -> None:
        assert name not in [b.name for b in self._benchmarks], \
            f"Benchmark {name} already exists"
        self._benchmarks.append(Benchmark(name, float(value), float(precision),
                                          higher_is_better))

    def write_csv(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            f.write("name,value,precision,higherIsBetter\n")
            for b in self._benchmarks:
                f.write(b.to_csv_entry() + "\n")

    @staticmethod
    def read_csv(path: str) -> Dict[str, Benchmark]:
        out: Dict[str, Benchmark] = {}
        with open(path) as f:
            for row in csv.DictReader(f):
                out[row["name"]] = Benchmark(
                    row["name"], float(row["value"]), float(row["precision"]),
                    row["higherIsBetter"].strip().lower() == "true")
        return out

    def verify(self, committed_csv: str, new_csv: str = None) -> None:
        """compareBenchmark parity: fail on missing/extra names or drift
        beyond precision in the bad direction."""
        if new_csv:
            self.write_csv(new_csv)
        old = self.read_csv(committed_csv)
        new = {b.name: b for b in self._benchmarks}
        assert set(new) == set(old), (
            f"benchmark sets differ: new-only={sorted(set(new) - set(old))}, "
            f"missing={sorted(set(old) - set(new))}")
        failures = []
        for name, bn in new.items():
            bo = old[name]
            assert bn.higher_is_better == bo.higher_is_better, name
            diff = bn.value - bo.value
            ok = (diff + bn.precision > 0) if bn.higher_is_better \
                else (-diff + bn.precision > 0)
            if not ok:
                failures.append(
                    f"{name}: new {bn.value} vs committed {bo.value} "
                    f"(precision {bn.precision})")
        assert not failures, "benchmark regressions:\n" + "\n".join(failures)
