"""Model downloader / repository (reference downloader/ package).

ModelDownloader manages repositories of pretrained models with JSON ``.meta``
schemas, sha256 verification, and retry-with-timeout fault tolerance
(downloader/ModelDownloader.scala:27-120, downloader/Schema.scala:24-100).
Repos are local directories or HTTP bases (remote fetch goes through the
retrying HTTP client). ModelSchema carries ``layerNames`` for ImageFeaturizer's
cutOutputLayers, exactly like the reference's schema feeds setModel.
"""

from .downloader import (
    FaultToleranceUtils,
    ModelDownloader,
    ModelNotFoundError,
    ModelSchema,
)

__all__ = ["FaultToleranceUtils", "ModelDownloader", "ModelNotFoundError",
           "ModelSchema"]
