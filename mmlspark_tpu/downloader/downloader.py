"""Model repository with .meta schemas, hashing, and retries."""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

from ..core.faults import (RetryPolicy, atomic_write_bytes,
                           rename_with_exdev_fallback)
from ..models.module import FunctionModel


class ModelNotFoundError(KeyError):
    pass


@dataclasses.dataclass
class ModelSchema:
    """Model metadata (.meta JSON) — downloader/Schema.scala:24-100 parity."""

    name: str
    uri: str                         # model payload location (dir or URL)
    hash: Optional[str] = None       # sha256 of the payload archive
    size: int = 0
    inputNode: str = "ARGUMENT_0"
    numLayers: int = 0
    layerNames: List[str] = dataclasses.field(default_factory=list)
    modelType: str = "image"

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=1)

    @staticmethod
    def from_json(s: str) -> "ModelSchema":
        return ModelSchema(**json.loads(s))


class FaultToleranceUtils:
    """retryWithTimeout parity (downloader/ModelDownloader.scala:37-47)."""

    @staticmethod
    def retry_with_timeout(fn: Callable[[], Any], retries: int = 3,
                           timeout_s: float = 60.0,
                           backoff_s: float = 1.0,
                           policy: Optional[RetryPolicy] = None) -> Any:
        from concurrent.futures import ThreadPoolExecutor
        from concurrent.futures import TimeoutError as FutureTimeout

        # jittered exponential backoff between attempts (core.faults policy;
        # seed the policy for a deterministic wait sequence)
        pol = policy or RetryPolicy(max_retries=retries, base_s=backoff_s,
                                    multiplier=2.0, jitter=0.1)
        rng = pol.make_rng()
        last: Optional[Exception] = None
        for attempt in range(retries):
            # Non-context-managed on purpose: `with` would join the worker on exit,
            # so a hung fn() blocks the caller past the timeout. shutdown(wait=False)
            # abandons the thread (it dies with the process); callers must make fn()
            # idempotent vs a still-running prior attempt (e.g. write to a unique
            # temp location and atomically rename — see download_model).
            pool = ThreadPoolExecutor(max_workers=1)
            future = pool.submit(fn)
            try:
                return future.result(timeout=timeout_s)
            except FutureTimeout:
                last = TimeoutError(f"operation exceeded {timeout_s}s")
            except Exception as e:  # noqa: BLE001 — retry any failure
                last = e
            finally:
                pool.shutdown(wait=False, cancel_futures=True)
            time.sleep(pol.next_wait(attempt, rng))
        raise last  # type: ignore[misc]


def _sha256_dir(path: str) -> str:
    """Stable content hash of a file or directory tree."""
    h = hashlib.sha256()
    if os.path.isfile(path):
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()
    for root, dirs, files in os.walk(path):
        dirs.sort()
        for name in sorted(files):
            full = os.path.join(root, name)
            h.update(os.path.relpath(full, path).encode())
            with open(full, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
    return h.hexdigest()


class ModelDownloader:
    """Fetch models from a repo into a local cache, verified and retried.

    ``repo``: local directory holding ``<name>.meta`` files (+ payload dirs),
    or an ``http(s)://`` base URL. Remote repos are fetched through the
    in-repo retrying HTTP client (io/http.send_with_retries driven by a
    core.faults.RetryPolicy): ``<repo>/index.json`` lists the available
    ``*.meta`` names (or inline schema objects), ``<repo>/<name>.meta``
    holds a schema, and each schema's ``uri`` points at a single payload
    FILE (e.g. an ``.onnx``) fetched with sha256 verification and a
    durable atomic write (tmp + fsync + rename, core/faults.py).

    ``http_send``: injectable ``(HTTPRequestData, timeout) -> response``
    transport — tests serve a repo from a dict without touching the
    network; production uses the default retrying client.
    """

    def __init__(self, local_path: str, repo: Optional[str] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 http_send: Optional[Callable] = None,
                 timeout_s: float = 60.0):
        self.local_path = local_path
        self.repo = repo
        self.retry_policy = retry_policy or RetryPolicy(max_retries=3,
                                                        base_s=0.5)
        self._http_send = http_send
        self.timeout_s = timeout_s
        os.makedirs(local_path, exist_ok=True)

    # -- remote transport -------------------------------------------------
    @property
    def is_remote(self) -> bool:
        return bool(self.repo) and self.repo.startswith(("http://", "https://"))

    def _fetch_url(self, url: str) -> bytes:
        """GET ``url`` through the retrying client; non-200 raises IOError."""
        from ..io.http import HTTPRequestData, send_with_retries

        req = HTTPRequestData(url=url, method="GET")
        if self._http_send is not None:
            resp = self._http_send(req, self.timeout_s)
        else:
            resp = send_with_retries(req, timeout=self.timeout_s,
                                     policy=self.retry_policy)
        if resp is None or resp.statusCode != 200 or resp.entity is None:
            code = resp.statusCode if resp is not None else "no response"
            raise IOError(f"GET {url} failed: {code}")
        return resp.entity

    # -- listing ---------------------------------------------------------
    def get_models(self) -> Iterator[ModelSchema]:
        """Iterate schemas in the remote/local repo (ModelDownloader.getModels)."""
        if self.repo is None:
            return iter(())
        if self.is_remote:
            base = self.repo.rstrip("/")
            index = json.loads(self._fetch_url(f"{base}/index.json"))

            def gen_remote():
                for entry in index:
                    if isinstance(entry, dict):
                        yield ModelSchema(**entry)
                    else:
                        name = str(entry)
                        if name.endswith(".meta"):
                            name = name[: -len(".meta")]
                        yield ModelSchema.from_json(
                            self._fetch_url(f"{base}/{name}.meta").decode("utf-8"))

            return gen_remote()
        metas = [f for f in sorted(os.listdir(self.repo)) if f.endswith(".meta")]

        def gen():
            for m in metas:
                with open(os.path.join(self.repo, m)) as f:
                    yield ModelSchema.from_json(f.read())

        return gen()

    def local_models(self) -> Iterator[ModelSchema]:
        metas = [f for f in sorted(os.listdir(self.local_path))
                 if f.endswith(".meta")]
        for m in metas:
            with open(os.path.join(self.local_path, m)) as f:
                yield ModelSchema.from_json(f.read())

    # -- fetch -----------------------------------------------------------
    def download_model(self, schema_or_name) -> ModelSchema:
        """Copy a model into the local cache; verify sha256; idempotent
        (ModelDownloader.downloadModel / downloadByName)."""
        schema = (schema_or_name if isinstance(schema_or_name, ModelSchema)
                  else self._find(schema_or_name))
        dest = os.path.join(self.local_path, schema.name)
        meta_dest = os.path.join(self.local_path, f"{schema.name}.meta")
        if os.path.exists(dest) and os.path.exists(meta_dest):
            if not schema.hash or _sha256_dir(dest) == schema.hash:
                return self._localized(schema, dest)
        src = schema.uri
        if src.startswith(("http://", "https://")):

            def fetch():
                # unique staging dir + atomic write + atomic rename: a
                # timed-out prior attempt still running in its abandoned
                # thread can never collide, and a crash mid-write leaves no
                # torn payload (core/faults.py durability contract)
                import tempfile

                stage = tempfile.mkdtemp(prefix=f".{schema.name}.",
                                         dir=self.local_path)
                staged = os.path.join(stage, "payload")
                try:
                    atomic_write_bytes(staged, self._fetch_url(src))
                    if schema.hash:
                        got = _sha256_dir(staged)
                        if got != schema.hash:
                            raise IOError(f"hash mismatch for {schema.name}: "
                                          f"{got} != {schema.hash}")
                    if os.path.exists(dest):
                        if os.path.isdir(dest):
                            shutil.rmtree(dest)
                        else:
                            os.remove(dest)
                    rename_with_exdev_fallback(staged, dest)
                finally:
                    shutil.rmtree(stage, ignore_errors=True)
                return dest

            FaultToleranceUtils.retry_with_timeout(
                fetch, retries=self.retry_policy.max_retries,
                policy=self.retry_policy)
            local = self._localized(schema, dest)
            with open(meta_dest, "w") as f:
                f.write(local.to_json())
            return local

        def copy():
            # unique staging dir + atomic rename: a timed-out prior attempt still
            # running in its abandoned thread can never collide with this one
            import tempfile

            stage = tempfile.mkdtemp(prefix=f".{schema.name}.", dir=self.local_path)
            staged = os.path.join(stage, "payload")
            try:
                if os.path.isdir(src):
                    shutil.copytree(src, staged)
                else:
                    shutil.copy(src, staged)
                if schema.hash:
                    got = _sha256_dir(staged)
                    if got != schema.hash:
                        raise IOError(
                            f"hash mismatch for {schema.name}: {got} != {schema.hash}")
                if os.path.exists(dest):
                    shutil.rmtree(dest) if os.path.isdir(dest) else os.remove(dest)
                # EXDEV-safe: staging (often tmpfs) and the destination cache
                # may live on different filesystems; the final hop into dest
                # stays an atomic same-fs rename either way
                rename_with_exdev_fallback(staged, dest)
            finally:
                shutil.rmtree(stage, ignore_errors=True)
            return dest

        FaultToleranceUtils.retry_with_timeout(copy, retries=3)
        local = self._localized(schema, dest)
        with open(meta_dest, "w") as f:
            f.write(local.to_json())
        return local

    def download_by_name(self, name: str) -> ModelSchema:
        return self.download_model(name)

    def _find(self, name: str) -> ModelSchema:
        if self.is_remote:
            # direct meta fetch first (no index.json required), then listing
            try:
                base = self.repo.rstrip("/")
                return ModelSchema.from_json(
                    self._fetch_url(f"{base}/{name}.meta").decode("utf-8"))
            except IOError:
                pass
        try:
            for s in self.get_models():
                if s.name == name:
                    return s
        except IOError as e:
            raise ModelNotFoundError(
                f"No model named {name!r} in repo {self.repo!r}: {e}")
        raise ModelNotFoundError(f"No model named {name!r} in repo {self.repo!r}")

    @staticmethod
    def _localized(schema: ModelSchema, dest: str) -> ModelSchema:
        return dataclasses.replace(schema, uri=dest)

    # -- model payload handling -----------------------------------------
    @staticmethod
    def save_function_model(model: FunctionModel, path: str,
                            name: Optional[str] = None) -> ModelSchema:
        """Persist a FunctionModel as a repo payload + schema."""
        from ..core.serialize import _save_value

        os.makedirs(path, exist_ok=True)
        manifest = _save_value(model.params, os.path.join(path, "params"))
        import pickle

        with open(os.path.join(path, "module.pkl"), "wb") as f:
            pickle.dump(model.module, f)
        info = {
            "params_manifest": manifest,
            "input_shape": list(model.input_shape),
            "layer_names": list(model.layer_names),
            "name": name or model.name,
        }
        with open(os.path.join(path, "model.json"), "w") as f:
            json.dump(info, f)
        return ModelSchema(
            name=name or model.name, uri=path, hash=_sha256_dir(path),
            inputNode="ARGUMENT_0", numLayers=len(model.layer_names),
            layerNames=list(model.layer_names))

    @staticmethod
    def load_function_model(schema_or_path) -> FunctionModel:
        """Load a model payload into a FunctionModel.

        Payload formats (the reference's loader accepts any CNTK graph,
        SerializableFunction.scala:23-42; ours accepts):
          - a native payload dir (model.json + module.pkl + params),
          - an ONNX file (or a dir containing exactly one ``*.onnx``),
          - a torchvision ResNet checkpoint ``*.pth``/``*.pt`` (schema.modelType
            "torch-resnet<depth>" carries the architecture).
        """
        from ..core.serialize import _load_value

        schema = schema_or_path if isinstance(schema_or_path, ModelSchema) else None
        path = schema.uri if schema is not None else schema_or_path

        onnx_path = None
        if os.path.isfile(path) and (
                path.endswith(".onnx")
                or (schema is not None and schema.modelType == "onnx")):
            onnx_path = path
        elif os.path.isdir(path) and not os.path.exists(os.path.join(path, "model.json")):
            cands = [f for f in os.listdir(path) if f.endswith(".onnx")]
            if len(cands) == 1:
                onnx_path = os.path.join(path, cands[0])
            elif cands or (schema is not None and schema.modelType == "onnx"):
                raise ValueError(
                    f"ONNX payload dir {path!r} must contain exactly one *.onnx "
                    f"file; found {sorted(cands)}")
        if onnx_path is not None:
            from ..onnx import import_onnx

            return import_onnx(
                onnx_path,
                layer_names=(list(schema.layerNames) or None) if schema else None,
                name=schema.name if schema else None)

        if os.path.isfile(path) and path.endswith((".pth", ".pt")):
            from ..models.torch_import import from_torch_resnet

            depth = 50
            if schema is not None and schema.modelType.startswith("torch-resnet"):
                depth = int(schema.modelType[len("torch-resnet"):] or 50)
            return from_torch_resnet(path, depth=depth)

        with open(os.path.join(path, "model.json")) as f:
            info = json.load(f)
        import pickle

        with open(os.path.join(path, "module.pkl"), "rb") as f:
            module = pickle.load(f)
        params = _load_value(info["params_manifest"], os.path.join(path, "params"))
        return FunctionModel(module=module, params=params,
                             input_shape=tuple(info["input_shape"]),
                             layer_names=info["layer_names"],
                             name=info["name"])
