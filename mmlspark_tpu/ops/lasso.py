"""Jitted lasso solver (ISTA) — LIME's per-row local linear fit.

Reference: the LIME stages fit a lasso per explained row via breeze normal
equations (lime/LIME.scala:158 fitLassoUDF -> LimeNamespaceInjections.fitLasso,
core/utils/BreezeUtils.scala). Here: proximal gradient (ISTA) with fixed
iteration count so it jits to one XLA program and ``vmap``s across rows —
explaining a whole partition of rows is a single device launch.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np


def _soft(x, t):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - t, 0.0)


@functools.partial(__import__("jax").jit, static_argnames=("iters", "fit_intercept"))
def fit_lasso(X, y, reg, sample_weights=None, iters: int = 200,
              fit_intercept: bool = True):
    """min_w 0.5/n * ||sqrt(W)(Xw + b - y)||^2 + reg * ||w||_1  via ISTA.

    X: [n, d], y: [n]; returns (w [d], b []).
    """
    import jax.numpy as jnp

    n, d = X.shape
    sw = (jnp.ones(n, dtype=jnp.float32) if sample_weights is None
          else sample_weights.astype(jnp.float32))
    sw = sw / jnp.maximum(jnp.sum(sw), 1e-12)
    Xf = X.astype(jnp.float32)
    yf = y.astype(jnp.float32)

    # weighted centering removes the intercept from the prox step
    if fit_intercept:
        x_mean = jnp.sum(Xf * sw[:, None], axis=0)
        y_mean = jnp.sum(yf * sw)
        Xc = Xf - x_mean
        yc = yf - y_mean
    else:
        Xc, yc = Xf, yf

    # Lipschitz bound for step size: ||X^T W X||_2 <= trace
    G = (Xc * sw[:, None]).T @ Xc
    L = jnp.trace(G) + 1e-6
    step = 1.0 / L

    def body(_, w):
        grad = (Xc * sw[:, None]).T @ (Xc @ w - yc)
        return _soft(w - step * grad, step * reg)

    import jax

    w = jax.lax.fori_loop(0, iters, body, jnp.zeros(d, dtype=jnp.float32))
    b = (y_mean - jnp.dot(x_mean, w)) if fit_intercept else jnp.float32(0.0)
    return w, b


def fit_lasso_batch(Xs, ys, reg, sample_weights=None, iters: int = 200):
    """vmap over rows: Xs [B, n, d], ys [B, n] -> (ws [B, d], bs [B])."""
    import jax

    f = lambda X, y, sw: fit_lasso(X, y, reg, sw, iters=iters)
    return jax.vmap(f)(Xs, ys, sample_weights)
