"""Compute ops: image processing, hashing, histograms — the kernel layer.

Where the reference drives C++ engines (OpenCV imgproc, LightGBM histograms, VW
hashing) through JNI/SWIG, this package provides the TPU-native kernels: jax/XLA
(and Pallas for the hot paths) with numpy host fallbacks, plus ctypes bindings to
the in-repo C++ runtime (native/) where host-side work is the bottleneck.
"""
