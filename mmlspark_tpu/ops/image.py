"""Image kernels: decode, resize, color, geometry, filtering.

TPU-native re-design of the reference's OpenCV JNI surface
(opencv/ImageTransformer.scala:26-150 — Imgproc.resize/cvtColor/blur/threshold/
GaussianBlur, Core.flip) and its JVM AWT resize (image/ResizeImageTransformer.scala):

  - batched, jit-friendly float ops on [B,H,W,C] arrays (``jax.image.resize``,
    separable gaussian via depthwise conv) for uniform-shape batches — the hot path
    feeding the DNN;
  - numpy per-image host fallbacks for ragged inputs (decode-time preprocessing).

Decode uses Pillow when present (gated), else a built-in PPM/PGM/BMP decoder.
"""

from __future__ import annotations

import io
import math
import struct
from typing import Optional, Sequence, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# Decode (host side; reference: io/image/ImageUtils.scala:1-159 decode via AWT)
# ---------------------------------------------------------------------------


def decode_image(data: bytes) -> Optional[np.ndarray]:
    """bytes -> HWC uint8 RGB array, or None if undecodable (reference returns
    null rows for broken images and drops them downstream)."""
    try:
        from PIL import Image  # Pillow ships with transformers

        img = Image.open(io.BytesIO(data))
        img = img.convert("RGB")
        return np.asarray(img, dtype=np.uint8)
    except ImportError:
        pass
    except Exception:
        return None
    try:
        return _decode_builtin(data)
    except Exception:
        return None


def _decode_builtin(data: bytes) -> np.ndarray:
    if data[:2] in (b"P6", b"P5"):
        return _decode_pnm(data)
    if data[:2] == b"BM":
        return _decode_bmp(data)
    raise ValueError("unsupported image format (install Pillow for JPEG/PNG)")


def _decode_pnm(data: bytes) -> np.ndarray:
    # P6 = binary PPM (RGB), P5 = binary PGM (gray)
    parts: list = []
    idx = 0
    while len(parts) < 4:
        nl = data.index(b"\n", idx)
        line = data[idx:nl]
        idx = nl + 1
        for tok in line.split(b"#")[0].split():
            parts.append(tok)
    magic, w, h, _maxval = parts[0], int(parts[1]), int(parts[2]), int(parts[3])
    raw = np.frombuffer(data[idx:], dtype=np.uint8)
    if magic == b"P6":
        return raw[: h * w * 3].reshape(h, w, 3).copy()
    return np.repeat(raw[: h * w].reshape(h, w, 1), 3, axis=2)


def _decode_bmp(data: bytes) -> np.ndarray:
    off = struct.unpack_from("<I", data, 10)[0]
    w, h = struct.unpack_from("<ii", data, 18)
    bpp = struct.unpack_from("<H", data, 28)[0]
    if bpp != 24:
        raise ValueError("only 24-bit BMP supported in builtin decoder")
    row_size = (w * 3 + 3) & ~3
    arr = np.zeros((abs(h), w, 3), dtype=np.uint8)
    for y in range(abs(h)):
        row = np.frombuffer(data, dtype=np.uint8, count=w * 3, offset=off + y * row_size)
        arr[abs(h) - 1 - y if h > 0 else y] = row.reshape(w, 3)[:, ::-1]  # BGR->RGB
    return arr


def encode_ppm(img: np.ndarray) -> bytes:
    """HWC uint8 RGB -> binary PPM bytes (for tests / round-trips)."""
    img = np.asarray(img, dtype=np.uint8)
    if img.ndim == 2:
        img = np.repeat(img[:, :, None], 3, axis=2)
    h, w, _ = img.shape
    return b"P6\n%d %d\n255\n" % (w, h) + img.tobytes()


# ---------------------------------------------------------------------------
# Resize
# ---------------------------------------------------------------------------


def resize(img: np.ndarray, height: int, width: int, method: str = "linear") -> np.ndarray:
    """Host-side single-image resize (C++ bilinear when built, numpy fallback)."""
    img = np.asarray(img)
    squeeze = img.ndim == 2
    if squeeze:
        img = img[:, :, None]
    h, w, c = img.shape
    if (h, w) == (height, width):
        out = img
    elif method != "nearest" and img.dtype in (np.uint8, np.float32):
        from .. import native_loader

        native = native_loader.resize_bilinear(img, height, width)
        out = native if native is not None else _resize_numpy(img, height, width)
    elif method == "nearest":
        ys = np.clip((np.arange(height) + 0.5) * h / height, 0, h - 1).astype(np.int64)
        xs = np.clip((np.arange(width) + 0.5) * w / width, 0, w - 1).astype(np.int64)
        out = img[ys][:, xs]
    else:
        out = _resize_numpy(img, height, width)
    return out[:, :, 0] if squeeze else out


def _resize_numpy(img: np.ndarray, height: int, width: int) -> np.ndarray:
    out = _bilinear(img.astype(np.float32), height, width)
    if img.dtype == np.uint8:
        return np.clip(np.rint(out), 0, 255).astype(np.uint8)
    return out.astype(img.dtype)


def _bilinear(img: np.ndarray, height: int, width: int) -> np.ndarray:
    h, w, c = img.shape
    # half-pixel centers (matches jax.image.resize / OpenCV INTER_LINEAR)
    ys = (np.arange(height) + 0.5) * h / height - 0.5
    xs = (np.arange(width) + 0.5) * w / width - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


def resize_batch(x, height: int, width: int, method: str = "linear"):
    """Batched jit-friendly resize on [B,H,W,C] (device path)."""
    import jax

    b, h, w, c = x.shape
    return jax.image.resize(x, (b, height, width, c),
                            method="nearest" if method == "nearest" else "linear")


# ---------------------------------------------------------------------------
# Geometry / color / filtering (ImageTransformer op parity)
# ---------------------------------------------------------------------------


def crop(img: np.ndarray, x: int, y: int, height: int, width: int) -> np.ndarray:
    return np.asarray(img)[y:y + height, x:x + width]


def center_crop(img: np.ndarray, height: int, width: int) -> np.ndarray:
    h, w = img.shape[:2]
    y = max((h - height) // 2, 0)
    x = max((w - width) // 2, 0)
    return crop(img, x, y, height, width)


def flip(img: np.ndarray, flip_code: int = 1) -> np.ndarray:
    """OpenCV Core.flip semantics: 0 = vertical (x-axis), >0 horizontal, <0 both."""
    if flip_code == 0:
        return np.asarray(img)[::-1].copy()
    if flip_code > 0:
        return np.asarray(img)[:, ::-1].copy()
    return np.asarray(img)[::-1, ::-1].copy()


def color_format(img: np.ndarray, code: str) -> np.ndarray:
    """cvtColor subset: 'gray'/'bgr2rgb'/'rgb2bgr'."""
    img = np.asarray(img)
    if code in ("gray", "grayscale"):
        if img.ndim == 2 or img.shape[2] == 1:
            return img
        w = np.array([0.299, 0.587, 0.114], dtype=np.float32)
        g = img[..., :3].astype(np.float32) @ w
        out = np.clip(np.rint(g), 0, 255).astype(img.dtype) if img.dtype == np.uint8 \
            else g.astype(img.dtype)
        return out[:, :, None]
    if code in ("bgr2rgb", "rgb2bgr"):
        return img[..., ::-1].copy()
    raise ValueError(f"Unknown color format {code!r}")


def box_blur(img: np.ndarray, kh: int, kw: int) -> np.ndarray:
    """Imgproc.blur parity: normalized box filter with edge replication."""
    img = np.asarray(img, dtype=np.float32)
    squeeze = img.ndim == 2
    if squeeze:
        img = img[:, :, None]
    ph, pw = kh // 2, kw // 2
    padded = np.pad(img, ((ph, kh - 1 - ph), (pw, kw - 1 - pw), (0, 0)), mode="edge")
    # separable box: cumulative sums along each axis
    cs = np.cumsum(padded, axis=0)
    rows = np.concatenate([cs[kh - 1:kh], cs[kh:] - cs[:-kh]], axis=0)
    cs = np.cumsum(rows, axis=1)
    out = np.concatenate([cs[:, kw - 1:kw], cs[:, kw:] - cs[:, :-kw]], axis=1) / (kh * kw)
    return out[:, :, 0] if squeeze else out


def gaussian_kernel_1d(sigma: float, radius: Optional[int] = None) -> np.ndarray:
    if radius is None:
        radius = max(int(math.ceil(3 * sigma)), 1)
    x = np.arange(-radius, radius + 1, dtype=np.float64)
    k = np.exp(-(x ** 2) / (2 * sigma * sigma))
    return (k / k.sum()).astype(np.float32)


def gaussian_blur(img: np.ndarray, sigma: float, kh: Optional[int] = None,
                  kw: Optional[int] = None) -> np.ndarray:
    """Imgproc.GaussianBlur parity: separable gaussian, edge-replicated."""
    img = np.asarray(img, dtype=np.float32)
    squeeze = img.ndim == 2
    if squeeze:
        img = img[:, :, None]
    kr = (kh // 2) if kh else None
    k = gaussian_kernel_1d(sigma, kr)
    r = len(k) // 2
    padded = np.pad(img, ((r, r), (0, 0), (0, 0)), mode="edge")
    out = np.zeros_like(img)
    for i, kv in enumerate(k):
        out += kv * padded[i:i + img.shape[0]]
    padded = np.pad(out, ((0, 0), (r, r), (0, 0)), mode="edge")
    out2 = np.zeros_like(img)
    for i, kv in enumerate(k):
        out2 += kv * padded[:, i:i + img.shape[1]]
    return out2[:, :, 0] if squeeze else out2


def gaussian_kernel_2d(app_width: int, sigma: float) -> np.ndarray:
    """GaussianKernel stage parity (opencv/ImageTransformer GaussianKernel)."""
    k = gaussian_kernel_1d(sigma, app_width // 2)
    return np.outer(k, k).astype(np.float32)


def threshold(img: np.ndarray, thresh: float, max_val: float,
              kind: str = "binary") -> np.ndarray:
    """Imgproc.threshold parity: binary / binary_inv / trunc / tozero / tozero_inv."""
    img = np.asarray(img, dtype=np.float32)
    if kind == "binary":
        return np.where(img > thresh, max_val, 0.0)
    if kind == "binary_inv":
        return np.where(img > thresh, 0.0, max_val)
    if kind == "trunc":
        return np.minimum(img, thresh)
    if kind == "tozero":
        return np.where(img > thresh, img, 0.0)
    if kind == "tozero_inv":
        return np.where(img > thresh, 0.0, img)
    raise ValueError(f"Unknown threshold kind {kind!r}")


# ---------------------------------------------------------------------------
# Unroll (image -> flat vector; UnrollImage.scala:28-53 parity)
# ---------------------------------------------------------------------------


def unroll_chw(img: np.ndarray, normalize: bool = False) -> np.ndarray:
    """HWC image -> flat CHW float64 vector (reference UnrollImage layout: the CNTK
    convention of channel-major flattening, UnrollImage.scala:28-53)."""
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    out = np.moveaxis(img, -1, 0).astype(np.float64).reshape(-1)
    return out / 255.0 if normalize else out


def unroll_batch_chw(x):
    """Batched device unroll: [B,H,W,C] -> [B, C*H*W] (jit-friendly)."""
    import jax.numpy as jnp

    b = x.shape[0]
    return jnp.moveaxis(x, -1, 1).reshape(b, -1)


# ---------------------------------------------------------------------------
# Device-EXACT batched mirrors (pipeline fusion, core/fusion.py)
#
# Each op below reproduces its host sibling BITWISE on [B,H,W,C] batches:
# pure value moves (crop/flip/reverse), exact casts, or the identical
# elementwise IEEE-f32 expression tree (XLA CPU/TPU do not reassociate or
# contract elementwise chains). Ops whose host path computes through f64
# (resize's interpolation weights, the cumsum blurs) have NO device mirror —
# the fused executor runs those on the host in a segment's `prepare` using
# the per-image functions above, which is what keeps fused == unfused exact.
# ---------------------------------------------------------------------------


def crop_batch(x, cx: int, cy: int, height: int, width: int):
    """Batched mirror of ``crop`` (numpy slicing semantics, any dtype)."""
    return x[:, cy:cy + height, cx:cx + width]


def flip_batch(x, flip_code: int = 1):
    """Batched mirror of ``flip`` (OpenCV Core.flip codes)."""
    if flip_code == 0:
        return x[:, ::-1]
    if flip_code > 0:
        return x[:, :, ::-1]
    return x[:, ::-1, ::-1]


def threshold_batch(x, thresh: float, max_val: float, kind: str = "binary"):
    """Batched mirror of ``threshold``: f32 compare + select, exact."""
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    t = jnp.float32(thresh)
    m = jnp.float32(max_val)
    z = jnp.float32(0.0)
    if kind == "binary":
        return jnp.where(xf > t, m, z)
    if kind == "binary_inv":
        return jnp.where(xf > t, z, m)
    if kind == "trunc":
        return jnp.minimum(xf, t)
    if kind == "tozero":
        return jnp.where(xf > t, xf, z)
    if kind == "tozero_inv":
        return jnp.where(xf > t, z, xf)
    raise ValueError(f"Unknown threshold kind {kind!r}")


def color_format_batch(x, code: str):
    """Batched mirror of ``color_format``. The gray path spells out the f32
    weighted sum in the same left-to-right order numpy's 3-element matvec
    evaluates, so host and device agree bitwise (verified in tests)."""
    import jax.numpy as jnp

    if code in ("gray", "grayscale"):
        if x.ndim == 3 or x.shape[-1] == 1:
            return x
        xf = x[..., :3].astype(jnp.float32)
        g = (xf[..., 0] * jnp.float32(0.299)
             + xf[..., 1] * jnp.float32(0.587)) + xf[..., 2] * jnp.float32(0.114)
        if x.dtype == jnp.uint8:
            g = jnp.clip(jnp.rint(g), 0, 255).astype(jnp.uint8)
        else:
            g = g.astype(x.dtype)
        return g[..., None]
    if code in ("bgr2rgb", "rgb2bgr"):
        return x[..., ::-1]
    raise ValueError(f"Unknown color format {code!r}")


def fix_channels_batch(x, c: int):
    """Batched mirror of the featurizer's channel fix: repeat a single
    channel up to ``c`` or slice extras off (exact value moves)."""
    import jax.numpy as jnp

    if x.ndim == 3:
        x = x[:, :, :, None]
    have = x.shape[3]
    if have == c:
        return x
    if have < c:
        return jnp.repeat(x[:, :, :, :1], c, axis=3)
    return x[:, :, :, :c]
