"""MurmurHash3 (x86 32-bit) — VW-compatible feature hashing.

The reference exposes VW's murmur through VowpalWabbitMurmur.hash for its
featurizers (vw/VowpalWabbitFeaturizer.scala:62-180, VowpalWabbitMurmurWithPrefix).
Pure-numpy implementation here (uint32 wraparound arithmetic); the C++ runtime
(native/) provides a batched fast path loaded via ctypes when built.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

import numpy as np

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl32(x: np.uint32, r: int) -> np.uint32:
    x = np.uint32(x)
    return np.uint32((int(x) << r | int(x) >> (32 - r)) & 0xFFFFFFFF)


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3_x86_32 over bytes; matches VW/Scala reference output."""
    with np.errstate(over="ignore"):
        h = np.uint32(seed & 0xFFFFFFFF)
        n = len(data)
        n_blocks = n // 4
        blocks = np.frombuffer(data[: n_blocks * 4], dtype="<u4")
        for k in blocks:
            k = np.uint32(k) * _C1
            k = _rotl32(k, 15) * _C2
            h = np.uint32(h ^ k)
            h = _rotl32(h, 13)
            h = np.uint32(h * np.uint32(5) + np.uint32(0xE6546B64))
        # tail
        tail = data[n_blocks * 4:]
        k = np.uint32(0)
        if len(tail) >= 3:
            k = np.uint32(k ^ np.uint32(tail[2] << 16))
        if len(tail) >= 2:
            k = np.uint32(k ^ np.uint32(tail[1] << 8))
        if len(tail) >= 1:
            k = np.uint32(k ^ np.uint32(tail[0]))
            k = np.uint32(k * _C1)
            k = _rotl32(k, 15)
            k = np.uint32(k * _C2)
            h = np.uint32(h ^ k)
        # finalization
        h = np.uint32(h ^ np.uint32(n))
        h = np.uint32(h ^ (h >> np.uint32(16)))
        h = np.uint32(h * np.uint32(0x85EBCA6B))
        h = np.uint32(h ^ (h >> np.uint32(13)))
        h = np.uint32(h * np.uint32(0xC2B2AE35))
        h = np.uint32(h ^ (h >> np.uint32(16)))
        return int(h)


def hash_string(s: str, seed: int = 0) -> int:
    return murmur3_32(s.encode("utf-8"), seed)


class MurmurWithPrefix:
    """Prefix-seeded hashing: precompute the hash state of a fixed prefix so
    per-feature hashing only processes the suffix
    (reference vw/VowpalWabbitMurmurWithPrefix.scala)."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        self.prefix_bytes = prefix.encode("utf-8")

    def hash(self, suffix: str, seed: int = 0) -> int:
        # correctness first: hash(prefix + suffix); the prefix-state optimization
        # lives in the C++ path
        return murmur3_32(self.prefix_bytes + suffix.encode("utf-8"), seed)


def hash_strings(values: Iterable[str], seed: int = 0) -> np.ndarray:
    """Batch hashing: C++ fast path when built, python fallback."""
    vals = list(values)
    from .. import native_loader

    native = native_loader.murmur3_batch(vals, seed)
    if native is not None:
        return native
    return np.fromiter((hash_string(v, seed) for v in vals), dtype=np.int64)
