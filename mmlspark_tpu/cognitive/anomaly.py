"""Anomaly-detector services (reference cognitive/AnamolyDetection.scala:117-160)."""

from __future__ import annotations

import json
from typing import Any, Dict, List

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, ServiceParam
from . import schemas as S
from .base import CognitiveServicesBase


class _AnomalyBase(CognitiveServicesBase):
    series = ServiceParam("series", "Timestamped points [{timestamp,value}...]")
    granularity = ServiceParam("granularity", "hourly/daily/...")
    maxAnomalyRatio = ServiceParam("maxAnomalyRatio", "Max anomaly fraction")
    sensitivity = ServiceParam("sensitivity", "Detection sensitivity")
    customInterval = ServiceParam("customInterval", "Custom interval")
    period = ServiceParam("period", "Seasonality period")
    _service_param_names = ["series", "granularity", "maxAnomalyRatio",
                            "sensitivity", "customInterval", "period"]

    def _build_entity(self, vals):
        series = vals.get("series")
        if series is None:
            series = []
        clean = []
        for pt in series:
            if isinstance(pt, dict):
                clean.append({"timestamp": str(pt.get("timestamp")),
                              "value": float(pt.get("value"))})
        body: Dict[str, Any] = {"series": clean,
                                "granularity": str(vals.get("granularity",
                                                            "daily"))}
        for k in ("maxAnomalyRatio", "sensitivity", "period"):
            if vals.get(k) is not None:
                body[k] = vals[k]
        if vals.get("customInterval") is not None:
            body["customInterval"] = int(vals["customInterval"])
        return json.dumps(body).encode("utf-8")


class DetectAnomalies(_AnomalyBase):
    """Batch anomaly detection over a whole series column
    (AnomalyDetectorSchemas.scala ADEntireResponse)."""

    responseBinding = S.ADEntireResponse


class DetectLastAnomaly(_AnomalyBase):
    """Detect whether the latest point is anomalous
    (AnomalyDetectorSchemas.scala ADLastResponse)."""

    responseBinding = S.ADLastResponse


class SimpleDetectAnomalies(_AnomalyBase):
    """Grouped convenience: rows (group, timestamp, value) -> per-row anomaly
    flags (AnamolyDetection.scala SimpleDetectAnomalies)."""

    groupbyCol = Param("groupbyCol", "Series-grouping column", None, ptype=str)
    timestampCol = Param("timestampCol", "Timestamp column", "timestamp", ptype=str)
    valueCol = Param("valueCol", "Value column", "value", ptype=str)

    def transform(self, df: DataFrame) -> DataFrame:
        group_col = self.get_or_throw("groupbyCol")
        ts_col, val_col = self.get("timestampCol"), self.get("valueCol")
        out_col = self.get_or_throw("outputCol")
        data = df.collect()
        groups = data[group_col]
        n = len(groups)
        by_group: Dict[Any, List[int]] = {}
        for i, g in enumerate(groups):
            by_group.setdefault(g, []).append(i)

        # ONE request per group (reference SimpleDetectAnomalies behavior)
        keys = list(by_group)
        series_col = np.empty(len(keys), dtype=object)
        for gi, g in enumerate(keys):
            series_col[gi] = [{"timestamp": str(data[ts_col][i]),
                               "value": float(data[val_col][i])}
                              for i in by_group[g]]
        group_df = DataFrame([{"__series__": series_col}])
        inner = DetectAnomalies(
            outputCol=out_col, errorCol=self.get("errorCol"),
            url=self.get("url"), handler=self.get("handler"))
        inner._param_map.update({k: v for k, v in self._param_map.items()
                                 if inner.has_param(k) and k not in (
                                     "outputCol", "errorCol", "url", "handler")})
        inner.set_col("series", "__series__")
        res = inner.transform(group_df).collect()[out_col]

        # scatter per-row anomaly flags back by position within the group
        flags = np.empty(n, dtype=object)
        for gi, g in enumerate(keys):
            arr = (res[gi] or {}).get("isAnomaly")
            for pos, i in enumerate(by_group[g]):
                flags[i] = (bool(arr[pos]) if arr is not None
                            and pos < len(arr) else None)
        return df.with_column(out_col, flags)
