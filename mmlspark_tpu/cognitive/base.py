"""Cognitive service base stage.

Reference: cognitive/CognitiveServiceBase.scala:29-151 — a SimpleHTTPTransformer
pipeline parameterized by ServiceParams (each holding a literal value or an
input-column name), subscription-key header injection, URL building, and
optional async polling on Operation-Location (RecognizeText pattern,
cognitive/ComputerVision.scala:165-260).
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core import faults
from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasOutputCol, Param, ServiceParam
from ..core.pipeline import Transformer
from ..core.schema import ColType, Schema
from ..io.http import HTTPRequestData, HTTPResponseData, send_with_retries


class HasServiceParams(Transformer):
    """Helpers to resolve ServiceParams per row."""

    _abstract = True

    def _service_values(self, part, i, names: List[str]) -> Dict[str, Any]:
        out = {}
        for name in names:
            v = self.get_service_value(name, part, i)
            if v is not None:
                out[name] = v
        return out


class CognitiveServicesBase(HasServiceParams, HasOutputCol):
    """POST JSON (or binary) per row; parse the JSON response into a struct col."""

    _abstract = True

    subscriptionKey = ServiceParam("subscriptionKey", "API subscription key")
    url = Param("url", "Service endpoint URL", None, ptype=str)
    errorCol = Param("errorCol", "Error column", "errors", ptype=str)
    concurrency = Param("concurrency", "Concurrent requests", 1, ptype=int)
    timeout = Param("timeout", "Request timeout (s)", 60.0, ptype=float)
    handler = ComplexParam("handler", "Injected (HTTPRequestData)->HTTPResponseData")
    retryPolicy = ComplexParam(
        "retryPolicy", "core.faults.RetryPolicy for the default HTTP handler "
        "(jittered backoff, sleep budget, deterministic when seeded)")
    pollingDelayMs = Param("pollingDelayMs", "Async poll interval", 300, ptype=int)
    maxPollingRetries = Param("maxPollingRetries", "Async poll attempts", 100,
                              ptype=int)

    # subclasses set these
    _service_param_names: List[str] = []
    _is_async = False          # Operation-Location polling (RecognizeText)
    _method = "POST"

    def set_subscription_key(self, key: str):
        return self.set_scalar("subscriptionKey", key)

    def set_url(self, url: str):
        return self.set("url", url)

    def set_location_url(self, location: str, path: str):
        return self.set("url",
                        f"https://{location}.api.cognitive.microsoft.com{path}")

    # -- request building (subclasses may override) ----------------------
    def _url_params(self, vals: Dict[str, Any]) -> Dict[str, str]:
        return {}

    def _build_entity(self, vals: Dict[str, Any]) -> bytes:
        body = {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                for k, v in vals.items() if k not in ("subscriptionKey",)}
        return json.dumps(body).encode("utf-8")

    def _content_type(self, vals: Dict[str, Any]) -> str:
        return "application/json"

    def _validate(self, vals: Dict[str, Any]) -> None:
        """Hook: raise if required params are missing (error lands in errorCol)."""

    def _build_request(self, part, i) -> Optional[HTTPRequestData]:
        from urllib.parse import quote, urlencode

        vals = self._service_values(
            part, i, self._service_param_names + ["subscriptionKey"])
        self._validate(vals)
        url = self.get_or_throw("url")
        q = self._url_params(vals)
        if q:
            sep = "&" if "?" in url else "?"
            # commas stay literal (Azure comma-separated feature lists)
            url = url + sep + urlencode(
                q, quote_via=lambda v, safe="", enc=None, err=None:
                quote(v, safe=","))
        headers = {}
        if self._method != "GET":
            headers["Content-Type"] = self._content_type(vals)
        key = vals.get("subscriptionKey")
        if key:
            headers["Ocp-Apim-Subscription-Key"] = str(key)
        entity = self._build_entity(vals) if self._method != "GET" else None
        return HTTPRequestData(url=url, method=self._method, headers=headers,
                               entity=entity)

    # -- async polling (ComputerVision.scala RecognizeText pattern) -------
    def _poll(self, resp: HTTPResponseData, headers: Dict[str, str],
              handler) -> HTTPResponseData:
        loc = None
        if resp.headers:
            loc = resp.headers.get("Operation-Location") \
                or resp.headers.get("operation-location")
        if not loc:
            return resp
        delay = self.get("pollingDelayMs") / 1000.0
        for _ in range(self.get("maxPollingRetries")):
            time.sleep(delay)
            poll = handler(HTTPRequestData(url=loc, method="GET",
                                           headers=dict(headers)))
            if poll.statusCode != 200 or poll.entity is None:
                continue
            obj = json.loads(poll.entity.decode("utf-8"))
            status = str(obj.get("status", "")).lower()
            if status in ("succeeded", "failed"):
                return poll
        return resp

    #: per-service typed response schema (a TypedStruct subclass or a
    #: typing.List[...] of one) — SparkBindings parity: responses are parsed
    #: into schema-checked structs, not raw JSON (cognitive/*Schemas.scala
    #: via core/schema/SparkBindings.scala:13-47). None = raw JSON.
    responseBinding = None

    typedOutput = Param("typedOutput",
                        "Parse responses into the typed schema (raw JSON "
                        "structs when False)", True, ptype=bool)

    def _parse_success(self, resp: HTTPResponseData) -> Any:
        """Map a 200 response to the output value: the service's typed
        response struct when a binding is declared (schema-checked; mismatch
        lands in errorCol), else the raw JSON."""
        obj = json.loads(resp.entity.decode("utf-8"))
        if self.responseBinding is not None and self.get("typedOutput"):
            from .schemas import _bind_value

            return _bind_value(self.responseBinding, obj, "$")
        return obj

    def transform(self, df: DataFrame) -> DataFrame:
        out_col = self.get_or_throw("outputCol")
        err_col = self.get("errorCol")
        handler = self.get("handler") or (
            lambda r: send_with_retries(
                r, timeout=self.get("timeout"),
                policy=self.get("retryPolicy"),
                deadline=faults.deadline_from_headers(r.headers)))

        def fn(part):
            names = list(part)
            n = len(part[names[0]]) if names else 0
            out = np.empty(n, dtype=object)
            errs = np.empty(n, dtype=object)
            for i in range(n):
                try:
                    req = self._build_request(part, i)
                except Exception as e:
                    out[i], errs[i] = None, f"request build failed: {e}"
                    continue
                if req is None:
                    out[i] = errs[i] = None
                    continue
                resp = handler(req)
                if self._is_async and resp.statusCode in (200, 202):
                    resp = self._poll(resp, req.headers or {}, handler)
                if resp.statusCode == 200 and resp.entity is not None:
                    try:
                        out[i] = self._parse_success(resp)
                        errs[i] = None
                    except Exception as e:
                        out[i], errs[i] = None, f"parse failed: {e}"
                else:
                    out[i] = None
                    errs[i] = f"{resp.statusCode}: {resp.statusLine}"
            part[out_col] = out
            if err_col:
                part[err_col] = errs
            return part

        return df.map_partitions(fn)

    def transform_schema(self, schema: Schema) -> Schema:
        out = schema.copy()
        out_col = self.get_or_throw("outputCol")
        out.types[out_col] = ColType.STRUCT
        if self.responseBinding is not None and self.get("typedOutput"):
            from .schemas import _type_schema

            # downstream consumers bind columns to fields against this
            # (SparkBindings .schema parity)
            out.meta(out_col)["response_schema"] = _type_schema(
                self.responseBinding)
        return out


class DocumentsBase(CognitiveServicesBase):
    """Text-analytics batch format: rows -> {documents: [{id, text, language}]}
    (cognitive/TextAnalytics.scala:171-230)."""

    _abstract = True

    text = ServiceParam("text", "Input text (value or column)")
    language = ServiceParam("language", "Language hint (value or column)")
    _service_param_names = ["text", "language"]

    def _build_entity(self, vals: Dict[str, Any]) -> bytes:
        doc = {"id": "0", "text": str(vals.get("text", ""))}
        if vals.get("language"):
            doc["language"] = str(vals["language"])
        return json.dumps({"documents": [doc]}).encode("utf-8")
