"""Face services (reference cognitive/Face.scala:18-280)."""

from __future__ import annotations

import json

from typing import List

from ..core.params import ServiceParam

from . import schemas as S
from .base import CognitiveServicesBase
from .vision import _ImageInputBase


class DetectFace(_ImageInputBase):
    """Face detection with attributes (Face.scala DetectFace).
    The response is a bare JSON array of faces (FaceSchemas.scala Face)."""

    responseBinding = List[S.DetectedFace]

    returnFaceId = ServiceParam("returnFaceId", "Include face ids")
    returnFaceLandmarks = ServiceParam("returnFaceLandmarks", "Include landmarks")
    returnFaceAttributes = ServiceParam("returnFaceAttributes",
                                        "Attribute list (age,gender,...)")
    _service_param_names = ["imageUrl", "imageBytes", "returnFaceId",
                            "returnFaceLandmarks", "returnFaceAttributes"]

    def _url_params(self, vals):
        q = {}
        if vals.get("returnFaceId") is not None:
            q["returnFaceId"] = str(bool(vals["returnFaceId"])).lower()
        if vals.get("returnFaceLandmarks") is not None:
            q["returnFaceLandmarks"] = str(bool(vals["returnFaceLandmarks"])).lower()
        attrs = vals.get("returnFaceAttributes")
        if attrs:
            q["returnFaceAttributes"] = (",".join(attrs)
                                         if isinstance(attrs, (list, tuple))
                                         else str(attrs))
        return q


class FindSimilarFace(CognitiveServicesBase):
    """Find similar faces from a face list (Face.scala FindSimilar)."""

    responseBinding = List[S.FoundFace]

    faceId = ServiceParam("faceId", "Query face id")
    faceIds = ServiceParam("faceIds", "Candidate face ids")
    faceListId = ServiceParam("faceListId", "Face list id")
    maxNumOfCandidatesReturned = ServiceParam("maxNumOfCandidatesReturned",
                                              "Max candidates")
    mode = ServiceParam("mode", "matchPerson | matchFace")
    _service_param_names = ["faceId", "faceIds", "faceListId",
                            "maxNumOfCandidatesReturned", "mode"]


class GroupFaces(CognitiveServicesBase):
    """Group face ids by similarity (Face.scala Group)."""

    faceIds = ServiceParam("faceIds", "Face ids to group")
    _service_param_names = ["faceIds"]


class IdentifyFaces(CognitiveServicesBase):
    """Identify faces against a person group (Face.scala Identify)."""

    faceIds = ServiceParam("faceIds", "Face ids")
    personGroupId = ServiceParam("personGroupId", "Person group")
    maxNumOfCandidatesReturned = ServiceParam("maxNumOfCandidatesReturned",
                                              "Max candidates")
    confidenceThreshold = ServiceParam("confidenceThreshold", "Min confidence")
    _service_param_names = ["faceIds", "personGroupId",
                            "maxNumOfCandidatesReturned", "confidenceThreshold"]


class VerifyFaces(CognitiveServicesBase):
    """Verify two faces belong to the same person (Face.scala Verify)."""

    faceId1 = ServiceParam("faceId1", "First face id")
    faceId2 = ServiceParam("faceId2", "Second face id")
    faceId = ServiceParam("faceId", "Face id (vs person)")
    personGroupId = ServiceParam("personGroupId", "Person group")
    personId = ServiceParam("personId", "Person id")
    _service_param_names = ["faceId1", "faceId2", "faceId", "personGroupId",
                            "personId"]
