"""Azure Search writer (reference cognitive/AzureSearch.scala:26-136 +
AzureSearchAPI.scala:42 index management)."""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, ServiceParam
from .base import CognitiveServicesBase
from ..io.http import HTTPRequestData, send_with_retries


class AddDocuments(CognitiveServicesBase):
    """Batch-upload rows as search documents (AzureSearch.scala AddDocuments)."""

    serviceName = Param("serviceName", "Search service name", None, ptype=str)
    indexName = Param("indexName", "Target index", None, ptype=str)
    actionCol = Param("actionCol", "Per-row @search.action column", None, ptype=str)
    batchSize = Param("batchSize", "Docs per request", 100, ptype=int)

    def _endpoint(self) -> str:
        if self.get("url"):
            return self.get("url")
        return (f"https://{self.get_or_throw('serviceName')}.search.windows.net"
                f"/indexes/{self.get_or_throw('indexName')}/docs/index"
                f"?api-version=2019-05-06")

    def transform(self, df: DataFrame) -> DataFrame:
        out_col = self.get_or_throw("outputCol")
        handler = self.get("handler") or send_with_retries
        action_col = self.get("actionCol")
        batch = self.get("batchSize")
        key = None
        sk = self.get("subscriptionKey")
        if sk:
            if "value" in sk:
                key = sk["value"]
            else:  # column-backed key: one service key per dataset, take row 0
                col = df.column(sk["col"])
                key = col[0] if len(col) else None
        rows = df.rows()
        statuses: List[Any] = []
        for start in range(0, len(rows), batch):
            chunk = rows[start:start + batch]
            docs = []
            for r in chunk:
                doc = {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                       for k, v in r.items()}
                doc["@search.action"] = (doc.pop(action_col)
                                         if action_col and action_col in doc
                                         else "upload")
                docs.append(doc)
            headers = {"Content-Type": "application/json"}
            if key:
                headers["api-key"] = str(key)
            req = HTTPRequestData(url=self._endpoint(), method="POST",
                                  headers=headers,
                                  entity=json.dumps({"value": docs}).encode())
            resp = handler(req)
            status = resp.statusCode
            statuses.extend([status] * len(chunk))
        return df.with_column(out_col, np.asarray(statuses, dtype=np.int64))


class AzureSearchWriter:
    """df -> Azure Search index (AzureSearchWriter.write parity)."""

    @staticmethod
    def write(df: DataFrame, subscription_key: str, service_name: str,
              index_name: str, handler=None, batch_size: int = 100) -> DataFrame:
        stage = AddDocuments(outputCol="status", serviceName=service_name,
                             indexName=index_name, batchSize=batch_size)
        stage.set_scalar("subscriptionKey", subscription_key)
        if handler is not None:
            stage.set("handler", handler)
        return stage.transform(df)
