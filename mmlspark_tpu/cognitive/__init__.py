"""Cognitive services as pipeline stages (reference cognitive/ package).

Azure AI REST services wrapped as transformers over the HTTP stack:
vision (OCR/analyze/tag/describe/thumbnails/recognize-text-with-polling),
text analytics (sentiment/language/entities/NER/key phrases), face, speech,
anomaly detection, Bing image search, Azure Search writer. Every stage uses
value-or-column ServiceParams (cognitive/CognitiveServiceBase.scala:29-151) and
typed response schemas (SparkBindings parity via dataclasses).
"""

from .base import CognitiveServicesBase, HasServiceParams
from .vision import (
    OCR,
    AnalyzeImage,
    DescribeImage,
    GenerateThumbnails,
    RecognizeDomainSpecificContent,
    RecognizeText,
    TagImage,
)
from .text import (
    EntityDetector,
    KeyPhraseExtractor,
    LanguageDetector,
    NER,
    TextSentiment,
)
from .face import DetectFace, FindSimilarFace, GroupFaces, IdentifyFaces, VerifyFaces
from .speech import SpeechToText
from .anomaly import DetectAnomalies, DetectLastAnomaly, SimpleDetectAnomalies
from .bing import BingImageSearch
from .search import AddDocuments, AzureSearchWriter

__all__ = [
    "AddDocuments", "AnalyzeImage", "AzureSearchWriter", "BingImageSearch",
    "CognitiveServicesBase", "DescribeImage", "DetectAnomalies",
    "DetectFace", "DetectLastAnomaly", "EntityDetector", "FindSimilarFace",
    "GenerateThumbnails", "GroupFaces", "HasServiceParams", "IdentifyFaces",
    "KeyPhraseExtractor", "LanguageDetector", "NER", "OCR",
    "RecognizeDomainSpecificContent", "RecognizeText", "SimpleDetectAnomalies",
    "SpeechToText", "TagImage", "TextSentiment", "VerifyFaces",
]
