"""Text-analytics services (reference cognitive/TextAnalytics.scala:171-230)."""

from .base import DocumentsBase


class TextSentiment(DocumentsBase):
    """Sentiment scoring per document."""


class LanguageDetector(DocumentsBase):
    """Language detection (no language hint input)."""

    _service_param_names = ["text"]


class EntityDetector(DocumentsBase):
    """Linked-entity detection."""


class NER(DocumentsBase):
    """Named-entity recognition."""


class KeyPhraseExtractor(DocumentsBase):
    """Key-phrase extraction."""
