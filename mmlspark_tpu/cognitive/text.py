"""Text-analytics services (reference cognitive/TextAnalytics.scala:171-230).

Responses parse into the typed schemas of schemas.py
(TextAnalyticsSchemas.scala parity)."""

from . import schemas as S
from .base import DocumentsBase


class TextSentiment(DocumentsBase):
    """Sentiment scoring per document."""

    responseBinding = S.SentimentResponse


class LanguageDetector(DocumentsBase):
    """Language detection (no language hint input)."""

    _service_param_names = ["text"]
    responseBinding = S.DetectLanguageResponse


class EntityDetector(DocumentsBase):
    """Linked-entity detection."""

    responseBinding = S.DetectEntitiesResponse


class NER(DocumentsBase):
    """Named-entity recognition."""

    responseBinding = S.NERResponse


class KeyPhraseExtractor(DocumentsBase):
    """Key-phrase extraction."""

    responseBinding = S.KeyPhraseResponse
