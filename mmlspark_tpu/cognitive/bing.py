"""Bing image search (reference cognitive/BingImageSearch.scala)."""

from __future__ import annotations

import json
from typing import Any, Dict

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ServiceParam
from .base import CognitiveServicesBase


class BingImageSearch(CognitiveServicesBase):
    """Query -> image search results (GET with q= param)."""

    q = ServiceParam("q", "Search query (value or column)")
    count = ServiceParam("count", "Results per query")
    offset = ServiceParam("offset", "Result offset")
    imageType = ServiceParam("imageType", "photo/clipart/...")
    _service_param_names = ["q", "count", "offset", "imageType"]
    _method = "GET"

    def _url_params(self, vals):
        q = {"q": str(vals.get("q", ""))}
        for k in ("count", "offset"):
            if vals.get(k) is not None:
                q[k] = str(int(vals[k]))
        if vals.get("imageType"):
            q["imageType"] = str(vals["imageType"])
        return q

    @staticmethod
    def get_url_transformer(image_col: str, url_col: str):
        """Extract contentUrl list from search results (reference helper)."""
        from ..core.pipeline import Transformer
        from ..stages.basic import UDFTransformer

        def extract(v):
            if v is None:
                return None
            return [img.get("contentUrl") for img in v.get("value", [])]

        t = UDFTransformer(inputCol=image_col, outputCol=url_col)
        t.set("udf", extract)
        return t
