"""Typed response schemas for the cognitive services.

Reference parity: the reference ships per-service response case classes bound
to Spark rows via SparkBindings (cognitive/TextAnalyticsSchemas.scala,
ComputerVisionSchemas.scala, FaceSchemas.scala, AnomalyDetectorSchemas.scala,
SpeechSchemas.scala, all built on core/schema/SparkBindings.scala:13-47) so
downstream stages can bind columns to fields with schema checking. Here the
equivalent is a dataclass binding layer: every service declares a response
dataclass; JSON responses are parsed INTO it with per-field type validation
(wrong shapes raise BindingError with a JSON-path), and the bound structs
support both attribute and item access so column consumers can navigate
``resp.documents[0].score`` or ``resp["documents"][0]["score"]``.

``struct_schema(cls)`` emits a JSON-able schema description that transform
stages attach to the output column's metadata — the SparkBindings .schema
equivalent downstream checks can validate against.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, List, Optional


class BindingError(TypeError):
    """A JSON response does not match the declared schema."""


@dataclasses.dataclass
class TypedStruct:
    """Base for bound response structs: attribute + item access, dict-ish."""

    def __getitem__(self, key):
        try:
            return getattr(self, key)
        except AttributeError:
            raise KeyError(key)

    def get(self, key, default=None):
        return getattr(self, key, default)

    def keys(self):
        return [f.name for f in dataclasses.fields(self)]

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def bind(cls, obj, path: str = "$"):
    """Parse ``obj`` (decoded JSON) into dataclass ``cls``, validating every
    field's type recursively. Unknown JSON fields are ignored (APIs add
    fields); missing non-Optional fields raise."""
    if not (isinstance(cls, type) and issubclass(cls, TypedStruct)):
        raise TypeError(f"{cls} is not a TypedStruct")
    if not isinstance(obj, dict):
        raise BindingError(
            f"{path}: expected object for {cls.__name__}, got "
            f"{type(obj).__name__}")
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for fld in dataclasses.fields(cls):
        kwargs[fld.name] = _bind_value(hints[fld.name], obj.get(fld.name),
                                       f"{path}.{fld.name}")
    return cls(**kwargs)


def _bind_value(t, v, path):
    origin = typing.get_origin(t)
    if origin is typing.Union:  # Optional[T] (the only union used here)
        args = [a for a in typing.get_args(t) if a is not type(None)]
        if v is None:
            return None
        return _bind_value(args[0], v, path)
    if v is None:
        raise BindingError(f"{path}: missing required field")
    if origin is list:
        (elt,) = typing.get_args(t)
        if not isinstance(v, (list, tuple)):
            raise BindingError(f"{path}: expected array, got "
                               f"{type(v).__name__}")
        return [_bind_value(elt, x, f"{path}[{i}]") for i, x in enumerate(v)]
    if isinstance(t, type) and issubclass(t, TypedStruct):
        return bind(t, v, path)
    if t is float:
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise BindingError(f"{path}: expected number, got "
                               f"{type(v).__name__}")
        return float(v)
    if t is int:
        if isinstance(v, bool) or not isinstance(v, int):
            raise BindingError(f"{path}: expected integer, got "
                               f"{type(v).__name__}")
        return int(v)
    if t is bool:
        if not isinstance(v, bool):
            raise BindingError(f"{path}: expected boolean, got "
                               f"{type(v).__name__}")
        return v
    if t is str:
        if not isinstance(v, str):
            raise BindingError(f"{path}: expected string, got "
                               f"{type(v).__name__}")
        return str(v)
    if t is Any:
        return v
    raise BindingError(f"{path}: unsupported schema type {t!r}")


def struct_schema(cls) -> dict:
    """JSON-able schema description of a TypedStruct (SparkBindings.schema
    equivalent, attached to output-column metadata)."""
    hints = typing.get_type_hints(cls)
    return {"struct": cls.__name__,
            "fields": {f.name: _type_schema(hints[f.name])
                       for f in dataclasses.fields(cls)}}


def _type_schema(t):
    origin = typing.get_origin(t)
    if origin is typing.Union:
        args = [a for a in typing.get_args(t) if a is not type(None)]
        return {"optional": _type_schema(args[0])}
    if origin is list:
        (elt,) = typing.get_args(t)
        return {"array": _type_schema(elt)}
    if isinstance(t, type) and issubclass(t, TypedStruct):
        return struct_schema(t)
    if t is Any:
        return "any"
    return t.__name__


# ---------------------------------------------------------------------------
# Text analytics (TextAnalyticsSchemas.scala)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TAError(TypedStruct):
    id: str
    message: str


@dataclasses.dataclass
class SentimentScore(TypedStruct):
    id: str
    score: float


@dataclasses.dataclass
class SentimentResponse(TypedStruct):
    documents: List[SentimentScore]
    errors: Optional[List[TAError]] = None


@dataclasses.dataclass
class DetectedLanguage(TypedStruct):
    name: str
    iso6391Name: str
    score: float


@dataclasses.dataclass
class DetectLanguageScore(TypedStruct):
    id: str
    detectedLanguages: List[DetectedLanguage]


@dataclasses.dataclass
class DetectLanguageResponse(TypedStruct):
    documents: List[DetectLanguageScore]
    errors: Optional[List[TAError]] = None


@dataclasses.dataclass
class Match(TypedStruct):
    text: str
    offset: int
    length: int


@dataclasses.dataclass
class Entity(TypedStruct):
    name: str
    matches: List[Match]
    wikipediaLanguage: Optional[str] = None
    wikipediaId: Optional[str] = None
    wikipediaUrl: Optional[str] = None
    bingId: Optional[str] = None


@dataclasses.dataclass
class DetectEntitiesScore(TypedStruct):
    id: str
    entities: List[Entity]


@dataclasses.dataclass
class DetectEntitiesResponse(TypedStruct):
    documents: List[DetectEntitiesScore]
    errors: Optional[List[TAError]] = None


@dataclasses.dataclass
class NERMatch(TypedStruct):
    text: str
    offset: int
    length: int
    entityTypeScore: Optional[float] = None


@dataclasses.dataclass
class NEREntity(TypedStruct):
    name: str
    matches: List[NERMatch]
    type: Optional[str] = None
    subtype: Optional[str] = None
    wikipediaLanguage: Optional[str] = None
    wikipediaId: Optional[str] = None
    wikipediaUrl: Optional[str] = None
    bingId: Optional[str] = None


@dataclasses.dataclass
class NERDoc(TypedStruct):
    id: str
    entities: List[NEREntity]


@dataclasses.dataclass
class NERResponse(TypedStruct):
    documents: List[NERDoc]
    errors: Optional[List[TAError]] = None


@dataclasses.dataclass
class KeyPhraseScore(TypedStruct):
    id: str
    keyPhrases: List[str]


@dataclasses.dataclass
class KeyPhraseResponse(TypedStruct):
    documents: List[KeyPhraseScore]
    errors: Optional[List[TAError]] = None


# ---------------------------------------------------------------------------
# Computer vision (ComputerVisionSchemas.scala)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class OCRWord(TypedStruct):
    boundingBox: str
    text: str


@dataclasses.dataclass
class OCRLine(TypedStruct):
    boundingBox: str
    words: List[OCRWord]


@dataclasses.dataclass
class OCRRegion(TypedStruct):
    boundingBox: str
    lines: List[OCRLine]


@dataclasses.dataclass
class OCRResponse(TypedStruct):
    language: str
    regions: List[OCRRegion]
    textAngle: Optional[float] = None
    orientation: Optional[str] = None


@dataclasses.dataclass
class ImageTag(TypedStruct):
    name: str
    confidence: float
    hint: Optional[str] = None


@dataclasses.dataclass
class ImageCaption(TypedStruct):
    text: str
    confidence: float


@dataclasses.dataclass
class ImageDescription(TypedStruct):
    tags: List[str]
    captions: List[ImageCaption]


@dataclasses.dataclass
class ImageMetadata(TypedStruct):
    width: Optional[int] = None
    height: Optional[int] = None
    format: Optional[str] = None


@dataclasses.dataclass
class ImageCategory(TypedStruct):
    name: str
    score: float


@dataclasses.dataclass
class FaceRectangle(TypedStruct):
    left: int
    top: int
    width: int
    height: int


@dataclasses.dataclass
class AIFace(TypedStruct):
    faceRectangle: FaceRectangle
    age: Optional[int] = None
    gender: Optional[str] = None


@dataclasses.dataclass
class ColorInfo(TypedStruct):
    dominantColorForeground: Optional[str] = None
    dominantColorBackground: Optional[str] = None
    dominantColors: Optional[List[str]] = None
    accentColor: Optional[str] = None
    isBWImg: Optional[bool] = None


@dataclasses.dataclass
class AIResponse(TypedStruct):
    """AnalyzeImage response (features present only when requested)."""

    requestId: Optional[str] = None
    metadata: Optional[ImageMetadata] = None
    categories: Optional[List[ImageCategory]] = None
    tags: Optional[List[ImageTag]] = None
    description: Optional[ImageDescription] = None
    faces: Optional[List[AIFace]] = None
    color: Optional[ColorInfo] = None
    imageType: Optional[Any] = None
    adult: Optional[Any] = None


@dataclasses.dataclass
class TagImagesResponse(TypedStruct):
    tags: List[ImageTag]
    requestId: Optional[str] = None
    metadata: Optional[ImageMetadata] = None


@dataclasses.dataclass
class DescribeImageResponse(TypedStruct):
    description: ImageDescription
    requestId: Optional[str] = None
    metadata: Optional[ImageMetadata] = None


@dataclasses.dataclass
class RTWord(TypedStruct):
    boundingBox: List[int]
    text: str


@dataclasses.dataclass
class RTLine(TypedStruct):
    boundingBox: List[int]
    text: str
    words: List[RTWord]


@dataclasses.dataclass
class RTResult(TypedStruct):
    lines: List[RTLine]


@dataclasses.dataclass
class RTResponse(TypedStruct):
    """RecognizeText async result (status + recognitionResult)."""

    status: str
    recognitionResult: Optional[RTResult] = None


@dataclasses.dataclass
class DSIRCelebrity(TypedStruct):
    name: str
    confidence: float
    faceRectangle: Optional[FaceRectangle] = None


@dataclasses.dataclass
class DSIRLandmark(TypedStruct):
    name: str
    confidence: float


@dataclasses.dataclass
class DSIRResult(TypedStruct):
    celebrities: Optional[List[DSIRCelebrity]] = None
    landmarks: Optional[List[DSIRLandmark]] = None


@dataclasses.dataclass
class DSIRResponse(TypedStruct):
    """RecognizeDomainSpecificContent response."""

    result: DSIRResult
    requestId: Optional[str] = None
    metadata: Optional[ImageMetadata] = None


# ---------------------------------------------------------------------------
# Face (FaceSchemas.scala)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Emotion(TypedStruct):
    anger: Optional[float] = None
    contempt: Optional[float] = None
    disgust: Optional[float] = None
    fear: Optional[float] = None
    happiness: Optional[float] = None
    neutral: Optional[float] = None
    sadness: Optional[float] = None
    surprise: Optional[float] = None


@dataclasses.dataclass
class FaceAttributes(TypedStruct):
    age: Optional[float] = None
    gender: Optional[str] = None
    smile: Optional[float] = None
    glasses: Optional[str] = None
    emotion: Optional[Emotion] = None


@dataclasses.dataclass
class DetectedFace(TypedStruct):
    faceId: Optional[str] = None
    faceRectangle: Optional[FaceRectangle] = None
    faceAttributes: Optional[FaceAttributes] = None
    faceLandmarks: Optional[Any] = None


@dataclasses.dataclass
class FoundFace(TypedStruct):
    persistedFaceId: Optional[str] = None
    faceId: Optional[str] = None
    confidence: Optional[float] = None


# ---------------------------------------------------------------------------
# Anomaly detection (AnomalyDetectorSchemas.scala)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ADEntireResponse(TypedStruct):
    isAnomaly: List[bool]
    isPositiveAnomaly: List[bool]
    isNegativeAnomaly: List[bool]
    period: int
    expectedValues: List[float]
    upperMargins: List[float]
    lowerMargins: List[float]


@dataclasses.dataclass
class ADLastResponse(TypedStruct):
    isAnomaly: bool
    isPositiveAnomaly: bool
    isNegativeAnomaly: bool
    period: int
    expectedValue: float
    upperMargin: float
    lowerMargin: float
    suggestedWindow: Optional[int] = None


# ---------------------------------------------------------------------------
# Speech (SpeechSchemas.scala)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SpeechNBest(TypedStruct):
    Confidence: Optional[float] = None
    Lexical: Optional[str] = None
    ITN: Optional[str] = None
    MaskedITN: Optional[str] = None
    Display: Optional[str] = None


@dataclasses.dataclass
class SpeechResponse(TypedStruct):
    RecognitionStatus: str
    Offset: Optional[int] = None
    Duration: Optional[int] = None
    DisplayText: Optional[str] = None
    NBest: Optional[List[SpeechNBest]] = None
