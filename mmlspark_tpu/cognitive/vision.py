"""Computer-vision services (reference cognitive/ComputerVision.scala:165-520).

Responses parse into the typed schemas of schemas.py
(ComputerVisionSchemas.scala parity)."""

from __future__ import annotations

import json
from typing import Any, Dict

from ..core.params import Param, ServiceParam
from . import schemas as S
from .base import CognitiveServicesBase


class _ImageInputBase(CognitiveServicesBase):
    """Accepts an image URL (JSON body) or raw bytes (octet-stream body)."""

    imageUrl = ServiceParam("imageUrl", "Image URL (value or column)")
    imageBytes = ServiceParam("imageBytes", "Raw image bytes (value or column)")
    _service_param_names = ["imageUrl", "imageBytes"]

    def _validate(self, vals):
        if vals.get("imageUrl") is None and vals.get("imageBytes") is None:
            raise ValueError("one of imageUrl/imageBytes is required")

    def _content_type(self, vals):
        return ("application/octet-stream" if vals.get("imageBytes") is not None
                else "application/json")

    def _build_entity(self, vals):
        if vals.get("imageBytes") is not None:
            return bytes(vals["imageBytes"])
        return json.dumps({"url": str(vals.get("imageUrl", ""))}).encode("utf-8")


class OCR(_ImageInputBase):
    """Printed-text OCR (ComputerVision.scala OCR)."""

    responseBinding = S.OCRResponse

    detectOrientation = ServiceParam("detectOrientation", "Detect text orientation")
    language = ServiceParam("language", "Language hint")
    _service_param_names = ["imageUrl", "imageBytes", "detectOrientation",
                            "language"]

    def _url_params(self, vals):
        q = {}
        if vals.get("language"):
            q["language"] = str(vals["language"])
        if vals.get("detectOrientation") is not None:
            q["detectOrientation"] = str(bool(vals["detectOrientation"])).lower()
        return q


class RecognizeText(_ImageInputBase):
    """Async handwritten/printed text recognition with Operation-Location
    polling (ComputerVision.scala:165-260)."""

    mode = ServiceParam("mode", "'Printed' or 'Handwritten'")
    _service_param_names = ["imageUrl", "imageBytes", "mode"]
    _is_async = True
    responseBinding = S.RTResponse

    def _url_params(self, vals):
        return {"mode": str(vals["mode"])} if vals.get("mode") else {}


class AnalyzeImage(_ImageInputBase):
    """Full image analysis (ComputerVision.scala AnalyzeImage)."""

    responseBinding = S.AIResponse

    visualFeatures = ServiceParam("visualFeatures", "Comma/list of features")
    details = ServiceParam("details", "Detail domains")
    language = ServiceParam("language", "Result language")
    _service_param_names = ["imageUrl", "imageBytes", "visualFeatures",
                            "details", "language"]

    def _url_params(self, vals):
        q = {}
        for name, key in (("visualFeatures", "visualFeatures"),
                          ("details", "details"), ("language", "language")):
            v = vals.get(name)
            if v is not None:
                q[key] = ",".join(v) if isinstance(v, (list, tuple)) else str(v)
        return q


class TagImage(_ImageInputBase):
    """Image tagging (ComputerVision.scala TagImage)."""

    responseBinding = S.TagImagesResponse


class DescribeImage(_ImageInputBase):
    """Caption generation (ComputerVision.scala DescribeImage)."""

    responseBinding = S.DescribeImageResponse

    maxCandidates = ServiceParam("maxCandidates", "Caption candidates")
    _service_param_names = ["imageUrl", "imageBytes", "maxCandidates"]

    def _url_params(self, vals):
        if vals.get("maxCandidates") is not None:
            return {"maxCandidates": str(int(vals["maxCandidates"]))}
        return {}


class GenerateThumbnails(_ImageInputBase):
    """Smart-cropped thumbnails (ComputerVision.scala GenerateThumbnails).
    Response is binary image bytes, not JSON."""

    width = ServiceParam("width", "Thumbnail width")
    height = ServiceParam("height", "Thumbnail height")
    smartCropping = ServiceParam("smartCropping", "Enable smart cropping")
    _service_param_names = ["imageUrl", "imageBytes", "width", "height",
                            "smartCropping"]

    def _url_params(self, vals):
        q = {"width": str(int(vals.get("width", 64))),
             "height": str(int(vals.get("height", 64)))}
        if vals.get("smartCropping") is not None:
            q["smartCropping"] = str(bool(vals["smartCropping"])).lower()
        return q

    def _parse_success(self, resp):
        return resp.entity  # binary thumbnail bytes, not JSON


class RecognizeDomainSpecificContent(_ImageInputBase):
    """Domain models, e.g. celebrities/landmarks (ComputerVision.scala:470-520)."""

    responseBinding = S.DSIRResponse

    model = ServiceParam("model", "Domain model name")
    _service_param_names = ["imageUrl", "imageBytes", "model"]
