"""Speech-to-text service (reference cognitive/SpeechToText.scala:22-100)."""

from __future__ import annotations

from ..core.params import ServiceParam
from . import schemas as S
from .base import CognitiveServicesBase


class SpeechToText(CognitiveServicesBase):
    """Audio bytes -> transcription (SpeechSchemas.scala parity)."""

    responseBinding = S.SpeechResponse

    audioData = ServiceParam("audioData", "Audio bytes (value or column)")
    language = ServiceParam("language", "Spoken language")
    format = ServiceParam("format", "simple | detailed")
    profanity = ServiceParam("profanity", "masked | removed | raw")
    _service_param_names = ["audioData", "language", "format", "profanity"]

    def _content_type(self, vals):
        return "audio/wav; codec=audio/pcm; samplerate=16000"

    def _build_entity(self, vals):
        return bytes(vals.get("audioData", b""))

    def _url_params(self, vals):
        q = {}
        if vals.get("language"):
            q["language"] = str(vals["language"])
        if vals.get("format"):
            q["format"] = str(vals["format"])
        if vals.get("profanity"):
            q["profanity"] = str(vals["profanity"])
        return q
