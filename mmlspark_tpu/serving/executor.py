"""Async pipelined serving: overlapped drain/compute/readback executor.

``ServingServer._loop`` is strictly serial — drain -> transform -> fulfill ->
drain — so the device idles during host drain/journal/fulfill and the host
idles during compute. This module rebuilds the hot path as a pipelined
executor (the Orca/continuous-batching shape; cf. TVM's decoupled
schedule/compute split, arXiv:1802.04799):

    ingress queue --[drain/coalesce/journal]--> submit queue
                  --[compute: one worker per replica]--> ready queue
                  --[readback/fulfill thread]--> reply slots

  - The DRAIN stage coalesces batch N+1 while batch N computes. Once the
    coalescing window closes it keeps absorbing arrivals until an in-flight
    slot frees (bounded by ``inflight``), so a saturated server forms
    convoy-merged batches with no idle coalescing sleep — the static
    ``max_wait_ms`` tax the sync loop pays every cycle.
  - The COMPUTE stage runs one worker per replica. Transforms that expose a
    ``submit()`` protocol (fused pipelines — core/fusion.py
    ``transform_submit``) dispatch without blocking and hand a
    device-resident pending handle downstream, exploiting JAX async
    dispatch; plain transforms compute in place (their XLA sections release
    the GIL, so drain/readback still overlap them).
  - The READBACK thread resolves pending outputs, fulfills reply slots,
    feeds the adaptive controller, and commits journal epochs.

Epoch/journal at-least-once semantics, deadline 504 gates, and graceful
drain are shared with the sync loop (both paths call the same
``_prepare_batch`` / ``_apply_output`` server helpers), so replies are
bitwise-identical between the two modes.

``ReplicaSet`` places R copies of the transform round-robin across
``jax.local_devices()`` — on a multi-chip host each replica computes on its
own device; on a single-device host replicas still pipeline host-side work.
``AdaptiveBatchController`` replaces the static coalescing window with a
self-tuning one that holds queue wait ~= alpha * compute time (the
``max_wait_sweep`` in BENCH_serving.json shows the static optimum shifts
with load).
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
import queue as queue_mod
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..core import faults
from ..obs import trace as obs_trace

__all__ = ["AdaptiveBatchController", "PipelinedExecutor", "Replica",
           "ReplicaSet"]

_LOG = logging.getLogger("mmlspark_tpu.serving")


# ---------------------------------------------------------------------------
# Adaptive batching controller
# ---------------------------------------------------------------------------


class AdaptiveBatchController:
    """Self-tuning coalescing window: hold queue_ms ~= alpha * compute_ms.

    The static ``max_wait_ms`` has a load-dependent optimum (the
    ``max_wait_sweep_resnet18`` in BENCH_serving.json: 0 ms serializes
    requests behind full computes under load, while any wait at all is pure
    added latency for a single-stream client). Under the executor's
    slot-aware drain, BACKPRESSURE already merges convoys while every
    in-flight slot is busy — the explicit window only delays dispatch when
    a slot is FREE. So the window's job reduces to: spend at most
    ``alpha * compute`` of extra latency coalescing co-arrivals, minus the
    queue wait the load already imposes:

        window = clamp(alpha * compute_ewma - queue_ewma, min, max)

    gated on co-arrival evidence (batch-rows EWMA > 1): a single-stream
    client never pays a coalescing wait nobody else will join. At
    saturation queue_ewma ~ compute_ewma, so the window collapses to
    ``min_wait_ms`` and batching comes entirely from backpressure; under
    light concurrent load the window opens to merge near-simultaneous
    arrivals within the latency budget.
    """

    def __init__(self, alpha: float = 0.5, min_wait_ms: float = 0.0,
                 max_wait_ms: float = 50.0, init_wait_ms: float = 5.0,
                 ewma: float = 0.25, solo_rows: float = 1.2):
        self.alpha = float(alpha)
        self.min_wait_ms = float(min_wait_ms)
        self.max_wait_ms = float(max_wait_ms)
        self.ewma = float(ewma)
        #: batch-rows EWMA at or below this means "no co-arrivals": the
        #: window stays at min (waiting coalesces nothing)
        self.solo_rows = float(solo_rows)
        self._wait = min(max(float(init_wait_ms), self.min_wait_ms),
                         self.max_wait_ms)
        self._compute_ms: Optional[float] = None
        self._queue_ms: Optional[float] = None
        self._rows: Optional[float] = None
        self._depth: float = 0.0
        self._updates = 0
        self._seeded = False
        self._lock = threading.Lock()

    def window_ms(self) -> float:
        with self._lock:
            return self._wait

    def set_window_clamp(self, max_wait_ms: float) -> float:
        """Re-bound the window's upper clamp live (the brownout
        controller's knob): returns the PREVIOUS clamp so the caller can
        restore it. The current wait is re-clamped immediately."""
        with self._lock:
            prev = self.max_wait_ms
            self.max_wait_ms = max(float(max_wait_ms), self.min_wait_ms)
            self._wait = min(self._wait, self.max_wait_ms)
            return prev

    def seed_compute_ms(self, compute_ms: float) -> None:
        """Model-informed cold start (core/tune.py Tuner): seed the compute
        EWMA with the cost model's predicted per-batch compute so the first
        windows are sized from a prediction instead of the ``init_wait_ms``
        guess. A seed never overrides MEASURED state: once observe() has
        run, it only re-anchors the EWMA blend."""
        with self._lock:
            self._seeded = True
            if self._compute_ms is None:
                self._compute_ms = float(compute_ms)
                if self._rows is not None and self._rows > self.solo_rows:
                    w = self.alpha * self._compute_ms - (self._queue_ms or 0.0)
                    self._wait = min(self.max_wait_ms,
                                     max(self.min_wait_ms, w))
            else:
                self._compute_ms = self._ewma(self._compute_ms,
                                              float(compute_ms))

    def _ewma(self, prev: Optional[float], x: float) -> float:
        return x if prev is None else (1 - self.ewma) * prev + self.ewma * x

    def observe(self, compute_s: float, queue_s: float, batch_rows: int,
                queue_depth: int) -> None:
        """Feed one completed batch: compute+readback seconds, mean queue
        wait of its rows, its row count, and the ingress depth left behind."""
        with self._lock:
            self._updates += 1
            self._compute_ms = self._ewma(self._compute_ms, compute_s * 1e3)
            self._queue_ms = self._ewma(self._queue_ms, queue_s * 1e3)
            self._rows = self._ewma(self._rows, float(batch_rows))
            self._depth = self._ewma(self._depth, float(queue_depth))
            if self._rows <= self.solo_rows:
                w = self.min_wait_ms
            else:
                w = self.alpha * self._compute_ms - self._queue_ms
            self._wait = min(self.max_wait_ms, max(self.min_wait_ms, w))

    def state(self) -> Dict[str, Any]:
        """Live controller state for /_mmlspark/stats: the tuned window AND
        the governing knobs (alpha/min/max), so a running server's batching
        configuration is inspectable, not constructor-only."""
        with self._lock:
            rnd = lambda v: None if v is None else round(v, 4)  # noqa: E731
            return {"wait_ms": round(self._wait, 4),
                    "compute_ewma_ms": rnd(self._compute_ms),
                    "queue_ewma_ms": rnd(self._queue_ms),
                    "rows_ewma": rnd(self._rows),
                    "target_queue_ms": rnd(
                        None if self._compute_ms is None
                        else self.alpha * self._compute_ms),
                    "depth_ewma": round(self._depth, 3),
                    "alpha": self.alpha,
                    "min_wait_ms": self.min_wait_ms,
                    "max_wait_ms": self.max_wait_ms,
                    "seeded": self._seeded,
                    "updates": self._updates}


# ---------------------------------------------------------------------------
# Replicas
# ---------------------------------------------------------------------------


class Replica:
    """One placed copy of the serving transform (device + counters)."""

    __slots__ = ("index", "device", "transform", "batches", "rows", "busy_s")

    def __init__(self, index: int, device: Any, transform: Callable):
        self.index = index
        self.device = device
        self.transform = transform
        self.batches = 0
        self.rows = 0
        self.busy_s = 0.0


class ReplicaSet:
    """R replicas of the serving transform placed round-robin across local
    devices (the data-parallel dispatch of Automap, arXiv:2112.02958,
    applied to whole serving batches).

    ``devices`` defaults to ``jax.local_devices()`` (a single ``None``
    pseudo-device when jax is unavailable, keeping the executor usable for
    host-only transforms). ``transform_factory(index, device)`` builds a
    per-replica transform — per-replica CompileCaches, per-replica model
    copies; the default shares ``transform`` across replicas (jit dispatch
    is thread-safe and executables are cached per device).
    """

    def __init__(self, transform: Optional[Callable] = None, n: int = 1,
                 devices: Optional[List[Any]] = None,
                 transform_factory: Optional[Callable] = None):
        if transform is None and transform_factory is None:
            raise ValueError("need transform or transform_factory")
        if devices is None:
            devices = self._local_devices()
        if not devices:
            devices = [None]
        self.replicas: List[Replica] = []
        #: placements skipped because replica init raised: (index, device,
        #: error string) — surfaced in describe()/stats so a degraded start
        #: is visible, not silent
        self.placement_failures: List[Dict[str, Any]] = []
        for i in range(max(1, int(n))):
            dev = devices[i % len(devices)]
            # a device that raises at replica init (driver fault, OOM on one
            # chip) must not fail the whole server start: log, skip it, and
            # serve on the survivors; raise only when nothing survives
            try:
                t = transform_factory(i, dev) \
                    if transform_factory is not None else transform
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                _LOG.warning(
                    "replica %d init failed on device %s — placing the "
                    "remaining replicas without it", i, dev, exc_info=True)
                self.placement_failures.append(
                    {"replica": i, "device": str(dev) if dev is not None
                     else None, "error": str(e)})
                continue
            self.replicas.append(Replica(i, dev, t))
        if not self.replicas:
            raise RuntimeError(
                "every replica placement failed: "
                + "; ".join(f"replica {f['replica']} on {f['device']}: "
                            f"{f['error']}"
                            for f in self.placement_failures))

    def __len__(self) -> int:
        return len(self.replicas)

    @staticmethod
    def _local_devices() -> List[Any]:
        try:
            import jax

            return list(jax.local_devices())
        except Exception:  # noqa: BLE001 — host-only deployment
            return []

    @staticmethod
    def _device_ctx(device: Any):
        if device is None:
            return contextlib.nullcontext()
        import sys

        jax = sys.modules.get("jax")
        dd = getattr(jax, "default_device", None) if jax is not None else None
        if dd is None:
            return contextlib.nullcontext()
        return dd(device)

    def run(self, replica: Replica, df):
        """Full transform on the replica's device (dispatch + readback)."""
        with self._device_ctx(replica.device):
            return replica.transform(df)

    def submit(self, replica: Replica, df):
        """Non-blocking dispatch when the transform supports the submit
        protocol: returns a zero-arg resolve() or None (no protocol)."""
        sub = getattr(replica.transform, "submit", None)
        if sub is None:
            return None
        with self._device_ctx(replica.device):
            return sub(df)

    def swap_transform(self, transform: Callable) -> None:
        """Install a new transform on every replica. Each batch reads its
        replica's transform exactly once at dispatch, so a swap changes
        versions only BETWEEN batches (in-flight work completes on the
        closure it captured). The lifecycle plane routes through the
        executor's ``swap_transform`` instead, which takes the dispatch
        lock first."""
        for r in self.replicas:
            r.transform = transform

    def describe(self, wall_s: float) -> List[Dict[str, Any]]:
        out = []
        for r in self.replicas:
            out.append({
                "replica": r.index,
                "device": str(r.device) if r.device is not None else None,
                "batches": r.batches, "rows": r.rows,
                "busy_s": round(r.busy_s, 6),
                "utilization": round(r.busy_s / wall_s, 4)
                if wall_s > 0 else None})
        return out


# ---------------------------------------------------------------------------
# Pipelined executor
# ---------------------------------------------------------------------------


_SENTINEL = object()


class PipelinedExecutor:
    """Drain/compute/readback pipeline over a ServingServer's ingress queue.

    Bounded by ``inflight`` (number of batches past drain and not yet
    fulfilled — the explicit in-flight depth knob): the drain thread
    acquires a slot before journaling/staging a batch, the readback thread
    releases it after fulfillment, and while the drain thread waits for a
    slot it keeps absorbing ingress arrivals into the forming batch
    (continuous batching).
    """

    def __init__(self, server, replica_set: ReplicaSet,
                 controller: Optional[AdaptiveBatchController] = None,
                 inflight: int = 2, timeline_cap: int = 512,
                 supervisor=None, watchdog=None):
        self.server = server
        self.replicas = replica_set
        self.controller = controller
        self.inflight = max(1, int(inflight))
        # supervision layer (serving/supervisor.py): per-replica health
        # scores + quarantine/probe/readmit, and the hung-dispatch watchdog
        # budget policy. Both optional — absent, the executor behaves
        # exactly like the unsupervised build.
        self.supervisor = supervisor
        self.watchdog = watchdog
        self._submit_q: "queue_mod.Queue" = queue_mod.Queue()
        self._ready_q: "queue_mod.Queue" = queue_mod.Queue()
        self._slots = threading.Semaphore(self.inflight)
        # pending slot reductions (set_inflight shrink): consumed at release
        # time instead of blocking the caller on a semaphore acquire
        self._shrink = 0
        self._stop = server._stop
        self._lock = threading.Lock()
        self._seq = 0
        self.epochs = 0
        self._timeline: "deque" = deque(maxlen=timeline_cap)
        self._busy = {"drain": 0.0, "readback": 0.0}
        # in-flight dispatch registry for the watchdog scan: replica index
        # -> [prep, gen, t0, budget_s]; an entry doubles as the completion
        # claim token — whoever removes it under the lock owns the outcome
        self._dispatch: Dict[int, list] = {}
        # pipeline-active wall clock: accumulates only while >= 1 batch is in
        # flight, so overlap_ratio is not diluted by idle-server time
        self._active = 0
        self._active_t0 = 0.0
        self._active_wall = 0.0
        self.threads: List[threading.Thread] = []

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "PipelinedExecutor":
        name = self.server.name
        self.threads = [threading.Thread(target=self._drain_loop, daemon=True,
                                         name=f"{name}-drain")]
        for r in self.replicas.replicas:
            self.threads.append(threading.Thread(
                target=self._compute_loop, args=(r,), daemon=True,
                name=f"{name}-compute-{r.index}"))
        self.threads.append(threading.Thread(
            target=self._readback_loop, daemon=True, name=f"{name}-readback"))
        if self.watchdog is not None:
            self.threads.append(threading.Thread(
                target=self._watchdog_loop, daemon=True,
                name=f"{name}-watchdog"))
        for t in self.threads:
            t.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Join the pipeline: the server has already set ``_stop`` (and, on
        graceful drain, waited for in-flight slots to empty). Sentinels
        flush the stage queues so workers exit after finishing queued work."""
        self.server._wake.set()
        for t in self.threads:
            if t.name.endswith("-drain"):
                t.join(timeout=timeout)
        for _ in self.replicas.replicas:
            self._submit_q.put(_SENTINEL)
        for t in self.threads:
            if "-compute-" in t.name:
                t.join(timeout=timeout)
        self._ready_q.put(_SENTINEL)
        for t in self.threads:
            if t.name.endswith("-readback") or t.name.endswith("-watchdog"):
                t.join(timeout=timeout)

    # -- live knobs ------------------------------------------------------
    def set_inflight(self, n: int) -> None:
        """Re-bound the in-flight depth live (the auto-tuner's knob,
        core/tune.py). Growth releases permits immediately; shrink takes
        effect as in-flight batches complete (their releases are consumed
        instead of returned), so the hot path never blocks on a resize."""
        n = max(1, int(n))
        grow = 0
        with self._lock:
            delta = n - self.inflight
            if delta == 0:
                return
            self.inflight = n
            if delta > 0:
                cancel = min(self._shrink, delta)
                self._shrink -= cancel
                grow = delta - cancel
            else:
                self._shrink += -delta
        for _ in range(grow):
            self._slots.release()

    def _release_slot(self) -> None:
        with self._lock:
            if self._shrink > 0:
                self._shrink -= 1
                return
        self._slots.release()

    def swap_transform(self, transform: Callable) -> None:
        """Atomically install a new served transform (the model-lifecycle
        promotion swap): the flip happens under the dispatch lock — the
        same lock the prep-generation registry (``_dispatch``) is guarded
        by — so it lands between batch registrations, never inside one.
        Batches already dispatched complete (and are claimed by the
        readback loop against their registered generation) on the
        transform they captured; batches registered after the swap run
        the new one. In-flight work never mixes versions."""
        with self._lock:
            self.replicas.swap_transform(transform)

    # -- bookkeeping -----------------------------------------------------
    def _mark(self, stage: str, seq: int, t0: float, t1: float,
              replica: Optional[int] = None) -> None:
        with self._lock:
            self._timeline.append({"stage": stage, "seq": seq,
                                   "t0": t0, "t1": t1, "replica": replica})

    def timeline(self) -> List[Dict[str, Any]]:
        """Recent (stage, seq, t0, t1, replica) events — overlap forensics."""
        with self._lock:
            return list(self._timeline)

    def idle_fraction(self) -> float:
        """Instantaneous idle-capacity estimate: the fraction of replicas
        with no batch in flight right now. The multimodel plane clamps its
        AutoML budget with this — a saturated pipeline vetoes trials even
        when the arrival forecast reads calm."""
        n = max(1, len(self.replicas.replicas))
        with self._lock:
            active = min(self._active, n)
        return max(0.0, 1.0 - active / n)

    def _enter_pipe(self) -> None:
        with self._lock:
            if self._active == 0:
                self._active_t0 = time.perf_counter()
            self._active += 1

    def _exit_pipe(self) -> None:
        with self._lock:
            self._active -= 1
            if self._active == 0:
                self._active_wall += time.perf_counter() - self._active_t0

    # -- stage 1: drain / coalesce / journal -----------------------------
    def _gather(self, first) -> Optional[list]:
        """Continuous batching: coalesce a batch AND acquire an in-flight
        slot, with the two waits merged. While every slot is busy,
        coalescing is free — the batch keeps absorbing arrivals with no
        dispatch to delay (this is where convoys merge under load). Once a
        slot is held, only the adaptive window keeps the batch open, so a
        free device never idles behind a coalescing sleep (the static
        ``max_wait_ms`` tax the sync loop pays every cycle). Returns the
        batch with the slot HELD, or None on stop (slot released)."""
        srv = self.server
        batch = [first]
        window = self.controller.window_ms() \
            if self.controller is not None else srv.max_wait_ms
        deadline = time.perf_counter() + window / 1000.0
        acquired = self._slots.acquire(blocking=False)
        while len(batch) < srv.max_batch_size:
            if self._stop.is_set():
                break
            now = time.perf_counter()
            if acquired:
                remaining = deadline - now
                if remaining <= 0:
                    break
                try:
                    batch.append(srv._queue.get(timeout=remaining))
                except queue_mod.Empty:
                    break
            else:
                while len(batch) < srv.max_batch_size:
                    try:
                        batch.append(srv._queue.get_nowait())
                    except queue_mod.Empty:
                        break
                acquired = self._slots.acquire(timeout=0.002)
        while not acquired:  # batch full (or stopping): still need the slot
            if self._stop.is_set():
                break
            acquired = self._slots.acquire(timeout=0.002)
        if self._stop.is_set() and not acquired:
            for item in batch:  # hard stop: requeue, do not strand
                srv._queue.put(item)
            return None
        return batch

    def _drain_loop(self) -> None:
        srv = self.server
        while not self._stop.is_set():
            first = srv._next_request()
            if first is None:
                continue
            t_c0 = time.perf_counter()
            batch = self._gather(first)
            if batch is None:
                return
            self._enter_pipe()
            t_w0 = time.time()
            t_p0 = time.perf_counter()
            prep = srv._prepare_batch(batch)
            t_p1 = time.perf_counter()
            if prep is None:  # every request expired while queued
                self._release_slot()
                self._exit_pipe()
                continue
            with self._lock:
                self._seq += 1
                prep.seq = self._seq
                self._busy["drain"] += t_p1 - t_p0
            self._mark("drain", prep.seq, t_c0, t_p1)
            srv._trace_batch("drain", prep, t_w0, t_p1 - t_p0)
            self._submit_q.put(prep)

    # -- stage 2: compute (one worker per replica) -----------------------
    def _compute_loop(self, replica: Replica) -> None:
        srv = self.server
        sup = self.supervisor
        while True:
            if sup is not None and not sup.admitted(replica.index):
                # quarantined: no submit-queue pulls until a probe succeeds
                if self._stop.is_set():
                    return
                if sup.probe_due(replica.index):
                    sup.begin_probe(replica.index)
                    sup.note_probe(replica.index,
                                   sup.run_probe(replica))
                else:
                    time.sleep(0.005)
                continue
            prep = self._submit_q.get()
            if prep is _SENTINEL:
                return
            # in-flight deadline gate: a request whose deadline expired while
            # the batch sat staged gets its 504 NOW, pre-dispatch
            prep = srv._regate_inflight(prep)
            if prep is None:
                self._release_slot()
                self._exit_pipe()
                continue
            t_w0 = time.time()
            t0 = time.perf_counter()
            budget = None
            if self.watchdog is not None:
                # a tuned K-step mega-dispatch runs up to K micro-batches in
                # one Python-level call; scale the budget so it isn't read
                # as a hang (serve_pipeline attaches the hint)
                hint = getattr(replica.transform, "mega_k", None)
                try:
                    batches = int(hint() if callable(hint) else hint or 1)
                except Exception:  # noqa: BLE001 — hint must not kill loop
                    batches = 1
                budget = self.watchdog.budget_s(prep.n, batches=batches)
            with self._lock:
                gen = prep.wd_gen
                self._dispatch[replica.index] = [prep, gen, t0, budget]
            pending = out = err = None
            try:
                # chaos seams: a delay plan on WORKER_DISPATCH_HANG wedges
                # this dispatch (the watchdog's prey); a raising plan on
                # WORKER_CRASH simulates the replica dying mid-dispatch
                faults.fire(faults.WORKER_DISPATCH_HANG,
                            replica=replica.index, seq=prep.seq)
                faults.fire(faults.WORKER_CRASH,
                            replica=replica.index, seq=prep.seq)
                # batch_context: traced requests visible to the H2D staging
                # and fused-segment layers under this dispatch
                with obs_trace.batch_context(srv.tracer,
                                             list(prep.ctxs.values())):
                    pending = self.replicas.submit(replica, prep.df)
                    if pending is None:
                        out = self.replicas.run(replica, prep.df)
            except Exception as e:  # noqa: BLE001 — batch fails, not server
                err = e
            t1 = time.perf_counter()
            with self._lock:
                # completion claim: if the watchdog already expired this
                # dispatch (gen bumped, registry entry gone), the result is
                # STALE — the re-dispatched copy owns the slot and replies
                live = prep.wd_gen == gen and \
                    self._dispatch.pop(replica.index, [None, -1])[1] == gen
                replica.busy_s += t1 - t0
                if live:
                    replica.batches += 1
                    replica.rows += prep.n
            if sup is not None:
                if err is not None:
                    sup.note_failure(replica.index)
                else:
                    sup.note_success(replica.index, t1 - t0)
            if not live:
                # late return of a wedged dispatch: discard the result; the
                # supervisor's probe path decides re-admission from here
                self._mark("stale", prep.seq, t0, t1, replica.index)
                continue
            if err is None and self.watchdog is not None:
                self.watchdog.observe(t1 - t0)
            self._mark("compute", prep.seq, t0, t1, replica.index)
            srv._trace_batch("dispatch", prep, t_w0, t1 - t0,
                             replica=replica.index)
            self._ready_q.put((prep, pending, out, err, t1 - t0))

    # -- hung-dispatch watchdog ------------------------------------------
    def _watchdog_loop(self) -> None:
        wd = self.watchdog
        while not self._stop.wait(wd.poll_s):
            self._watchdog_scan()

    def _watchdog_scan(self, now: Optional[float] = None) -> None:
        """One watchdog pass over the in-flight dispatch registry. A
        dispatch past its wall budget is WEDGED: claim it (bump the prep's
        generation so the stuck thread's eventual return is discarded),
        quarantine the replica, and either re-dispatch the batch on a
        healthy peer or — when none exists — double the budget in place a
        few times before abandoning with an accounted 504. Exposed with a
        ``now`` override so chaos tests can drive scans deterministically."""
        wd = self.watchdog
        if now is None:
            now = time.perf_counter()
        requeue, extend, abandon = [], [], []
        with self._lock:
            for idx, entry in list(self._dispatch.items()):
                prep, gen, t0, budget = entry
                if budget is None or now - t0 <= budget:
                    continue
                if prep.wd_gen != gen:
                    continue
                peers = len(self.replicas.replicas) - 1 \
                    if self.supervisor is None \
                    else self.supervisor.healthy_peers(idx)
                if peers > 0 and prep.wd_tries < wd.max_redispatch:
                    prep.wd_gen += 1
                    prep.wd_tries += 1
                    del self._dispatch[idx]
                    requeue.append((idx, prep))
                elif prep.wd_expiries + 1 < wd.abandon_after:
                    # no healthy peer: keep waiting with a doubled budget —
                    # a long first-compile must not become a false 504
                    prep.wd_expiries += 1
                    entry[3] = budget * 2.0
                    entry[2] = now
                    extend.append(idx)
                else:
                    prep.wd_gen += 1
                    del self._dispatch[idx]
                    abandon.append((idx, prep))
        for idx, prep in requeue:
            # supervisor/journal work OUTSIDE the executor lock
            if self.supervisor is not None:
                self.supervisor.note_wedged(idx)
            wd.note_trip("requeue")
            _LOG.warning("dispatch on replica %d wedged (seq %d): "
                         "re-dispatching on a healthy replica", idx, prep.seq)
            self._submit_q.put(prep)
        for idx in extend:
            wd.note_trip("extend")
        for idx, prep in abandon:
            if self.supervisor is not None:
                self.supervisor.note_wedged(idx)
            wd.note_trip("abandon")
            _LOG.warning("dispatch on replica %d wedged (seq %d) with no "
                         "healthy peer: abandoning batch with 504s",
                         idx, prep.seq)
            self._abandon(prep)

    def _abandon(self, prep) -> None:
        """Answer every request of a wedged batch 504 with an accounted
        reason, release its slot, and sweep the journal — the batch's epoch
        commits once the abandoned slots are popped (at-least-once: a crash
        before this point replays the batch, which is the contract)."""
        srv = self.server
        for rid in prep.ids:
            srv.stats.record_shed(504, "watchdog_abandoned")
            srv._fulfill(int(rid), 504,
                         b'{"error": "dispatch watchdog expired"}',
                         content_type="application/json")
        self._release_slot()
        self._exit_pipe()
        srv._maybe_commit_epochs()

    # -- stage 3: readback / fulfill -------------------------------------
    def _readback_loop(self) -> None:
        srv = self.server
        while True:
            item = self._ready_q.get()
            if item is _SENTINEL:
                return
            prep, pending, out, err, compute_s = item
            t_w0 = time.time()
            t0 = time.perf_counter()
            if err is not None:
                srv._fail_batch(prep.ids, err)
            else:
                try:
                    if pending is not None:
                        out = pending()
                    srv._apply_output(prep.ids, out)
                except Exception as e:  # noqa: BLE001
                    srv._fail_batch(prep.ids, e)
            t1 = time.perf_counter()
            with self._lock:
                self._busy["readback"] += t1 - t0
                self.epochs += 1
            self._mark("readback", prep.seq, t0, t1)
            srv._trace_batch("readback", prep, t_w0, t1 - t0)
            self._release_slot()
            self._exit_pipe()
            if self.controller is not None:
                self.controller.observe(compute_s + (t1 - t0), prep.queue_s,
                                        prep.n, srv._queue.qsize())
            srv._maybe_commit_epochs()
            srv._tuner_tick(prep.queue_s + compute_s + (t1 - t0))

    # -- stats surface (/_mmlspark/stats "async" section) ----------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            wall = self._active_wall
            if self._active > 0:
                wall += time.perf_counter() - self._active_t0
            drain_s = self._busy["drain"]
            readback_s = self._busy["readback"]
            epochs = self.epochs
            active = self._active
        compute_s = sum(r.busy_s for r in self.replicas.replicas)
        serial = drain_s + compute_s + readback_s
        supervisor = None
        if self.supervisor is not None:
            supervisor = self.supervisor.summary()
        watchdog = None
        if self.watchdog is not None:
            watchdog = self.watchdog.summary()
        return {
            "mode": "pipelined",
            "inflight": self.inflight,
            # supervision layer (serving/supervisor.py): per-replica health
            # states + watchdog trip counters; None when supervision is off
            "supervisor": supervisor,
            "watchdog": watchdog,
            "placement_failures": self.replicas.placement_failures or None,
            # batches currently past drain and not yet fulfilled: the live
            # slot occupancy (== inflight means the pipeline is saturated
            # — the perf-attribution companion to the ring gauges)
            "inflight_active": active,
            "epochs": epochs,
            "replicas": self.replicas.describe(wall),
            "controller": self.controller.state()
            if self.controller is not None else None,
            "busy_s": {"drain": round(drain_s, 6),
                       "compute": round(compute_s, 6),
                       "readback": round(readback_s, 6)},
            "active_wall_s": round(wall, 6),
            # > 1.0 means stages genuinely overlapped (stage-busy seconds
            # exceed the wall time the pipeline was occupied); 1.0 = serial
            "overlap_ratio": round(serial / wall, 4) if wall > 0 else None,
        }
