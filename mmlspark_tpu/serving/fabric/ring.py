"""Journaled consistent-hash ring with virtual nodes and bounded movement.

The L1 front assigns tenants to L2 cells by hashing the tenant key onto
this ring. Consistent hashing gives the bounded-movement rebalance the
fabric contract requires: adding or removing a cell re-assigns only the
keys inside that cell's own hash share — every other tenant stays pinned
to its incumbent cell, so per-tenant admission quotas, hedge reservoirs
and SLO-burn buckets survive a resize untouched.

Ring membership is a knob like any other in this codebase: every epoch
transition (add / remove / drain / restore) is journaled with the full
post-state and supports one-step ``rollback()``. The ``ring.rebalance``
fault point fires BEFORE anything mutates, so an injected crash leaves
the journaled previous epoch serving. An optional durable journal file
(JSONL, fsynced per entry, torn-tail tolerant on replay) lets a restarted
L1 come back on the epoch it last served.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import logging
import os
import threading
from typing import Dict, Iterable, List, Optional, Tuple

from ...core import faults

logger = logging.getLogger(__name__)

#: cell states: ``up`` takes new assignments; ``draining`` serves what it
#: has but is skipped by ``cell_for`` / ``order_for`` (maintenance handoff)
UP = "up"
DRAINING = "draining"

_JOURNAL_CAP = 256


class RingEpochError(RuntimeError):
    """An invalid epoch transition (unknown cell, duplicate add, ...)."""


def _hash64(s: str) -> int:
    """Stable 64-bit ring position (sha256 prefix — no PYTHONHASHSEED)."""
    return int.from_bytes(hashlib.sha256(s.encode("utf-8")).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring over named cells with ``vnodes`` virtual nodes
    per cell. Thread-safe; all epoch transitions are journaled (in-memory
    ring buffer plus the optional durable ``journal_path``) and one-step
    reversible via :meth:`rollback`."""

    def __init__(self, vnodes: int = 64,
                 journal_path: Optional[str] = None,
                 journal_cap: int = _JOURNAL_CAP):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = int(vnodes)
        self.epoch = 0
        self.rebalances = 0
        self.rollbacks = 0
        self.rebalance_failures = 0   # ring.rebalance crashes absorbed
        self.journal_errors = 0       # durable-append failures (accounted)
        self._cells: Dict[str, str] = {}          # name -> UP | DRAINING
        self._points: List[Tuple[int, str]] = []  # sorted (hash, cell)
        self._keys: List[int] = []                # hash column for bisect
        self._prev: Optional[Tuple[int, Dict[str, str]]] = None
        self._journal: List[Dict[str, object]] = []
        self._journal_cap = int(journal_cap)
        self._journal_degraded = False
        self._lock = threading.Lock()
        self._fh = None
        self._path = journal_path
        if journal_path:
            self._replay(journal_path)
            self._fh = open(journal_path, "a", encoding="utf-8")

    # -- hashing ----------------------------------------------------------

    def _rebuild(self) -> None:
        pts = []
        for cell in self._cells:
            for i in range(self.vnodes):
                pts.append((_hash64("%s#%d" % (cell, i)), cell))
        pts.sort()
        self._points = pts
        self._keys = [h for h, _ in pts]

    def cell_for(self, key: str,
                 exclude: Iterable[str] = ()) -> Optional[str]:
        """The cell owning ``key``: first assignable cell clockwise from
        the key's ring position (draining and ``exclude``-ed cells are
        skipped — their arcs re-hash onto the survivors)."""
        order = self.order_for(key, exclude=exclude)
        return order[0] if order else None

    def order_for(self, key: str,
                  exclude: Iterable[str] = ()) -> List[str]:
        """All assignable cells in ring-walk order from ``key``'s position:
        the affinity cell first, then the survivors a dead affinity cell's
        arc would re-hash onto, in order."""
        skip = set(exclude)
        with self._lock:
            if not self._points:
                return []
            live = {c for c, st in self._cells.items()
                    if st == UP and c not in skip}
            if not live:
                return []
            i = bisect.bisect_right(self._keys, _hash64(key))
            n = len(self._points)
            order: List[str] = []
            for step in range(n):
                cell = self._points[(i + step) % n][1]
                if cell in live and cell not in order:
                    order.append(cell)
                    if len(order) == len(live):
                        break
            return order

    def share(self, cell: str) -> float:
        """``cell``'s fraction of the hash space (its rebalance bound)."""
        with self._lock:
            if not self._points or cell not in self._cells:
                return 0.0
            span = 0
            full = 1 << 64
            for j, (h, c) in enumerate(self._points):
                if c != cell:
                    continue
                prev = self._points[j - 1][0] if j else self._points[-1][0] - full
                span += h - prev
            return span / full

    # -- epoch transitions ------------------------------------------------

    def _transition(self, action: str, cell: str, new_state: Optional[str],
                    *, expect: Optional[Tuple[str, ...]] = None) -> None:
        with self._lock:
            have = self._cells.get(cell)
            if expect is not None and have not in expect:
                raise RingEpochError(
                    "%s %r: state is %r" % (action, cell, have))
            # the crash seam: an armed plan raising here must leave the
            # journaled previous epoch serving — nothing has mutated yet
            faults.fire(faults.RING_REBALANCE, action=action, cell=cell,
                        epoch=self.epoch)
            self._prev = (self.epoch, dict(self._cells))
            if new_state is None:
                self._cells.pop(cell, None)
            else:
                self._cells[cell] = new_state
            self.epoch += 1
            self.rebalances += 1
            self._rebuild()
            self._log(action, cell)

    def add_cell(self, cell: str) -> None:
        self._transition("add", cell, UP, expect=(None,))

    def remove_cell(self, cell: str) -> None:
        self._transition("remove", cell, None, expect=(UP, DRAINING))

    def drain_cell(self, cell: str) -> None:
        """Stop new assignments to ``cell`` (its arc re-hashes onto the
        survivors); the cell itself keeps serving what is in flight."""
        self._transition("drain", cell, DRAINING, expect=(UP,))

    def restore_cell(self, cell: str) -> None:
        self._transition("restore", cell, UP, expect=(DRAINING,))

    def rollback(self, reason: str = "rollback") -> bool:
        """One-step rollback to the previous journaled epoch (same contract
        as every other knob: a rollback is itself a journaled epoch)."""
        with self._lock:
            if self._prev is None:
                return False
            _, members = self._prev
            faults.fire(faults.RING_REBALANCE, action="rollback", cell=None,
                        epoch=self.epoch)
            self._prev = None
            self._cells = dict(members)
            self.epoch += 1
            self.rollbacks += 1
            self._rebuild()
            self._log(reason, None)
            return True

    # -- journal ----------------------------------------------------------

    def _log(self, action: str, cell: Optional[str]) -> None:
        entry = {"epoch": self.epoch, "action": action, "cell": cell,
                 "members": dict(self._cells)}
        self._journal.append(entry)
        if len(self._journal) > self._journal_cap:
            # analysis: allow C001 -- _log's callers (_transition/rollback) hold self._lock
            self._journal = self._journal[-self._journal_cap:]
        if self._fh is None or self._journal_degraded:
            return
        try:
            self._fh.write(json.dumps(entry, sort_keys=True) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
        except OSError as e:
            # a full/unwritable journal volume must not take the ring down:
            # accounted degrade, in-memory journal keeps the epoch history
            self.journal_errors += 1
            self._journal_degraded = True
            logger.warning("ring journal degraded (%s); epochs stay "
                           "in-memory only", e)

    def _replay(self, path: str) -> None:
        """Adopt the last intact journaled epoch (torn tails skipped)."""
        if not os.path.exists(path):
            return
        last = None
        try:
            with open(path, "r", encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail from a crashed writer
                    if isinstance(rec, dict) and "members" in rec:
                        last = rec
                        self._journal.append(rec)
        except OSError:
            return
        # pre-publication (__init__-only), but locked anyway: the C001
        # lock contract is per-field, not per-phase
        with self._lock:
            if last is not None:
                self._cells = {str(k): str(v)
                               for k, v in dict(last["members"]).items()}
                self.epoch = int(last.get("epoch", 0))
                self._rebuild()
            if len(self._journal) > self._journal_cap:
                self._journal = self._journal[-self._journal_cap:]

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            finally:
                self._fh = None

    # -- introspection ----------------------------------------------------

    def members(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._cells)

    def journal(self) -> List[Dict[str, object]]:
        with self._lock:
            return list(self._journal)

    def summary(self) -> Dict[str, object]:
        with self._lock:
            return {
                "epoch": self.epoch,
                "vnodes": self.vnodes,
                "cells": dict(self._cells),
                "rebalances": self.rebalances,
                "rollbacks": self.rollbacks,
                "rebalance_failures": self.rebalance_failures,
                "journal_errors": self.journal_errors,
                "journal": list(self._journal[-16:]),
            }
