"""Federated front fabric: two-level fronts with consistent-hash affinity.

The reference exposes one Spark Serving front per streaming query — a
single point of failure that also owns all per-tenant admission, hedge
and SLO-burn state. The fabric splits that into an L1 front that hashes
``X-MMLSpark-Tenant`` onto L2 cells (ordinary RoutingFronts) over a
journaled consistent-hash ring, so per-tenant state stays pinned to one
cell across resizes and a cell death is a bounded re-hash, not a reset.

  - ``ring.HashRing``    — journaled consistent-hash ring (virtual nodes,
    bounded-movement rebalance, epochs with one-step rollback).
  - ``front.FrontFabric`` — the L1 routing policy plugged into
    RoutingFront via its ``fabric=`` knob (default off: the single-front
    path is byte-identical).

See docs/front_fabric.md for the fabric contract.
"""

from .ring import HashRing, RingEpochError
from .front import FrontFabric, make_fabric

__all__ = ["HashRing", "RingEpochError", "FrontFabric", "make_fabric"]
