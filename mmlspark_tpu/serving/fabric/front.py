"""L1 routing policy: consistent-hash tenant affinity onto L2 cells.

``FrontFabric`` is what a RoutingFront consults instead of its
capacity-weighted round-robin when constructed with ``fabric=``. Each
registered "worker" of an L1 front is an L2 front (a cell); the fabric
maps every request's affinity key (``X-MMLSpark-Tenant``, falling back
to the session/trace id) onto the ring and returns the cells in ring-walk
order — affinity cell first, then the survivors its arc would re-hash
onto. Everything else (circuit breakers, health probes, opaque body
forwarding with deadline/trace headers, hedging, the retry walk) is the
front's existing machinery, unchanged.

Planned maintenance uses :meth:`drain_cell`: the cell stops receiving new
assignments (a journaled ring epoch), in-flight forwards flush, and the
handoff is journaled. A crash of the ``ring.rebalance`` seam during any
membership change is absorbed and accounted — the previous epoch serves.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Mapping, Optional

from ...core import faults
from ..tenants import (TenantAdmission, DEFAULT_TENANT, MODEL_HEADER,
                       header_lookup)
from .ring import HashRing, RingEpochError

#: fallback affinity headers when no tenant header is present (session or
#: trace id — keeps an anonymous session pinned to one cell)
SESSION_HEADERS = ("x-mmlspark-session", "x-mmlspark-trace")


def affinity_key_of(headers: Optional[Mapping[str, str]]) -> str:
    """The ring key for a request: tenant header first, then the model
    header, then session/trace id, then the default tenant (all anonymous
    default-model traffic shares one cell). The model rung keeps every
    request for one model landing on the same cell, so that cell's mall
    keeps the model resident instead of N cells each paying a re-warm."""
    tenant = TenantAdmission.tenant_of(headers)
    if tenant != DEFAULT_TENANT:
        return tenant
    model = header_lookup(headers, MODEL_HEADER) if headers else None
    if model:
        return f"model:{model}"
    if headers:
        lowered = {str(k).lower(): v for k, v in headers.items()}
        for h in SESSION_HEADERS:
            v = lowered.get(h)
            if v:
                return str(v)
    return DEFAULT_TENANT


class FrontFabric:
    """The L1 side of the fabric: a journaled ring plus per-cell in-flight
    accounting (what :meth:`drain_cell` waits on) and re-hash counters."""

    def __init__(self, vnodes: int = 64,
                 journal_path: Optional[str] = None,
                 drain_timeout_s: float = 30.0):
        self.ring = HashRing(vnodes=vnodes, journal_path=journal_path)
        self.drain_timeout_s = float(drain_timeout_s)
        self.assignments = 0   # requests routed with an affinity cell
        self.rehashes = 0      # requests that landed off their affinity cell
        self.drains = 0        # completed drain handoffs
        self._inflight: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- membership (driven by the front's register/deregister) -----------

    def note_register(self, cell: str) -> bool:
        """Add a cell on registration. A ``ring.rebalance`` crash is
        absorbed: the previous epoch keeps serving, accounted."""
        try:
            self.ring.add_cell(cell)
            return True
        except RingEpochError:
            return False  # duplicate registration refresh — not an epoch
        except Exception:
            self.ring.rebalance_failures += 1
            return False

    def note_deregister(self, cell: str) -> bool:
        try:
            self.ring.remove_cell(cell)
            return True
        except RingEpochError:
            return False
        except Exception:
            self.ring.rebalance_failures += 1
            return False

    # -- routing -----------------------------------------------------------

    def order_for(self, headers: Optional[Mapping[str, str]],
                  routable: List[str]) -> List[str]:
        """Cells to try, in order: the affinity cell first, then the ring-walk
        survivors — filtered to ``routable`` (circuit-breaker OPEN cells are
        the front's concern and arrive already excluded)."""
        key = affinity_key_of(headers)
        walk = self.ring.order_for(key)
        allowed = set(routable)
        order = [c for c in walk if c in allowed]
        with self._lock:
            if order:
                self.assignments += 1
                if walk and order[0] != walk[0]:
                    self.rehashes += 1  # affinity cell dead/drained/open
        return order

    # -- in-flight accounting (the drain barrier) --------------------------

    def begin(self, cell: str) -> None:
        with self._lock:
            self._inflight[cell] = self._inflight.get(cell, 0) + 1

    def end(self, cell: str) -> None:
        with self._lock:
            n = self._inflight.get(cell, 0) - 1
            if n <= 0:
                self._inflight.pop(cell, None)
            else:
                self._inflight[cell] = n

    def inflight(self, cell: str) -> int:
        with self._lock:
            return self._inflight.get(cell, 0)

    # -- planned maintenance ------------------------------------------------

    def drain_cell(self, cell: str,
                   timeout_s: Optional[float] = None) -> Dict[str, object]:
        """Drain-and-shift: journal a ``drain`` epoch (new assignments stop,
        the cell's arc re-hashes onto survivors), wait for the L1's in-flight
        forwards to that cell to flush, then journal the handoff."""
        timeout = self.drain_timeout_s if timeout_s is None else float(timeout_s)
        try:
            self.ring.drain_cell(cell)
        except RingEpochError as e:
            return {"cell": cell, "ok": False, "error": str(e)}
        except Exception:
            self.ring.rebalance_failures += 1
            return {"cell": cell, "ok": False, "error": "rebalance_crash"}
        deadline = time.monotonic() + timeout
        flushed = True
        while self.inflight(cell) > 0:
            if time.monotonic() >= deadline:
                flushed = False
                break
            time.sleep(0.01)
        # the handoff epoch: the drained cell leaves the ring entirely —
        # journaled, so the shift survives an L1 restart
        try:
            self.ring.remove_cell(cell)
        except Exception:
            self.ring.rebalance_failures += 1
        with self._lock:
            self.drains += 1
            residual = self._inflight.get(cell, 0)
        return {"cell": cell, "ok": True, "flushed": flushed,
                "residual_inflight": residual, "epoch": self.ring.epoch}

    # -- introspection ------------------------------------------------------

    def summary(self) -> Dict[str, object]:
        with self._lock:
            inflight = dict(self._inflight)
            out = {
                "assignments": self.assignments,
                "rehashes": self.rehashes,
                "drains": self.drains,
                "inflight": inflight,
            }
        out["ring"] = self.ring.summary()
        return out

    def close(self) -> None:
        self.ring.close()


def make_fabric(fabric) -> Optional[FrontFabric]:
    """Coerce a RoutingFront's ``fabric=`` argument: ``None``/``False`` off,
    ``True`` defaults, a dict as kwargs, or a ready ``FrontFabric``."""
    if fabric is None or fabric is False:
        return None
    if fabric is True:
        return FrontFabric()
    if isinstance(fabric, FrontFabric):
        return fabric
    if isinstance(fabric, Mapping):
        return FrontFabric(**dict(fabric))
    raise TypeError("fabric must be None/bool/dict/FrontFabric, got %r"
                    % (fabric,))
