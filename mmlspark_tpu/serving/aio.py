"""Async HTTP/1.1 transport: event-loop ingress + pooled keep-alive client.

The thread-per-connection ``ThreadingHTTPServer`` ingress spends a thread
(and its stack) per open socket and a fresh TCP handshake per non-keep-alive
client — under the 16-client load test that connection churn already rivals
compute (BENCH_serving.json queue p95 vs compute p95). This module is the
high-concurrency replacement both ``ServingServer`` and ``RoutingFront``
mount behind their ``http_mode="async"`` knob:

  - ``AsyncHTTPServer``: one event loop on one dedicated thread handles every
    connection. Keep-alive is the default (HTTP/1.1), and reads are
    PIPELINED: a connection's parser keeps reading requests while earlier
    ones await their batch, with responses written strictly in order
    (bounded by ``pipeline_depth`` so a flooding client cannot queue
    unbounded work). Handlers are coroutines; the serving bridge awaits the
    reply-slot future the batch loop fulfills, so thousands of idle
    keep-alive connections cost file descriptors, not threads.
  - ``AsyncConnectionPool``: the client side for the routing front's
    forwards — per-worker keep-alive connection reuse instead of a fresh
    ``urlopen`` socket per hop, with a single stale-connection retry (a
    pooled socket the worker closed while idle).

The parser is deliberately minimal: Content-Length bodies only (chunked
uploads get 411 — no serving client streams chunks), header block bounded by
the stream reader's line limit, body bounded by ``max_body``.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque
from typing import Awaitable, Callable, Dict, Optional, Tuple
from urllib.parse import urlsplit

__all__ = ["AsyncConnectionPool", "AsyncHTTPServer", "Headers",
           "HTTPRequest", "HTTPResponse"]

#: readline() bound — caps request-line and each header line (and therefore
#: the whole header block, via _MAX_HEADERS lines)
_LINE_LIMIT = 16384
_MAX_HEADERS = 100
_REASONS = {200: "OK", 204: "No Content", 400: "Bad Request",
            403: "Forbidden", 404: "Not Found", 408: "Request Timeout",
            411: "Length Required", 413: "Payload Too Large",
            500: "Internal Server Error", 502: "Bad Gateway",
            503: "Service Unavailable", 504: "Gateway Timeout"}


class Headers(dict):
    """Plain dict of header name -> value (received casing preserved, so
    journaled rows match the threaded transport byte-for-byte) with a
    case-insensitive ``get`` — the lookup convention every consumer
    (``deadline_from_headers``, ``context_from_headers``) already uses."""

    def get(self, key, default=None):  # type: ignore[override]
        v = dict.get(self, key)
        if v is not None:
            return v
        lk = str(key).lower()
        for k, kv in self.items():
            if str(k).lower() == lk:
                return kv
        return default


class HTTPRequest:
    __slots__ = ("method", "path", "headers", "body", "version")

    def __init__(self, method: str, path: str, headers: Headers,
                 body: bytes, version: str = "HTTP/1.1"):
        self.method = method
        self.path = path
        self.headers = headers
        self.body = body
        self.version = version


class HTTPResponse:
    __slots__ = ("status", "body", "content_type", "extra")

    def __init__(self, status: int, body: bytes = b"",
                 content_type: str = "application/json",
                 extra: Optional[Dict[str, str]] = None):
        self.status = int(status)
        self.body = bytes(body)
        self.content_type = content_type
        self.extra = extra

    def render(self, keep_alive: bool) -> bytes:
        reason = _REASONS.get(self.status, "Unknown")
        lines = [f"HTTP/1.1 {self.status} {reason}",
                 f"Content-Type: {self.content_type}",
                 f"Content-Length: {len(self.body)}"]
        for k, v in (self.extra or {}).items():
            lines.append(f"{k}: {v}")
        lines.append("Connection: %s" %
                     ("keep-alive" if keep_alive else "close"))
        return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + self.body


class AsyncHTTPServer:
    """Keep-alive, pipelined HTTP/1.1 server on a dedicated event loop.

    ``handler``: ``async (HTTPRequest) -> HTTPResponse``. Runs on the loop
    thread — it must never block (the serving bridge awaits reply-slot
    events instead). Lifecycle mirrors the threaded transport: ``start()``
    binds (resolving port 0), ``stop()`` closes every connection and joins
    the loop thread. ``stats()`` exposes connection/request counters — the
    load test's proof that 64 concurrent keep-alive clients ride one thread.
    """

    def __init__(self, host: str, port: int,
                 handler: Callable[[HTTPRequest], Awaitable[HTTPResponse]],
                 name: str = "aio-http", max_body: int = 1 << 31,
                 idle_timeout_s: float = 75.0, body_timeout_s: float = 60.0,
                 pipeline_depth: int = 8):
        self.host = host
        self.port = port
        self.handler = handler
        self.name = name
        self.max_body = int(max_body)
        self.idle_timeout_s = float(idle_timeout_s)
        self.body_timeout_s = float(body_timeout_s)
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._started = threading.Event()
        self._start_err: Optional[BaseException] = None
        self._stopping = False
        # counters mutated on the loop thread only; read anywhere (ints)
        self.connections_total = 0
        self.open_connections = 0
        self.peak_open_connections = 0
        self.requests_total = 0

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "AsyncHTTPServer":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=self.name)
        self._thread.start()
        self._started.wait(timeout=10)
        if self._start_err is not None:
            self._thread.join(timeout=5)
            raise self._start_err
        if not self._started.is_set():
            raise RuntimeError(f"{self.name}: event loop failed to start")
        return self

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self.loop = loop
        try:
            try:
                self._server = loop.run_until_complete(asyncio.start_server(
                    self._serve_conn, self.host, self.port,
                    limit=_LINE_LIMIT))
                self.port = self._server.sockets[0].getsockname()[1]
            except BaseException as e:  # bind failure -> surface in start()
                self._start_err = e
                return
            finally:
                self._started.set()
            loop.run_forever()
            # stop() requested: close the listener, cancel live connections
            self._server.close()
            loop.run_until_complete(self._server.wait_closed())
            tasks = [t for t in asyncio.all_tasks(loop) if not t.done()]
            for t in tasks:
                t.cancel()
            if tasks:
                loop.run_until_complete(
                    asyncio.gather(*tasks, return_exceptions=True))
        finally:
            try:
                loop.run_until_complete(loop.shutdown_asyncgens())
            except Exception:  # noqa: BLE001 — closing anyway
                pass
            loop.close()

    def stop(self) -> None:
        self._stopping = True
        if self.loop is not None and self.loop.is_running():
            self.loop.call_soon_threadsafe(self.loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10)

    def stats(self) -> Dict[str, int]:
        return {"connections_total": self.connections_total,
                "open_connections": self.open_connections,
                "peak_open_connections": self.peak_open_connections,
                "requests_total": self.requests_total}

    # -- connection handling ---------------------------------------------
    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        self.connections_total += 1
        self.open_connections += 1
        self.peak_open_connections = max(self.peak_open_connections,
                                         self.open_connections)
        # responses must leave in request order (HTTP/1.1 pipelining): the
        # read side parses ahead and queues handler tasks; the write side
        # drains them in order. maxsize bounds a flooding client.
        resp_q: "asyncio.Queue" = asyncio.Queue(maxsize=self.pipeline_depth)
        w_task = asyncio.ensure_future(self._write_loop(writer, resp_q))
        try:
            while True:
                try:
                    req, keep = await self._read_request(reader)
                except _ParseError as e:
                    await resp_q.put((_done(HTTPResponse(
                        e.status, b'{"error": "%s"}' %
                        e.msg.encode("latin-1", "replace"))), False))
                    break
                except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                        ConnectionError):
                    break
                if req is None:
                    break
                task = asyncio.ensure_future(self._dispatch(req))
                await resp_q.put((task, keep))
                if not keep:
                    break
        except asyncio.CancelledError:
            pass
        finally:
            await resp_q.put(None)
            try:
                await w_task
            except asyncio.CancelledError:
                pass
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001 — peer already gone
                pass
            self.open_connections -= 1

    async def _dispatch(self, req: HTTPRequest) -> HTTPResponse:
        try:
            resp = await self.handler(req)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — a request fails, not the loop
            resp = HTTPResponse(500, b'{"error": "%s"}' %
                                str(e).encode("latin-1", "replace"))
        self.requests_total += 1
        return resp

    async def _write_loop(self, writer: asyncio.StreamWriter,
                          resp_q: "asyncio.Queue") -> None:
        # runs until the reader enqueues None: even after the peer vanishes
        # or a close-response, keep DRAINING the queue (discarding) so a
        # reader blocked on a full pipeline queue can never deadlock
        alive = True
        while True:
            item = await resp_q.get()
            if item is None:
                return
            task, keep = item
            try:
                resp = await task
            except asyncio.CancelledError:
                return  # server shutdown
            if not alive:
                continue
            try:
                writer.write(resp.render(keep))
                await writer.drain()
            except (ConnectionError, RuntimeError):
                alive = False
                continue
            if not keep:
                alive = False

    async def _read_request(self, reader: asyncio.StreamReader
                            ) -> Tuple[Optional[HTTPRequest], bool]:
        try:
            line = await asyncio.wait_for(reader.readline(),
                                          self.idle_timeout_s)
        except ValueError as e:  # line over the reader limit
            raise _ParseError(400, "request line too long") from e
        if not line:
            return None, False  # clean EOF between requests
        parts = line.decode("latin-1").strip().split()
        if len(parts) != 3 or not parts[2].startswith("HTTP/"):
            raise _ParseError(400, "malformed request line")
        method, target, version = parts
        headers = Headers()
        for _ in range(_MAX_HEADERS):
            try:
                hline = await asyncio.wait_for(reader.readline(),
                                               self.body_timeout_s)
            except ValueError as e:
                raise _ParseError(400, "header line too long") from e
            if hline in (b"\r\n", b"\n", b""):
                break
            k, sep, v = hline.decode("latin-1").partition(":")
            if not sep:
                raise _ParseError(400, "malformed header")
            headers[k.strip()] = v.strip()
        else:
            raise _ParseError(400, "too many headers")
        if "chunked" in str(headers.get("Transfer-Encoding", "")).lower():
            raise _ParseError(411, "chunked bodies unsupported")
        try:
            length = int(headers.get("Content-Length", 0) or 0)
        except ValueError as e:
            raise _ParseError(400, "bad Content-Length") from e
        if length < 0 or length > self.max_body:
            raise _ParseError(413, "body too large")
        body = b""
        if length:
            body = await asyncio.wait_for(reader.readexactly(length),
                                          self.body_timeout_s)
        conn = str(headers.get("Connection", "")).lower()
        keep = conn != "close" and not (version == "HTTP/1.0"
                                        and "keep-alive" not in conn)
        return HTTPRequest(method, target, headers, body, version), keep


class _ParseError(Exception):
    def __init__(self, status: int, msg: str):
        super().__init__(msg)
        self.status = status
        self.msg = msg


def _done(resp: HTTPResponse) -> "asyncio.Future":
    fut: "asyncio.Future" = asyncio.get_running_loop().create_future()
    fut.set_result(resp)
    return fut


# ---------------------------------------------------------------------------
# Pooled keep-alive client (the routing front's forward hop)
# ---------------------------------------------------------------------------


class AsyncConnectionPool:
    """Per-host keep-alive connection reuse for loop-thread HTTP requests.

    ``request()`` returns ``(status, Headers, body)`` — HTTP error statuses
    are RETURNED, not raised (the front treats any worker answer as
    authoritative); transport failures raise ``OSError`` /
    ``asyncio.TimeoutError`` so the caller's retry/circuit logic sees the
    same taxonomy the urlopen path produced. A request that finds its pooled
    socket closed by the peer before any response byte retries ONCE on a
    fresh connection (never after partial reads — no double-processing)."""

    def __init__(self, per_host: int = 8, idle_s: float = 30.0):
        self.per_host = max(1, int(per_host))
        self.idle_s = float(idle_s)
        self._idle: Dict[Tuple[str, int], deque] = {}

    async def request(self, method: str, url: str, body: bytes = b"",
                      headers: Optional[Dict[str, str]] = None,
                      timeout: Optional[float] = None,
                      deadline=None) -> Tuple[int, Headers, bytes]:
        """``deadline`` (core/faults.Deadline, or any object exposing
        ``remaining()``): gates the single stale-socket retry — a retry
        that would start after the request's deadline already lapsed is an
        answer nobody is waiting for (the caller's ``timeout`` bounds the
        total wall time either way; the gate makes the expiry an immediate
        error instead of a doomed second connection)."""
        parts = urlsplit(url)
        host = parts.hostname or "127.0.0.1"
        port = parts.port or (443 if parts.scheme == "https" else 80)
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query
        return await asyncio.wait_for(
            self._request((host, port), method, path, body, headers,
                          deadline),
            timeout)

    async def _request(self, key: Tuple[str, int], method: str, path: str,
                       body: bytes, headers: Optional[Dict[str, str]],
                       deadline=None) -> Tuple[int, Headers, bytes]:
        for attempt in (0, 1):
            fresh, (reader, writer) = await self._checkout(key, attempt == 1)
            try:
                req = [f"{method} {path} HTTP/1.1",
                       f"Host: {key[0]}:{key[1]}",
                       f"Content-Length: {len(body)}"]
                for k, v in (headers or {}).items():
                    if k.lower() not in ("host", "content-length",
                                         "connection"):
                        req.append(f"{k}: {v}")
                req.append("Connection: keep-alive")
                writer.write(("\r\n".join(req) + "\r\n\r\n"
                              ).encode("latin-1") + body)
                await writer.drain()
                status, rhdrs, rbody, reusable = await _read_response(reader)
            except (ConnectionError, asyncio.IncompleteReadError,
                    _StaleConnection) as e:
                self._discard(writer)
                # a reused socket the peer closed while idle: one retry on a
                # fresh connection; a fresh-connection failure is real —
                # and the retry must still be worth making: past the
                # request's X-MMLSpark-Deadline it can only waste a socket
                if not fresh and attempt == 0:
                    if deadline is not None and deadline.remaining() <= 0:
                        raise OSError(
                            f"connection to {key[0]}:{key[1]} went stale "
                            f"and the deadline expired before the retry"
                        ) from e
                    continue
                raise OSError(f"connection to {key[0]}:{key[1]} failed: {e}"
                              ) from e
            except BaseException:
                self._discard(writer)
                raise
            if reusable:
                self._checkin(key, reader, writer)
            else:
                self._discard(writer)
            return status, rhdrs, rbody
        raise OSError(f"connection to {key[0]}:{key[1]} failed")

    async def _checkout(self, key, force_fresh: bool):
        pool = self._idle.setdefault(key, deque())
        now = time.monotonic()
        while pool and not force_fresh:
            reader, writer, t = pool.popleft()
            if now - t > self.idle_s or writer.is_closing():
                self._discard(writer)
                continue
            return False, (reader, writer)
        return True, await asyncio.open_connection(*key)

    def _checkin(self, key, reader, writer) -> None:
        pool = self._idle.setdefault(key, deque())
        if len(pool) >= self.per_host or writer.is_closing():
            self._discard(writer)
            return
        pool.append((reader, writer, time.monotonic()))

    @staticmethod
    def _discard(writer) -> None:
        try:
            writer.close()
        except Exception:  # noqa: BLE001
            pass

    def close(self) -> None:
        for pool in self._idle.values():
            while pool:
                _, writer, _ = pool.popleft()
                self._discard(writer)


class _StaleConnection(Exception):
    pass


async def _read_response(reader: asyncio.StreamReader
                         ) -> Tuple[int, Headers, bytes, bool]:
    """Parse one HTTP/1.1 response: (status, headers, body, reusable)."""
    line = await reader.readline()
    if not line:
        raise _StaleConnection("peer closed before status line")
    parts = line.decode("latin-1").strip().split(None, 2)
    if len(parts) < 2 or not parts[0].startswith("HTTP/"):
        raise _StaleConnection(f"bad status line {line!r}")
    status = int(parts[1])
    headers = Headers()
    for _ in range(_MAX_HEADERS):
        hline = await reader.readline()
        if hline in (b"\r\n", b"\n", b""):
            break
        k, sep, v = hline.decode("latin-1").partition(":")
        if sep:
            headers[k.strip()] = v.strip()
    clen = headers.get("Content-Length")
    if clen is not None:
        body = await reader.readexactly(int(clen))
        reusable = str(headers.get("Connection", "")).lower() != "close"
    else:
        body = await reader.read()  # until EOF: connection not reusable
        reusable = False
    return status, headers, body, reusable
