"""Model lifecycle plane: versioned registry, shadow-scored canary
rollout with SLO-burn auto-rollback, and journaled train-on-serve.

See ``docs/lifecycle.md`` for the state machine, promotion gates, and
the feedback wire contract.
"""

from .canary import (CanaryConfig, CanaryController, LifecyclePlane,
                     make_lifecycle, score_outputs)
from .online import (CKPT_FORMAT, LABEL_HEADER, FeedbackJournal,
                     GBDTRefitAdapter, OnlineTrainer, VWOnlineAdapter)
from .registry import (CANARY, CANDIDATE, LIVE, RETIRED, ROLLED_BACK,
                       SHADOWING, STATES, ModelRegistry, ModelVersion,
                       structural_digest)

__all__ = [
    "CANARY", "CANDIDATE", "CKPT_FORMAT", "CanaryConfig",
    "CanaryController", "FeedbackJournal", "GBDTRefitAdapter",
    "LABEL_HEADER", "LIVE", "LifecyclePlane", "ModelRegistry",
    "ModelVersion", "OnlineTrainer", "RETIRED", "ROLLED_BACK",
    "SHADOWING", "STATES", "VWOnlineAdapter", "make_lifecycle",
    "score_outputs", "structural_digest",
]
