"""Shadow-scored canary rollout with SLO-burn auto-rollback.

Two cooperating pieces turn the registry's state machine into a rollout
engine:

``CanaryController``
    The decision loop (ticked from the server's tuner heartbeat, the
    brownout/fleet idiom): a candidate moves ``shadowing -> canary ->
    live`` with every step gated on evidence — shadow divergence counters
    first, then per-version SLO burn-rate buckets at each traffic step of
    the ramp (1 -> 5 -> 25 -> 100% by default). Any breach triggers a
    one-step rollback to the incumbent; every decision lands in a bounded
    journal like the tuner's and the fleet's.

``LifecyclePlane``
    The data path: the plane *is* the served transform (installed in
    front of the replica set), so routing is a per-batch decision made
    exactly once — a batch resolves its version at dispatch and never
    mixes versions mid-flight. During the shadow phase a sampled fraction
    of real traffic is duplicated to the candidate on a bounded queue
    drained by a background worker (the hedged-issue discipline: the
    incumbent's reply always wins, the shadow reply is scored against it
    — bitwise for integer/bytes payloads, per-dtype tolerance for floats
    — and discarded, never fulfilled to a client). Unknown attribute
    reads forward to the live version's transform, so fleet/tuner
    introspection (``mega_k`` and friends) sees the incumbent unchanged.

Promotion is zero-compile by construction: the controller runs the warm
hook (fleet persistent-cache warm of the candidate's executables) BEFORE
``ModelRegistry.swap_live`` flips traffic.
"""

from __future__ import annotations

import json
import queue
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ...obs import perf as obs_perf
from ...obs import trace as obs_trace
from .online import LABEL_HEADER
from .registry import (CANARY, LIVE, ROLLED_BACK, SHADOWING,
                       ModelRegistry, ModelVersion)

__all__ = ["CanaryConfig", "CanaryController", "LifecyclePlane",
           "make_lifecycle", "score_outputs"]


@dataclass(frozen=True)
class CanaryConfig:
    """Rollout policy knobs (all gates are per-version evidence)."""

    #: fraction of real batches duplicated to a shadowing candidate
    shadow_fraction: float = 0.1
    #: rows the shadow scorer must compare before the canary phase opens
    shadow_min_scored: int = 32
    #: ramped traffic shares; each step holds until its gate passes
    steps: Tuple[float, ...] = (0.01, 0.05, 0.25, 1.0)
    #: minimum wall-clock residence at a step before it can advance
    hold_s: float = 30.0
    #: minimum canary batches served at a step before it can advance
    min_step_requests: int = 8
    #: max tolerated SLO burn rate (any window) for the candidate
    burn_gate: float = 1.0
    #: max tolerated shadow divergence rate (0.0 = bitwise-or-tolerance)
    divergence_gate: float = 0.0
    #: controller tick rate limit (the tuner heartbeat is per-batch)
    check_interval_s: float = 1.0
    #: float-dtype shadow comparison tolerance (non-floats are bitwise)
    float_rtol: float = 1e-5
    float_atol: float = 1e-6
    #: per-version SLO buckets (the burn gate's denominator)
    objective_ms: float = 250.0
    slo_target: float = 0.99
    slo_windows_s: Tuple[float, ...] = (60.0, 300.0, 3600.0)
    #: routing RNG seed — rollouts are replayable decisions
    seed: int = 0
    journal_cap: int = 256


# ---------------------------------------------------------------------------
# Shadow scoring
# ---------------------------------------------------------------------------

def _rows_equal(a: Any, b: Any, rtol: float, atol: float) -> bool:
    """Bitwise for integer/bytes/object payloads, tolerance for floats."""
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        aa, ba = np.asarray(a), np.asarray(b)
        if aa.shape != ba.shape or aa.dtype != ba.dtype:
            return False
        if np.issubdtype(aa.dtype, np.inexact):
            return bool(np.allclose(aa, ba, rtol=rtol, atol=atol,
                                    equal_nan=True))
        return bool(np.array_equal(aa, ba))
    if isinstance(a, float) and isinstance(b, float):
        return bool(np.isclose(a, b, rtol=rtol, atol=atol, equal_nan=True))
    try:
        return bool(a == b)
    except Exception:  # noqa: BLE001 — incomparable payloads diverge
        return False


def _reply_rows(out: Any, reply_col: str):
    """(ids, replies) from a transform output — the _apply_output
    contract (id + reply columns); positional ids when absent."""
    coll = getattr(out, "collect", None)
    data = coll() if callable(coll) else None
    if data is None:
        if isinstance(out, dict):
            data = out
        else:
            arr = np.asarray(out)
            return list(range(len(arr))), list(arr)
    if reply_col not in data:
        return [], []
    replies = list(data[reply_col])
    ids = list(data["id"]) if "id" in data else list(range(len(replies)))
    return ids, replies


def score_outputs(expected: Any, actual: Any, *, reply_col: str = "reply",
                  rtol: float = 1e-5, atol: float = 1e-6
                  ) -> Tuple[int, int]:
    """Compare a candidate's output against the incumbent's for the same
    batch; returns ``(scored, divergent)`` row counts. Rows pair by the
    ``id`` column when both outputs carry one (positionally otherwise);
    rows present on one side only count as divergent."""
    try:
        e_ids, e_rows = _reply_rows(expected, reply_col)
        a_ids, a_rows = _reply_rows(actual, reply_col)
    except Exception:  # noqa: BLE001 — unreadable output scores nothing
        return 0, 0
    amap = {int(i): r for i, r in zip(a_ids, a_rows)}
    scored = divergent = 0
    for i, row in zip(e_ids, e_rows):
        scored += 1
        other = amap.pop(int(i), None)
        if other is None or not _rows_equal(row, other, rtol, atol):
            divergent += 1
    # candidate rows with no incumbent counterpart are divergence too
    scored += len(amap)
    divergent += len(amap)
    return scored, divergent


# ---------------------------------------------------------------------------
# Controller
# ---------------------------------------------------------------------------

class CanaryController:
    """Gated rollout decision loop over one active candidate at a time.

    ``rollout(version)`` arms a candidate; ``check()`` (rate-limited,
    called from the tuner heartbeat) walks it through the state machine:

      shadowing  gate: >= shadow_min_scored rows compared, zero shadow
                 errors, divergence rate within ``divergence_gate``
      canary[i]  gate: >= hold_s at the step AND >= min_step_requests
                 canary batches AND max burn rate <= burn_gate
      promote    warm hook first (zero-compile), then the registry's
                 two-phase ``swap_live``

    Any breach rolls the candidate back in ONE step — traffic share to
    zero, state ``rolled_back`` — with the evidence journaled. A swap
    failure (crash seam) journals and leaves the incumbent serving; the
    promotion retries on the next tick.
    """

    def __init__(self, registry: ModelRegistry, config: CanaryConfig, *,
                 apply_swap: Optional[Callable[[ModelVersion,
                                                Optional[ModelVersion]],
                                               None]] = None,
                 warm: Optional[Callable[[ModelVersion], Any]] = None,
                 clock=time.monotonic):
        self.registry = registry
        self.config = config
        self._apply_swap = apply_swap
        self._warm = warm
        self._clock = clock
        self._active: Optional[str] = None
        self._step = -1            # -1 = shadowing
        self._step_t0 = 0.0
        self._step_req0 = 0
        self._last_check = 0.0
        self.rollouts = 0
        self.promotions = 0
        self.rollbacks = 0
        #: bounded decision journal (brownout/fleet idiom)
        self.journal: List[Dict[str, Any]] = []
        self._journal_cap = max(8, int(config.journal_cap))

    def _log(self, action: str, **info: Any) -> None:
        if len(self.journal) >= self._journal_cap:
            del self.journal[: self._journal_cap // 4]
        self.journal.append({"action": action,
                             "t": round(self._clock(), 3), **info})

    # -- introspection ---------------------------------------------------
    def active_version(self) -> Optional[ModelVersion]:
        vid = self._active
        if vid is None:
            return None
        try:
            return self.registry.get(vid)
        except KeyError:
            return None

    def shadow_target(self) -> Optional[ModelVersion]:
        ver = self.active_version()
        return ver if ver is not None and ver.state == SHADOWING else None

    # -- rollout entry ---------------------------------------------------
    def rollout(self, version: str) -> ModelVersion:
        """Arm ``version`` (a registered candidate) for rollout. Only one
        rollout runs at a time; shadow is skipped when the config disables
        it (shadow_fraction or shadow_min_scored <= 0)."""
        if self._active is not None:
            raise ValueError(
                f"rollout already active for {self._active!r}")
        shadow = (self.config.shadow_fraction > 0.0
                  and self.config.shadow_min_scored > 0)
        ver = self.registry.transition(
            version, SHADOWING if shadow else CANARY)
        self._active = version
        self.rollouts += 1
        if shadow:
            self._step = -1
            self._log("shadow_start", version=version,
                      fraction=self.config.shadow_fraction)
        else:
            self._enter_step(ver, 0)
        return ver

    def _enter_step(self, ver: ModelVersion, step: int) -> None:
        share = float(self.config.steps[step])
        self._step = step
        self._step_t0 = self._clock()
        self._step_req0 = ver.requests["canary"]
        ver.traffic_share = share
        self._log("canary_step", version=ver.version, step=step,
                  share=share)

    # -- the gated walk --------------------------------------------------
    def check(self) -> None:
        """Rate-limited gate evaluation; never raises (a failed swap is
        journaled and retried, everything else is state inspection)."""
        now = self._clock()
        if now - self._last_check < self.config.check_interval_s:
            return
        self._last_check = now
        ver = self.active_version()
        if ver is None:
            self._active = None
            return
        if ver.state == SHADOWING:
            self._check_shadow(ver)
        elif ver.state == CANARY:
            self._check_canary(ver, now)
        else:
            # promoted or externally transitioned — rollout is over
            self._active = None

    def _check_shadow(self, ver: ModelVersion) -> None:
        if ver.shadow_errors > 0:
            self.rollback(ver, "shadow_errors",
                          errors=ver.shadow_errors)
            return
        if ver.shadow_scored < self.config.shadow_min_scored:
            return
        div = ver.divergence_rate()
        if div > self.config.divergence_gate:
            self.rollback(ver, "divergence", divergence=round(div, 6),
                          scored=ver.shadow_scored)
            return
        self.registry.transition(ver.version, CANARY,
                                 scored=ver.shadow_scored,
                                 divergence=round(div, 6))
        self._enter_step(ver, 0)

    def _check_canary(self, ver: ModelVersion, now: float) -> None:
        served = ver.requests["canary"] - self._step_req0
        burn = ver.max_burn()
        # breach check runs every tick — rollback must not wait for hold_s
        if served >= self.config.min_step_requests \
                and burn > self.config.burn_gate:
            self.rollback(ver, "slo_burn", burn=round(burn, 4),
                          step=self._step, served=served)
            return
        div = ver.divergence_rate()
        if div > self.config.divergence_gate:
            self.rollback(ver, "divergence", divergence=round(div, 6),
                          step=self._step)
            return
        if now - self._step_t0 < self.config.hold_s \
                or served < self.config.min_step_requests:
            return
        if self._step + 1 < len(self.config.steps):
            self._enter_step(ver, self._step + 1)
        else:
            self._promote(ver, burn)

    def _promote(self, ver: ModelVersion, burn: float) -> None:
        # warm BEFORE traffic: the fleet hook stages the candidate's
        # executables into the persistent compile cache so the swap costs
        # zero jit compiles. Best-effort — a cold promotion is journaled,
        # not blocked.
        warmed: Any = None
        try:
            if self._warm is not None:
                warmed = self._warm(ver)
            elif callable(ver.warm):
                warmed = ver.warm()
        except Exception as e:  # noqa: BLE001 — warm is an optimization
            warmed = f"error: {e}"
        self._log("warm", version=ver.version, result=str(warmed))
        try:
            self.registry.swap_live(ver.version, apply=self._apply_swap,
                                    burn=round(burn, 4))
        except Exception as e:  # noqa: BLE001 — crash seam: incumbent
            # keeps serving, the promotion retries on the next tick
            self._log("swap_failed", version=ver.version, error=str(e))
            return
        self.promotions += 1
        self._log("promote", version=ver.version)
        self._active = None

    def rollback(self, ver: ModelVersion, reason: str, **info: Any) -> None:
        """One-step rollback: the candidate stops taking traffic and the
        incumbent (which never stopped serving) carries 100% again."""
        ver.traffic_share = 0.0
        self.registry.transition(ver.version, ROLLED_BACK, reason=reason,
                                 **info)
        self.rollbacks += 1
        self._log("rollback", version=ver.version, reason=reason, **info)
        self._active = None

    def summary(self) -> Dict[str, Any]:
        ver = self.active_version()
        return {"active": self._active, "step": self._step,
                "state": ver.state if ver is not None else None,
                "rollouts": self.rollouts, "promotions": self.promotions,
                "rollbacks": self.rollbacks,
                "journal": list(self.journal[-16:])}


# ---------------------------------------------------------------------------
# The plane: lifecycle-aware served transform
# ---------------------------------------------------------------------------

class LifecyclePlane:
    """The lifecycle data path, installed AS the server's transform.

    Each batch resolves its version exactly once (at dispatch), so a
    promotion swap changes versions only between batches — the executor's
    prep-generation guard then guarantees completions claim against the
    dispatch that issued them. Real traffic is accounted per version
    (batch counters + SLO burn buckets); shadow duplicates ride a bounded
    queue to a background worker and are scored, never fulfilled.
    """

    def __init__(self, config: Optional[CanaryConfig] = None, *,
                 hooks: Optional[Dict[str, Any]] = None,
                 clock=time.monotonic):
        cfg = config if config is not None else CanaryConfig()
        self.config = cfg
        self._hooks = dict(hooks or {})
        self._clock = clock
        slo_cfg = obs_perf.SLOConfig(
            name="lifecycle", objective_ms=cfg.objective_ms,
            target=cfg.slo_target, windows_s=tuple(cfg.slo_windows_s))
        self.registry = ModelRegistry(slo_config=slo_cfg,
                                      journal_cap=cfg.journal_cap,
                                      clock=clock,
                                      namespace=self._hooks.get("namespace"))
        self.controller = CanaryController(
            self.registry, cfg, apply_swap=self._apply_swap,
            warm=self._hooks.get("warm"), clock=clock)
        self._server: Any = None
        self._reply_col = "reply"
        self._rng = random.Random(cfg.seed)
        self._rng_lock = threading.Lock()
        # bounded shadow queue: duplicates ride idle capacity or drop
        self._shadow_q: "queue.Queue" = queue.Queue(maxsize=2)
        self._shadow_stop = threading.Event()
        self._shadow_thread: Optional[threading.Thread] = None
        self.shadow_skipped = 0
        self._online: Any = None

    # -- attribute forwarding: fleet/tuner introspection (mega_k, ...)
    # sees the live version's transform through the plane
    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        reg = self.__dict__.get("registry")
        live = reg.live if reg is not None else None
        if live is None:
            raise AttributeError(name)
        return getattr(live.transform, name)

    # -- wiring ----------------------------------------------------------
    def bind(self, server: Any) -> "LifecyclePlane":
        """Adopt ``server.transform`` as the live incumbent and return the
        plane (the server installs the return value as its transform)."""
        self._server = server
        self._reply_col = getattr(server, "reply_col", "reply")
        if self.registry.live is None:
            self.registry.adopt_live(
                server.transform,
                version=self._hooks.get("live_version"),
                stage=self._hooks.get("live_stage"),
                cost=self._hooks.get("live_cost"))
        return self

    def start(self) -> None:
        if self._shadow_thread is None:
            self._shadow_stop.clear()
            self._shadow_thread = threading.Thread(
                target=self._shadow_loop, name="mmlspark-lifecycle-shadow",
                daemon=True)
            self._shadow_thread.start()

    def stop(self) -> None:
        self._shadow_stop.set()
        t = self._shadow_thread
        if t is not None:
            t.join(timeout=5.0)
            self._shadow_thread = None
        ot = self._online
        if ot is not None:
            try:
                ot.stop()
            except Exception:  # noqa: BLE001 — shutdown stays best-effort
                pass

    def tick(self, e2e_s: float) -> None:  # noqa: ARG002 — heartbeat shape
        self.controller.check()
        ot = self._online
        if ot is not None:
            ot.tick()

    # -- model management ------------------------------------------------
    def register(self, transform: Callable, **kwargs: Any) -> ModelVersion:
        return self.registry.register(transform, **kwargs)

    def rollout(self, version: str) -> ModelVersion:
        return self.controller.rollout(version)

    def deploy(self, transform: Callable, **kwargs: Any) -> ModelVersion:
        """register + rollout in one move (the online trainer's handoff)."""
        ver = self.register(transform, **kwargs)
        return self.rollout(ver.version)

    def attach_online(self, trainer: Any) -> None:
        self._online = trainer

    def feed_feedback(self, rows, labels) -> int:
        """Forward labeled feedback rows to the online trainer (0 when
        train-on-serve is not attached)."""
        ot = self._online
        if ot is None:
            return 0
        return int(ot.feed(rows, labels))

    # -- swap apply: the executor-guarded flip ---------------------------
    def _apply_swap(self, new: ModelVersion,
                    old: Optional[ModelVersion]) -> None:
        """Serialize the live flip with batch dispatch: re-install the
        plane on every replica under the executor's dispatch lock (the
        same lock the prep-generation registry uses), so the swap lands
        between batch registrations, never inside one."""
        srv = self._server
        ex = getattr(srv, "_executor", None) if srv is not None else None
        if ex is not None:
            ex.swap_transform(self)

    # -- routing ---------------------------------------------------------
    def _route(self) -> Tuple[ModelVersion, str]:
        cand = self.controller.active_version()
        if cand is not None and cand.state == CANARY:
            share = cand.traffic_share
            if share > 0.0:
                with self._rng_lock:
                    r = self._rng.random()
                if r < share:
                    return cand, "canary"
        live = self.registry.live
        if live is None:
            raise RuntimeError("lifecycle plane has no live version")
        return live, "live"

    def _account(self, ver: ModelVersion, role: str, dur_s: float,
                 t0_wall: float, cb) -> None:
        ver.requests[role] += 1
        if ver.slo is not None:
            try:
                ver.slo.record(dur_s)
            except Exception:  # noqa: BLE001 — accounting never kills serving
                pass
        if role == "canary" and cb is not None:
            tracer, ctxs = cb
            tracer.record_batch("lifecycle.canary", ctxs, t0_wall, dur_s,
                                version=ver.version)

    # -- data path -------------------------------------------------------
    def __call__(self, df: Any) -> Any:
        ver, role = self._route()
        self._maybe_feedback(df)
        cb = obs_trace.current_batch()
        t0w, t0p = time.time(), time.perf_counter()
        out = ver.transform(df)
        self._account(ver, role, time.perf_counter() - t0p, t0w, cb)
        self._maybe_shadow(df, out, cb)
        return out

    def submit(self, df: Any):
        """Async-dispatch face (the ReplicaSet contract): returns a
        zero-arg resolve, or None to make the caller fall back to the
        synchronous ``run`` path (which re-routes in ``__call__`` — the
        declined draw is never accounted)."""
        ver, role = self._route()
        sub = getattr(ver.transform, "submit", None)
        pending = sub(df) if sub is not None else None
        if pending is None:
            return None
        self._maybe_feedback(df)
        cb = obs_trace.current_batch()
        t0w, t0p = time.time(), time.perf_counter()

        def _resolve():
            out = pending()
            self._account(ver, role, time.perf_counter() - t0p, t0w, cb)
            self._maybe_shadow(df, out, cb)
            return out

        return _resolve

    # -- feedback extraction (X-MMLSpark-Label wire contract) ------------
    def _maybe_feedback(self, df: Any) -> None:
        if self._online is None:
            return
        try:
            if "headers" not in getattr(df, "columns", ()):
                return
            hs = df.column("headers")
            vs = df.column("value")
        except Exception:  # noqa: BLE001 — non-ingress frame shapes
            return
        rows, labels = [], []
        for h, v in zip(hs, vs):
            if not isinstance(h, dict):
                continue
            lab = next((val for k, val in h.items()
                        if k.lower() == LABEL_HEADER.lower()), None)
            if lab is None:
                continue
            try:
                body = v if isinstance(v, str) \
                    else bytes(v).decode("utf-8")
                rows.append(json.loads(body))
                labels.append(float(lab))
            except Exception:  # noqa: BLE001 — malformed feedback skipped
                continue
        if rows:
            self.feed_feedback(rows, labels)

    # -- shadow ----------------------------------------------------------
    def _maybe_shadow(self, df: Any, live_out: Any, cb) -> None:
        cand = self.controller.shadow_target()
        if cand is None:
            return
        with self._rng_lock:
            r = self._rng.random()
        if r >= self.config.shadow_fraction:
            return
        try:
            self._shadow_q.put_nowait((cand, df, live_out, cb))
            cand.shadow_issued += 1
        except queue.Full:
            # no idle capacity — drop the duplicate, never block serving
            self.shadow_skipped += 1

    def _shadow_loop(self) -> None:
        while not self._shadow_stop.is_set():
            try:
                cand, df, live_out, cb = self._shadow_q.get(timeout=0.1)
            except queue.Empty:
                continue
            t0w, t0p = time.time(), time.perf_counter()
            try:
                out = cand.transform(df)
            except Exception:  # noqa: BLE001 — candidate failures are gate
                # evidence, not serving failures
                cand.shadow_errors += 1
                continue
            dur = time.perf_counter() - t0p
            scored, divergent = score_outputs(
                live_out, out, reply_col=self._reply_col,
                rtol=self.config.float_rtol, atol=self.config.float_atol)
            cand.shadow_scored += scored
            cand.shadow_divergent += divergent
            if cb is not None:
                tracer, ctxs = cb
                tracer.record_batch("lifecycle.shadow", ctxs, t0w, dur,
                                    version=cand.version, scored=scored,
                                    divergent=divergent)

    # -- introspection ---------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        out = {"registry": self.registry.summary(),
               "canary": self.controller.summary(),
               "shadow_skipped": self.shadow_skipped}
        ot = self._online
        if ot is not None:
            try:
                out["online"] = ot.summary()
            except Exception:  # noqa: BLE001 — introspection best-effort
                pass
        return out


def make_lifecycle(spec: Any, hooks: Optional[Dict[str, Any]] = None,
                   clock=time.monotonic) -> Optional[LifecyclePlane]:
    """Coerce the server's ``lifecycle=`` knob: None/False -> off, True ->
    defaults, dict -> CanaryConfig kwargs, CanaryConfig -> as-is, a
    LifecyclePlane passes through (pre-wired planes keep their hooks)."""
    if spec is None or spec is False:
        return None
    if isinstance(spec, LifecyclePlane):
        return spec
    if spec is True:
        return LifecyclePlane(CanaryConfig(), hooks=hooks, clock=clock)
    if isinstance(spec, CanaryConfig):
        return LifecyclePlane(spec, hooks=hooks, clock=clock)
    if isinstance(spec, dict):
        return LifecyclePlane(CanaryConfig(**spec), hooks=hooks,
                              clock=clock)
    raise TypeError(f"lifecycle: cannot coerce {type(spec).__name__}")
