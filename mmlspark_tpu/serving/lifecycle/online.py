"""Train-on-serve: journal-backed online updates feeding the canary plane.

Labeled feedback arrives two ways — in-band on a prediction request (the
``X-MMLSpark-Label`` header: the body is the example, the header its
label) or batched through a ``POST /_mmlspark/feedback`` of
``{"rows": [...], "labels": [...]}``. Either path lands every example in
an append-only fsynced JSONL journal BEFORE any training sees it, so the
training fold is a pure replay of the journal:

  - ``OnlineTrainer`` consumes the journal in fixed-size batches grouped
    by ABSOLUTE example index (step k always covers rows
    ``[k*batch_rows, (k+1)*batch_rows)``), folding each batch into
    adapter-owned state on a background thread (or driven explicitly via
    ``train_pending`` — the tests' deterministic mode).
  - Every ``checkpoint_every`` steps the adapter state is serialized
    through the PR 2 atomic-checkpoint machinery (tmp + fsync +
    ``os.replace``), with the ``lifecycle.checkpoint`` chaos seam fired
    first: a crash mid-checkpoint leaves the previous checkpoint intact,
    and ``resume()`` + journal replay reproduces the uninterrupted run's
    state bitwise.
  - Finished candidates hand off to the canary pipeline
    (``plane.deploy`` = register + rollout) once ``publish_after`` new
    examples have been folded.

First adapters: the VW linear learner (``vw/learner.LinearLearner``
incremental scan steps) and GBDT refit (state = the bounded labeled-row
buffer; the model is a pure function of it).
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...core import faults
from ...core.faults import atomic_write_text

__all__ = ["LABEL_HEADER", "CKPT_FORMAT", "FeedbackJournal",
           "VWOnlineAdapter", "GBDTRefitAdapter", "OnlineTrainer"]

#: in-band feedback: a prediction request carrying this header is ALSO a
#: labeled training example (body = features, header value = label)
LABEL_HEADER = "X-MMLSpark-Label"

CKPT_FORMAT = "mmlspark_tpu.lifecycle.ckpt.v1"


def _arr_to_json(a: np.ndarray) -> Dict[str, Any]:
    a = np.ascontiguousarray(a)
    return {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
            "dtype": str(a.dtype), "shape": list(a.shape)}


def _arr_from_json(d: Dict[str, Any]) -> np.ndarray:
    return np.frombuffer(base64.b64decode(d["b64"]),
                         dtype=d["dtype"]).reshape(d["shape"]).copy()


class FeedbackJournal:
    """Append-only JSONL of labeled examples, one ``{"row","label"}``
    object per line, fsynced per append call (the write-ahead contract:
    an example is journaled before any trainer state reflects it)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._count = 0
        if os.path.exists(path):
            with open(path, encoding="utf-8") as fh:
                self._count = sum(1 for line in fh if line.strip())
        self._fh = open(path, "a", encoding="utf-8")

    def append(self, rows, labels) -> int:
        if len(rows) != len(labels):
            raise ValueError(
                f"rows/labels length mismatch: {len(rows)} vs {len(labels)}")
        lines = [json.dumps({"row": r, "label": float(lab)})
                 for r, lab in zip(rows, labels)]
        if not lines:
            return 0
        with self._lock:
            self._fh.write("\n".join(lines) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._count += len(lines)
        return len(lines)

    def count(self) -> int:
        with self._lock:
            return self._count

    def read(self, start: int, limit: int) -> List[Tuple[Any, float]]:
        """Examples ``[start, start+limit)`` in append order (a replay
        read — opens its own handle, never moves the append position)."""
        out: List[Tuple[Any, float]] = []
        with open(self.path, encoding="utf-8") as fh:
            for i, line in enumerate(ln for ln in fh if ln.strip()):
                if i < start:
                    continue
                if len(out) >= limit:
                    break
                d = json.loads(line)
                out.append((d["row"], float(d["label"])))
        return out

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except Exception:  # noqa: BLE001 — close is best-effort
                pass


# ---------------------------------------------------------------------------
# Adapters — the online contract:
#   fresh() -> state
#   step(state, rows, labels) -> state     (deterministic fold)
#   to_json(state) -> dict / from_json(dict) -> state   (bitwise round-trip)
#   make_transform(state, reply_col) -> served transform (optional)
# ---------------------------------------------------------------------------

class VWOnlineAdapter:
    """The VW linear learner as an online adapter: rows are sparse dicts
    ``{"indices": [...], "values": [...]}``, state is the learner's
    (weights + optimizer accumulators + lr clock) tuple — incremental
    scan steps via ``LinearLearner.partial_fit``, always the jax scan
    path (the native engine keeps state in C++ and cannot round-trip
    bitwise through a checkpoint)."""

    name = "vw"

    def __init__(self, config=None):
        from ...vw.learner import LearnerConfig

        self.config = config if config is not None else LearnerConfig()

    def fresh(self):
        from ...vw.learner import LinearLearner

        return LinearLearner(self.config)

    def step(self, learner, rows, labels):
        learner.partial_fit(rows, labels)
        return learner

    def to_json(self, learner) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for k, v in learner.state_dict().items():
            out[k] = _arr_to_json(v) if isinstance(v, np.ndarray) else v
        return out

    def from_json(self, d: Dict[str, Any]):
        from ...vw.learner import LinearLearner

        sd = {k: (_arr_from_json(v)
                  if isinstance(v, dict) and "b64" in v else v)
              for k, v in d.items()}
        return LinearLearner(self.config).load_state_dict(sd)

    def make_transform(self, learner, reply_col: str = "reply"):
        """Freeze the current weights into a served transform: each
        request body is a sparse-row JSON, the reply its linear score."""
        w = np.array(learner.weights)  # snapshot — the version is immutable
        num_bits = self.config.num_bits

        def transform(df):
            from ...core.dataframe import DataFrame
            from ...vw.learner import SparseDataset, predict_linear

            data = df.collect()
            bodies = data["value"]
            rows = []
            for b in bodies:
                body = b if isinstance(b, str) else bytes(b).decode("utf-8")
                rows.append(json.loads(body))
            ds = SparseDataset.from_rows(
                rows, np.zeros(len(rows)), num_bits=num_bits)
            preds = predict_linear(w, ds)
            return DataFrame.from_dict(
                {"id": np.asarray(data["id"]),
                 reply_col: [float(p) for p in preds]})

        return transform


class GBDTRefitAdapter:
    """GBDT as an online adapter by bounded-buffer refit: state is the
    labeled row buffer itself (rows are dense feature lists, or sparse
    dicts whose ``values`` are taken dense), and the model is a pure
    function of the buffer — refit at publish time. Resume is trivially
    bitwise: replaying the journal rebuilds the identical buffer."""

    name = "gbdt"

    def __init__(self, params=None, max_rows: int = 4096):
        self.params = params
        self.max_rows = max(1, int(max_rows))

    @staticmethod
    def _dense(row) -> List[float]:
        vals = row.get("values", row) if isinstance(row, dict) else row
        if isinstance(vals, (int, float)):
            return [float(vals)]  # scalar feature (header-labeled requests)
        return [float(x) for x in vals]

    def fresh(self) -> Dict[str, list]:
        return {"X": [], "y": []}

    def step(self, state, rows, labels):
        for r, lab in zip(rows, labels):
            state["X"].append(self._dense(r))
            state["y"].append(float(lab))
        overflow = len(state["y"]) - self.max_rows
        if overflow > 0:
            del state["X"][:overflow]
            del state["y"][:overflow]
        return state

    def to_json(self, state) -> Dict[str, Any]:
        return {"X": state["X"], "y": state["y"]}

    def from_json(self, d: Dict[str, Any]):
        return {"X": [[float(x) for x in r] for r in d["X"]],
                "y": [float(v) for v in d["y"]]}

    def fit(self, state):
        """Refit a Booster on the current buffer (the publish step)."""
        from ...gbdt.booster import TrainParams, train

        params = self.params if self.params is not None else TrainParams(
            num_iterations=20, num_leaves=15, min_data_in_leaf=1)
        X = np.asarray(state["X"], dtype=np.float64)
        y = np.asarray(state["y"], dtype=np.float64)
        return train(params, X, y)

    def make_transform(self, state, reply_col: str = "reply"):
        if not state["y"]:
            return None
        booster = self.fit(state)

        def transform(df):
            from ...core.dataframe import DataFrame

            data = df.collect()
            bodies = data["value"]
            rows = []
            for b in bodies:
                body = b if isinstance(b, str) else bytes(b).decode("utf-8")
                rows.append(GBDTRefitAdapter._dense(json.loads(body)))
            preds = booster.raw_predict(np.asarray(rows, dtype=np.float64))
            return DataFrame.from_dict(
                {"id": np.asarray(data["id"]),
                 reply_col: [float(p) for p in np.asarray(preds).ravel()]})

        return transform


# ---------------------------------------------------------------------------
# The trainer
# ---------------------------------------------------------------------------

class OnlineTrainer:
    """Journal-replay trainer: deterministic fold, atomic checkpoints,
    canary handoff. See the module docstring for the replay contract."""

    def __init__(self, adapter, journal_path: str,
                 checkpoint_path: Optional[str] = None, *,
                 batch_rows: int = 32, checkpoint_every: int = 1,
                 publish_after: int = 0, version_prefix: str = "online",
                 reply_col: str = "reply", poll_s: float = 0.25,
                 auto: bool = False, clock=time.monotonic):
        self.adapter = adapter
        self.journal = FeedbackJournal(journal_path)
        self.checkpoint_path = checkpoint_path \
            if checkpoint_path is not None else journal_path + ".ckpt"
        self.batch_rows = max(1, int(batch_rows))
        self.checkpoint_every = max(1, int(checkpoint_every))
        #: publish a candidate to the plane every this-many folded
        #: examples (0 = never publish automatically)
        self.publish_after = int(publish_after)
        self.version_prefix = version_prefix
        self.reply_col = reply_col
        self._clock = clock
        self._plane: Any = None
        # serializes the fold/checkpoint/publish path; feed() only touches
        # the journal's own lock, so ingestion never waits on training
        # re-entrant: publish() serializes against training but is also
        # called from _maybe_publish inside the train_pending fold
        self._train_lock = threading.RLock()
        self.state = adapter.fresh()
        self.step = 0
        self.consumed = 0
        self.published = 0
        self.publish_failed = 0
        self._published_at = 0
        self._poll_s = float(poll_s)
        self._auto = bool(auto)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- wiring ----------------------------------------------------------
    def attach_plane(self, plane) -> "OnlineTrainer":
        self._plane = plane
        plane.attach_online(self)
        return self

    # -- ingestion -------------------------------------------------------
    def feed(self, rows, labels) -> int:
        """Journal labeled examples (write-ahead: returns once fsynced)."""
        return self.journal.append(rows, labels)

    def pending(self) -> int:
        return self.journal.count() - self.consumed

    # -- training --------------------------------------------------------
    def train_pending(self, max_steps: Optional[int] = None,
                      flush: bool = False) -> int:
        """Fold journaled examples in absolute-index batches; returns the
        number of steps taken. Only full batches fold (``flush=True``
        takes the partial tail too — NOT bitwise-stable across resumes,
        since a later run may see the tail as part of a full batch)."""
        done = 0
        with self._train_lock:
            while max_steps is None or done < max_steps:
                avail = self.journal.count() - self.consumed
                take = self.batch_rows if avail >= self.batch_rows \
                    else (avail if flush and avail > 0 else 0)
                if take == 0:
                    break
                recs = self.journal.read(self.consumed, take)
                self.state = self.adapter.step(
                    self.state, [r for r, _ in recs],
                    [lab for _, lab in recs])
                self.consumed += len(recs)
                self.step += 1
                done += 1
                if self.step % self.checkpoint_every == 0:
                    self._checkpoint()
            if done:
                self._maybe_publish()
        return done

    def _checkpoint(self) -> None:
        # chaos seam BEFORE the write: a crash here leaves the previous
        # checkpoint intact and resume() replays forward bitwise
        faults.fire(faults.LIFECYCLE_CHECKPOINT, step=self.step,
                    consumed=self.consumed)
        payload = json.dumps({
            "format": CKPT_FORMAT,
            "adapter": type(self.adapter).__name__,
            "step": self.step,
            "consumed": self.consumed,
            "state": self.adapter.to_json(self.state),
        })
        atomic_write_text(self.checkpoint_path, payload)

    def resume(self) -> bool:
        """Load the checkpoint (when present) and position the replay
        cursor; the next ``train_pending`` replays the journal tail. A
        missing checkpoint resumes from scratch (full replay)."""
        if not os.path.exists(self.checkpoint_path):
            return False
        with open(self.checkpoint_path, encoding="utf-8") as fh:
            d = json.load(fh)
        if d.get("format") != CKPT_FORMAT:
            raise ValueError(f"bad checkpoint format {d.get('format')!r} "
                             f"in {self.checkpoint_path!r}")
        with self._train_lock:
            self.state = self.adapter.from_json(d["state"])
            self.step = int(d["step"])
            self.consumed = int(d["consumed"])
            self._published_at = self.consumed
        return True

    # -- canary handoff --------------------------------------------------
    def _state_digest(self) -> str:
        blob = json.dumps(self.adapter.to_json(self.state), sort_keys=True)
        return "o:" + hashlib.sha256(blob.encode("utf-8")).hexdigest()[:20]

    def _maybe_publish(self) -> None:
        if self.publish_after <= 0 or self._plane is None:
            return
        if self.consumed - self._published_at < self.publish_after:
            return
        self.publish()

    def publish(self) -> Optional[str]:
        """Build a transform from the current state and hand it to the
        canary pipeline (register + rollout). Returns the version id, or
        None when the adapter has nothing to serve or a rollout is
        already in flight (journaled as a failed publish, not retried
        until the next publish_after threshold). Serializes against
        training so the published state is a consistent snapshot."""
        with self._train_lock:
            self._published_at = self.consumed
            make = getattr(self.adapter, "make_transform", None)
            if make is None or self._plane is None:
                return None
            try:
                transform = make(self.state, self.reply_col)
                if transform is None:
                    return None
                vid = f"{self.version_prefix}-{self.step}"
                self._plane.deploy(transform, version=vid,
                                   digest=self._state_digest(),
                                   cost={"examples": self.consumed})
            except Exception:  # noqa: BLE001 — an active rollout or a
                # refit failure must not kill the training loop
                self.publish_failed += 1
                return None
            self.published += 1
            return vid

    # -- background loop -------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="mmlspark-lifecycle-online", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._poll_s):
            try:
                self.train_pending()
            except Exception:  # noqa: BLE001 — training must never die
                # silently; the journal keeps the examples for a retry
                continue

    def tick(self) -> None:
        """The plane's heartbeat hook: in ``auto`` mode without a
        background thread, fold at most one step inline per tick."""
        if self._auto and self._thread is None:
            self.train_pending(max_steps=1)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        self.journal.close()

    def summary(self) -> Dict[str, Any]:
        return {"adapter": getattr(self.adapter, "name",
                                   type(self.adapter).__name__),
                "step": self.step, "consumed": self.consumed,
                "pending": self.pending(), "published": self.published,
                "publish_failed": self.publish_failed,
                "journal_path": self.journal.path,
                "checkpoint_path": self.checkpoint_path}
