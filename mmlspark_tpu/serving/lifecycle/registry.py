"""Versioned in-process model registry: the serving tier's model lifecycle.

The reference serves exactly one immutable model per query
(HTTPSourceV2.scala binds the transform at stream start); every rollout is
a redeploy. Here models are first-class *versions* — TVM's framing of
imported checkpoints as interchangeable artifacts — moving through an
explicit state machine::

    candidate -> shadowing -> canary -> live -> retired
         \\            \\          \\
          \\            v          v
           +------> rolled_back  rolled_back

  - ``candidate``   registered, taking no traffic
  - ``shadowing``   scored against the incumbent on duplicated traffic
  - ``canary``      serving a ramped share of real traffic
  - ``live``        the incumbent (exactly one at a time)
  - ``retired``     a former incumbent after a successful promotion
  - ``rolled_back`` a candidate the gates rejected (terminal)

State transitions are journaled like every tuner/fleet decision (bounded
in-memory journal, surfaced at ``/_mmlspark/models``), and the live-pointer
swap is a two-phase operation with a chaos seam (``faults.LIFECYCLE_SWAP``)
fired BEFORE any state mutates: a crash mid-swap leaves the incumbent
serving, never a half-promoted registry.

Identity is structural: ``ModelVersion.digest`` prefers the model's own
``cache_token()`` (models/module.FunctionModel — the same cross-process
token the fleet's persistent compile cache keys on), falling back to a
sha256 of the pickled transform, then to a process-local id.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ...core import faults
from ...obs import perf as obs_perf

# lifecycle states (the docstring's state machine)
CANDIDATE = "candidate"
SHADOWING = "shadowing"
CANARY = "canary"
LIVE = "live"
RETIRED = "retired"
ROLLED_BACK = "rolled_back"

STATES = (CANDIDATE, SHADOWING, CANARY, LIVE, RETIRED, ROLLED_BACK)

#: legal transitions; candidate -> canary skips the shadow phase (an
#: operator's prerogative for pre-validated models)
_ALLOWED: Dict[str, Tuple[str, ...]] = {
    CANDIDATE: (SHADOWING, CANARY, RETIRED),
    SHADOWING: (CANARY, ROLLED_BACK, RETIRED),
    CANARY: (LIVE, ROLLED_BACK),
    LIVE: (RETIRED,),
    RETIRED: (),
    ROLLED_BACK: (),
}


def structural_digest(obj: Any) -> str:
    """Cross-process identity of a model/transform: ``cache_token()`` when
    the object carries one (FunctionModel and anything adopting its
    contract), else sha256 of its pickle, else a process-local id (opaque
    closures — correctness keeps, cross-process comparison degrades)."""
    tok = getattr(obj, "cache_token", None)
    if callable(tok):
        try:
            return str(tok())
        except Exception:  # noqa: BLE001 — fall through to pickle
            pass
    import hashlib
    import pickle

    try:
        return "p:" + hashlib.sha256(
            pickle.dumps(obj, protocol=4)).hexdigest()[:20]
    except Exception:  # noqa: BLE001 — unpicklable closure
        return f"id:{id(obj)}"


class ModelVersion:
    """One registered model: transform + structural digest + cost snapshot +
    lifecycle state + per-version traffic/divergence/SLO accounting."""

    __slots__ = ("version", "transform", "stage", "digest", "cost", "state",
                 "created_s", "warm", "slo", "requests", "shadow_issued",
                 "shadow_scored", "shadow_divergent", "shadow_errors",
                 "traffic_share")

    def __init__(self, version: str, transform: Callable, *,
                 stage: Any = None, digest: Optional[str] = None,
                 cost: Optional[dict] = None,
                 warm: Optional[Callable[[], Any]] = None,
                 slo: Optional[obs_perf.SLOTracker] = None,
                 created_s: float = 0.0):
        self.version = version
        self.transform = transform
        # the underlying pipeline/stage object (serve_pipeline's fused
        # model), kept so the warm hook can reach attach_persistent_cache
        self.stage = stage
        self.digest = digest if digest is not None \
            else structural_digest(stage if stage is not None else transform)
        # cost-model snapshot at registration (predicted ms / knobs): the
        # measured-vs-predicted promotion evidence rides the journal
        self.cost = dict(cost) if cost else None
        self.state = CANDIDATE
        self.created_s = created_s
        # zero-compile promotion hook: called by the controller BEFORE the
        # swap so the candidate's executables are warm when traffic lands
        self.warm = warm
        # per-version burn-rate buckets: the canary step gates read these
        self.slo = slo
        # batches served for real, by role (live/canary routing decisions)
        self.requests: Dict[str, int] = {"live": 0, "canary": 0}
        # shadow-phase accounting: issued = duplicated batches, scored =
        # rows compared against the incumbent, divergent = rows outside
        # the per-dtype tolerance, errors = candidate transform failures
        self.shadow_issued = 0
        self.shadow_scored = 0
        self.shadow_divergent = 0
        self.shadow_errors = 0
        # current share of real traffic routed here (0.0 outside canary)
        self.traffic_share = 0.0

    def divergence_rate(self) -> float:
        return (self.shadow_divergent / self.shadow_scored
                if self.shadow_scored else 0.0)

    def max_burn(self) -> float:
        if self.slo is None:
            return 0.0
        rates = self.slo.burn_rates()
        return max(rates.values()) if rates else 0.0

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "version": self.version,
            "state": self.state,
            "digest": self.digest,
            "traffic_share": round(self.traffic_share, 4),
            "requests": dict(self.requests),
            "shadow": {"issued": self.shadow_issued,
                       "scored": self.shadow_scored,
                       "divergent": self.shadow_divergent,
                       "errors": self.shadow_errors},
            "divergence_rate": round(self.divergence_rate(), 6),
        }
        if self.cost is not None:
            out["cost"] = self.cost
        if self.slo is not None:
            out["burn"] = {str(w): r
                           for w, r in self.slo.burn_rates().items()}
        return out


class ModelRegistry:
    """Thread-safe registry of ModelVersions with journaled transitions.

    One version is ``live`` at a time; ``swap_live`` is the two-phase
    promotion primitive — chaos seam first, then the caller's ``apply``
    (the executor-guarded transform flip), then the journaled state
    transitions. A crash or an apply failure before the flip leaves the
    registry (and the serving path) exactly as it was.
    """

    def __init__(self, slo_config: Optional[obs_perf.SLOConfig] = None,
                 journal_cap: int = 256, clock=time.monotonic,
                 namespace: Optional[str] = None):
        #: journal namespace (serving/multimodel: the owning model's name,
        #: stamped as ``ns`` on every entry so N registries' journals stay
        #: attributable after aggregation); None = the single-model plane
        self.namespace = namespace
        self._slo_config = slo_config
        self._clock = clock
        self._lock = threading.Lock()
        self._versions: Dict[str, ModelVersion] = {}
        self._order: List[str] = []
        self._live: Optional[str] = None
        self._seq = 0
        #: bounded decision journal (the tuner/fleet idiom): dicts of
        #: {action, version, from, to, t, ...}
        self.journal: List[Dict[str, Any]] = []
        self._journal_cap = max(8, int(journal_cap))
        self.transitions: Dict[str, int] = {}

    # -- journal ---------------------------------------------------------
    def _log(self, action: str, **info: Any) -> None:
        entry = {"action": action, "t": round(self._clock(), 3), **info}
        if self.namespace is not None:
            entry["ns"] = self.namespace
        if len(self.journal) >= self._journal_cap:
            del self.journal[: self._journal_cap // 4]
        self.journal.append(entry)
        self.transitions[action] = self.transitions.get(action, 0) + 1

    # -- registration ----------------------------------------------------
    def _new_version(self, transform: Callable, *, version: Optional[str],
                     stage: Any, digest: Optional[str],
                     cost: Optional[dict],
                     warm: Optional[Callable]) -> ModelVersion:
        self._seq += 1
        vid = version if version is not None else f"v{self._seq}"
        if vid in self._versions:
            raise ValueError(f"version {vid!r} already registered")
        slo = obs_perf.SLOTracker(self._slo_config, clock=self._clock) \
            if self._slo_config is not None \
            else obs_perf.SLOTracker(clock=self._clock)
        ver = ModelVersion(vid, transform, stage=stage, digest=digest,
                           cost=cost, warm=warm, slo=slo,
                           created_s=self._clock())
        self._versions[vid] = ver
        self._order.append(vid)
        return ver

    def register(self, transform: Callable, *, version: Optional[str] = None,
                 stage: Any = None, digest: Optional[str] = None,
                 cost: Optional[dict] = None,
                 warm: Optional[Callable[[], Any]] = None) -> ModelVersion:
        """Register a fitted transform as a ``candidate`` version."""
        with self._lock:
            ver = self._new_version(transform, version=version, stage=stage,
                                    digest=digest, cost=cost, warm=warm)
            self._log("register", version=ver.version, digest=ver.digest)
        return ver

    def adopt_live(self, transform: Callable, *,
                   version: Optional[str] = None, stage: Any = None,
                   digest: Optional[str] = None,
                   cost: Optional[dict] = None) -> ModelVersion:
        """Register the bootstrap incumbent directly as ``live`` (the
        transform the server was constructed with)."""
        with self._lock:
            if self._live is not None:
                raise ValueError(f"live version already set: {self._live}")
            ver = self._new_version(transform, version=version, stage=stage,
                                    digest=digest, cost=cost, warm=None)
            ver.state = LIVE
            ver.traffic_share = 1.0
            self._live = ver.version
            self._log("adopt", version=ver.version, digest=ver.digest)
        return ver

    # -- lookup ----------------------------------------------------------
    def get(self, version: str) -> ModelVersion:
        with self._lock:
            return self._versions[version]

    @property
    def live(self) -> Optional[ModelVersion]:
        with self._lock:
            return self._versions.get(self._live) \
                if self._live is not None else None

    def versions(self) -> List[ModelVersion]:
        with self._lock:
            return [self._versions[v] for v in self._order]

    # -- state machine ---------------------------------------------------
    def transition(self, version: str, new_state: str, **info: Any
                   ) -> ModelVersion:
        """Move a version to ``new_state``, validating against the state
        machine; the change is journaled with the caller's context."""
        if new_state not in STATES:
            raise ValueError(f"unknown state {new_state!r}")
        with self._lock:
            ver = self._versions[version]
            if new_state not in _ALLOWED[ver.state]:
                raise ValueError(
                    f"illegal transition {ver.state} -> {new_state} "
                    f"for {version!r}")
            old = ver.state
            ver.state = new_state
            self._log("transition", version=version, **{"from": old},
                      to=new_state, **info)
        return ver

    def swap_live(self, version: str,
                  apply: Optional[Callable[[ModelVersion,
                                            Optional[ModelVersion]],
                                           None]] = None,
                  **info: Any) -> ModelVersion:
        """Atomically promote ``version`` to live.

        Two-phase: (1) fire the ``lifecycle.swap`` chaos seam — a raising
        plan simulates a crash mid-swap and must leave the incumbent
        serving; (2) run ``apply(new, old)`` OUTSIDE the registry lock (the
        caller's executor-guarded transform flip — an apply failure aborts
        with no state change); (3) flip the live pointer and journal the
        transitions. In-flight batches dispatched before (2) complete on
        the incumbent's closure — versions never mix within a batch."""
        with self._lock:
            ver = self._versions[version]
            if LIVE not in _ALLOWED[ver.state]:
                raise ValueError(
                    f"cannot promote {version!r} from state {ver.state}")
            old = self._versions.get(self._live) \
                if self._live is not None else None
        faults.fire(faults.LIFECYCLE_SWAP, version=version,
                    incumbent=old.version if old is not None else None)
        if apply is not None:
            apply(ver, old)
        with self._lock:
            prev_state = ver.state
            ver.state = LIVE
            ver.traffic_share = 1.0
            self._live = version
            if old is not None:
                old.state = RETIRED
                old.traffic_share = 0.0
            self._log("promote", version=version, **{"from": prev_state},
                      incumbent=old.version if old is not None else None,
                      **info)
        return ver

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            live = self._live
            versions = [self._versions[v].summary() for v in self._order]
            journal = list(self.journal[-16:])
            transitions = dict(self.transitions)
        return {"live": live, "versions": versions,
                "transitions": transitions, "journal": journal}
