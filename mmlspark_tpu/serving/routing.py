"""RoutingFront — the driver-side routing service for multi-worker serving.

Reference: HTTPSourceV2.scala:113-173 — the driver runs an HttpServer; every
WorkerServer POSTs its ServiceInfo{name, host, port} to register, and public
traffic is spread across registered workers. Worker loss is handled by retrying
on another worker and evicting the dead one (Spark task retry gave the
reference this for free; here it's explicit).

TPU-native deployment note: one RoutingFront per serving cluster (typically on
the coordinator host), one ServingServer per TPU host; the pipeline inside
each worker uses that host's chips. Cross-worker replies ride the internal
endpoint (server.reply_to), so a worker group that shards a batch can answer
requests that entered elsewhere.
"""

from __future__ import annotations

import itertools
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.error import HTTPError, URLError
from urllib.parse import urlsplit
from urllib.request import Request, urlopen


class RoutingFront:
    """HTTP front: register workers, round-robin public requests, evict dead.

    Endpoints:
      POST /_mmlspark/register   {"address": "http://host:port/api"} -> 200
      GET  /_mmlspark/workers    -> {"workers": [...]}
      anything else              -> forwarded to a worker (retry across
                                    workers; a worker failing ``max_failures``
                                    consecutive times is evicted)
    """

    REGISTER_PATH = "/_mmlspark/register"
    WORKERS_PATH = "/_mmlspark/workers"

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 forward_timeout_s: float = 70.0, max_failures: int = 3,
                 token: Optional[str] = None):
        self.host = host
        self.port = port
        self.forward_timeout_s = forward_timeout_s
        self.max_failures = max_failures
        self.token = token  # when set, /register requires X-MMLSpark-Token
        self._workers: List[str] = []
        self._failures: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._rr = itertools.count()
        self._httpd: Optional[ThreadingHTTPServer] = None

    # -- worker management ------------------------------------------------
    def register(self, address: str) -> None:
        with self._lock:
            if address not in self._workers:
                self._workers.append(address)
            self._failures[address] = 0

    def deregister(self, address: str) -> None:
        with self._lock:
            if address in self._workers:
                self._workers.remove(address)
            self._failures.pop(address, None)

    @property
    def workers(self) -> List[str]:
        with self._lock:
            return list(self._workers)

    def _pick_order(self) -> List[str]:
        with self._lock:
            ws = list(self._workers)
        if not ws:
            return []
        start = next(self._rr) % len(ws)
        return ws[start:] + ws[:start]

    def _note_failure(self, address: str) -> None:
        with self._lock:
            n = self._failures.get(address, 0) + 1
            self._failures[address] = n
            if n >= self.max_failures and address in self._workers:
                self._workers.remove(address)

    def _note_success(self, address: str) -> None:
        with self._lock:
            self._failures[address] = 0

    # -- HTTP ---------------------------------------------------------------
    def _make_handler(self):
        front = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _read_body(self) -> bytes:
                length = int(self.headers.get("Content-Length", 0) or 0)
                return self.rfile.read(length) if length else b""

            def _respond(self, status: int, body: bytes,
                         ctype: str = "application/json"):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _handle(self):
                incoming = urlsplit(self.path)
                path = incoming.path.rstrip("/")
                body = self._read_body()
                if path == RoutingFront.REGISTER_PATH:
                    from .server import TOKEN_HEADER
                    if front.token is not None and \
                            self.headers.get(TOKEN_HEADER) != front.token:
                        self._respond(403, b'{"error": "bad cluster token"}')
                        return
                    try:
                        front.register(json.loads(body.decode())["address"])
                        self._respond(200, b"{}")
                    except Exception as e:  # noqa: BLE001
                        self._respond(400, json.dumps(
                            {"error": str(e)}).encode())
                    return
                if path == RoutingFront.WORKERS_PATH:
                    self._respond(200, json.dumps(
                        {"workers": front.workers}).encode())
                    return
                # forward to a worker, retrying across the ring; a request is
                # only REPLAYED on another worker when the failure shows it
                # never reached the first one (connect refused/reset) or the
                # method is idempotent — a read timeout on a POST may mean the
                # worker is mid-compute, so replaying would double-process it
                order = front._pick_order()
                if not order:
                    self._respond(503, b'{"error": "no workers registered"}')
                    return
                idempotent = self.command in ("GET", "HEAD")
                for addr in order:
                    parts = urlsplit(addr)
                    # "/" routes to the worker's registered api path; any
                    # other path+query forwards verbatim (proxy semantics) so
                    # the worker's own 404 behavior is preserved
                    wpath = parts.path if path in ("", "/") else incoming.path
                    query = f"?{incoming.query}" if incoming.query else ""
                    url = f"{parts.scheme}://{parts.netloc}{wpath or '/'}{query}"
                    req = Request(url, data=body if body else None,
                                  method=self.command,
                                  headers={k: v for k, v in
                                           self.headers.items()
                                           if k.lower() not in
                                           ("host", "content-length")})
                    try:
                        with urlopen(req,
                                     timeout=front.forward_timeout_s) as resp:
                            front._note_success(addr)
                            self._respond(
                                resp.status, resp.read(),
                                resp.headers.get("Content-Type",
                                                 "application/json"))
                            return
                    except HTTPError as e:
                        # worker answered (e.g. 500 from the pipeline):
                        # authoritative, do not retry elsewhere
                        front._note_success(addr)
                        self._respond(e.code, e.read() or b"",
                                      e.headers.get("Content-Type",
                                                    "text/plain"))
                        return
                    except (URLError, OSError) as e:
                        front._note_failure(addr)
                        reason = getattr(e, "reason", e)
                        timed_out = isinstance(reason, TimeoutError) or \
                            "timed out" in str(reason).lower()
                        if timed_out and not idempotent:
                            self._respond(504, json.dumps(
                                {"error": f"worker {addr} timed out; not "
                                          f"replayed (non-idempotent)"}
                            ).encode())
                            return
                        continue
                self._respond(502, b'{"error": "all workers failed"}')

            do_POST = _handle
            do_GET = _handle

        return Handler

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "RoutingFront":
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self.port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True,
                             name="routing-front")
        t.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}/"

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def register_worker(front_address: str, worker_address: str,
                    timeout: float = 10.0, token: Optional[str] = None) -> None:
    """Worker-side registration call (ServiceInfo POST parity)."""
    from .server import _post_json

    parts = urlsplit(front_address)
    url = f"{parts.scheme}://{parts.netloc}{RoutingFront.REGISTER_PATH}"
    _post_json(url, {"address": worker_address}, timeout=timeout, token=token)
